"""Expression engine tests (reference analog: be/test/exprs/)."""

import datetime

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from starrocks_tpu import types as T
from starrocks_tpu.column import HostTable
from starrocks_tpu.exprs import (
    Case, Cast, Col, InList, Lit,
    add, and_, between, col, div, eq, eval_expr, eval_predicate, ge, gt,
    is_null, le, like, lit, lt, mul, ne, not_, or_, sub, year, month,
)
from starrocks_tpu.exprs.compile import like_to_regex
from starrocks_tpu.exprs.ir import Call, coalesce


def _chunk(**data):
    types = data.pop("__types", {})
    return HostTable.from_pydict(data, types=types).to_chunk()


def _vals(c, e, n):
    v = eval_expr(c, e)
    data = np.asarray(jnp.broadcast_to(v.data, (c.capacity,)))[:n]
    if v.valid is None:
        return list(data)
    valid = np.asarray(jnp.broadcast_to(v.valid, (c.capacity,)))[:n]
    return [d if ok else None for d, ok in zip(data, valid)]


def test_arithmetic_ints():
    c = _chunk(a=[1, 2, 3], b=[10, 20, 30])
    assert _vals(c, add(col("a"), col("b")), 3) == [11, 22, 33]
    assert _vals(c, mul(col("a"), lit(5)), 3) == [5, 10, 15]
    assert _vals(c, sub(col("b"), col("a")), 3) == [9, 18, 27]


def test_divide_null_on_zero():
    c = _chunk(a=[10, 20, 30], b=[2, 0, 5])
    out = _vals(c, div(col("a"), col("b")), 3)
    assert out[0] == 5.0 and out[1] is None and out[2] == 6.0


def test_decimal_arithmetic():
    c = _chunk(
        price=[10.00, 20.50], disc=[0.05, 0.10],
        __types={"price": T.DECIMAL(15, 2), "disc": T.DECIMAL(15, 2)},
    )
    # price * (1 - disc): classic TPC-H Q1 expression
    e = mul(col("price"), sub(lit(1), col("disc")))
    v = eval_expr(c, e)
    assert v.type.is_decimal and v.type.scale == 4
    got = np.asarray(v.data)[:2]
    assert list(got) == [95000, 184500]  # 9.5000, 18.4500 at scale 4


def test_comparisons_and_null_prop():
    c = _chunk(a=[1, None, 3], b=[1, 2, 2])
    assert _vals(c, eq(col("a"), col("b")), 3) == [True, None, False]
    assert _vals(c, gt(col("a"), lit(2)), 3) == [False, None, True]
    # WHERE semantics: NULL -> excluded
    m = eval_predicate(c, gt(col("a"), lit(0)))
    assert list(np.asarray(m)[:3]) == [True, False, True]


def test_kleene_and_or():
    c = _chunk(a=[True, True, False, None], b=[None, True, None, None])
    assert _vals(c, and_(col("a"), col("b")), 4) == [None, True, False, None]
    assert _vals(c, or_(col("a"), col("b")), 4) == [True, True, None, None]


def test_is_null_not():
    c = _chunk(a=[1, None, 3])
    assert _vals(c, is_null(col("a")), 3) == [True if v is None else False for v in [1, None, 3]]
    assert _vals(c, not_(eq(col("a"), lit(1))), 3) == [False, None, True]


def test_case_when():
    c = _chunk(x=[1, 2, 3, 4])
    e = Case(
        whens=((lt(col("x"), lit(2)), lit(10)), (lt(col("x"), lit(4)), lit(20))),
        orelse=lit(30),
    )
    assert _vals(c, e, 4) == [10, 20, 20, 30]
    e2 = Case(whens=((eq(col("x"), lit(1)), lit(1)),), orelse=None)
    assert _vals(c, e2, 3) == [1, None, None]


def test_in_list():
    c = _chunk(s=["a", "b", "c", "d"], n=[1, 2, 3, 4])
    assert _vals(c, InList(col("s"), ("b", "d")), 4) == [False, True, False, True]
    assert _vals(c, InList(col("s"), ("zz",)), 4) == [False] * 4
    assert _vals(c, InList(col("n"), (2, 4), negated=True), 4) == [True, False, True, False]


def test_string_compare_and_like():
    c = _chunk(s=["apple", "banana", "cherry"])
    assert _vals(c, eq(col("s"), lit("banana")), 3) == [False, True, False]
    assert _vals(c, ne(col("s"), lit("banana")), 3) == [True, False, True]
    assert _vals(c, ge(col("s"), lit("banana")), 3) == [False, True, True]
    assert _vals(c, lt(col("s"), lit("b")), 3) == [True, False, False]
    assert _vals(c, like(col("s"), lit("%an%")), 3) == [False, True, False]
    assert _vals(c, like(col("s"), lit("_pple")), 3) == [True, False, False]


def test_like_regex_translation():
    assert like_to_regex("a%b_c") == "^a.*b.c$"
    assert like_to_regex("100\\%") == "^100%$"


def test_dates():
    c = HostTable.from_pydict(
        {"d": [
            (datetime.date(1998, 9, 2) - datetime.date(1970, 1, 1)).days,
            (datetime.date(1995, 1, 15) - datetime.date(1970, 1, 1)).days,
        ]},
        types={"d": T.DATE},
    ).to_chunk()
    assert _vals(c, year(col("d")), 2) == [1998, 1995]
    assert _vals(c, month(col("d")), 2) == [9, 1]
    assert _vals(c, le(col("d"), lit("1998-09-02")), 2) == [True, True]
    assert _vals(c, lt(col("d"), lit("1995-01-15")), 2) == [False, False]
    assert _vals(c, between(col("d"), lit("1995-01-01"), lit("1996-01-01")), 2) == [False, True]


def test_civil_from_days_vs_numpy():
    from starrocks_tpu.exprs.compile import _civil_from_days

    days = np.arange(-3000, 40000, 370)
    y, m, d = _civil_from_days(jnp.asarray(days))
    dates = days.astype("datetime64[D]")
    ys = dates.astype("datetime64[Y]").astype(int) + 1970
    ms = dates.astype("datetime64[M]").astype(int) % 12 + 1
    np.testing.assert_array_equal(np.asarray(y), ys)
    np.testing.assert_array_equal(np.asarray(m), ms)


def test_string_map_fns():
    c = _chunk(s=["Apple", "BANANA"])
    from starrocks_tpu.exprs.ir import Call

    up = eval_expr(c, Call("upper", col("s")))
    assert list(up.dict.decode(np.asarray(up.data)[:2])) == ["APPLE", "BANANA"]
    sb = eval_expr(c, Call("substr", col("s"), lit(1), lit(3)))
    assert list(sb.dict.decode(np.asarray(sb.data)[:2])) == ["App", "BAN"]


def test_coalesce():
    c = _chunk(a=[1, None, None], b=[None, 5, None])
    assert _vals(c, Call("coalesce", col("a"), col("b"), lit(0)), 3) == [1, 5, 0]


def test_cast():
    c = _chunk(a=[1, 2])
    v = eval_expr(c, Cast(col("a"), T.DOUBLE))
    assert v.type == T.DOUBLE
    v2 = eval_expr(c, Cast(col("a"), T.DECIMAL(15, 2)))
    assert list(np.asarray(v2.data)[:2]) == [100, 200]


def test_exprs_jittable():
    c = _chunk(a=[1.0, 2.0, 3.0], b=[4.0, 5.0, 6.0])

    @jax.jit
    def run(ch):
        return eval_predicate(ch, gt(add(col("a"), col("b")), lit(6.5)))

    m = run(c)
    assert list(np.asarray(m)[:3]) == [False, True, True]
    run(c)
    assert run._cache_size() == 1


def test_datetime_end_to_end():
    import tempfile

    from starrocks_tpu.runtime.session import Session

    d = tempfile.mkdtemp()
    s = Session(data_dir=d)
    s.sql("create table ev (id int, ts datetime, v double)")
    s.sql("""insert into ev values (1, '2024-03-01 10:30:00', 1.5),
             (2, '2024-03-01 11:00:00', 2.5), (3, '2024-03-02 09:00:00', 4.0)""")
    assert s.sql("select id from ev where ts >= '2024-03-01 11:00:00' order by id").rows() == [(2,), (3,)]
    assert s.sql("select id from ev where ts < '2024-03-02' order by id").rows() == [(1,), (2,)]
    assert s.sql("select day(ts) d, sum(v) s from ev group by 1 order by 1").rows() == [(1, 4.0), (2, 4.0)]
    # real parquet persistence roundtrip (fresh session over the same dir)
    s2 = Session(data_dir=d)
    assert s2.sql("select id from ev where ts >= '2024-03-01 11:00' order by id").rows() == [(2,), (3,)]
    # string comparisons with datetime-looking literals stay string-typed
    s2.sql("create table sv (name varchar)")
    s2.sql("insert into sv values ('2024-03-01 11:00:00'), ('other')")
    assert s2.sql("select count(*) c from sv where name = '2024-03-01 11:00:00'").rows() == [(1,)]
    # garbage time values in string context stay plain strings
    assert s2.sql("select count(*) c from sv where name = '2024-03-01 99:99'").rows() == [(0,)]
    # IN-list on a datetime column
    assert s2.sql("select id from ev where ts in ('2024-03-01 10:30:00')").rows() == [(1,)]
