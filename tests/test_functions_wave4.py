"""Wave-4 builtins: strings/hashes/datetime/vector/array/json/bitmap
(reference name surface: gensrc/script/functions.py)."""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture(scope="module")
def sess():
    cat = Catalog()
    cat.register("t", HostTable.from_pydict({
        "i": [5, 255, 4096, None],
        "s": ["hello world", "a,b,c", '{"k": {"x": 1}, "arr": [1, 2]}',
              None],
        "d": ["2024-01-04", "2023-12-31", "2020-02-29", "2020-01-01"],
        "url": ["https://example.com/p?x=1&y=2", "http://h.io/", "", None],
        "ip": ["1.2.3.4", "255.255.255.255", "bad", None],
        "arr": [[1.0, 2.0, 3.0], [4.0, 5.0, 6.0], [0.0, 0.0, 1.0], None],
        "ia": [[1, 2, 3], [3, 4], [7], None],
    }, types={"d": __import__("starrocks_tpu.types",
                              fromlist=["DATE"]).DATE}))
    return Session(cat)


def q1(sess, expr, where="i = 5"):
    return sess.sql(f"select {expr} from t where {where}").rows()[0][0]


def test_string_fns(sess):
    assert q1(sess, "substring(s, 1, 5)") == "hello"
    assert q1(sess, "trim_string('  x  ')") == "x"
    assert q1(sess, "replace_old(s, 'world', 'w')") == "hello w"
    assert q1(sess, "ceiling(1.2)") == 2
    assert q1(sess, "char(72, 105)") == "Hi"
    assert q1(sess, "conv('ff', 16, 10)") == "255"
    assert q1(sess, "conv('255', 10, 16)") == "FF"
    assert q1(sess, "money_format(i)") == "5.00"
    assert q1(sess, "format_bytes(i)", "i = 4096") == "4.00 KB"
    assert q1(sess, "url_extract_host(url)") == "example.com"
    assert q1(sess, "url_extract_parameter(url, 'y')") == "2"
    assert q1(sess, "tokenize('standard', s)") == ["hello", "world"]


def test_hash_and_id_fns(sess):
    # xxh64 known vector: xxh64(b"") = 0xEF46DB3751D8E999
    got = q1(sess, "xx_hash64('')")
    assert got == 0xEF46DB3751D8E999 - (1 << 64)
    assert q1(sess, "xx_hash64(s)") == q1(sess, "xx_hash3_64(s)")
    assert q1(sess, "xx_hash32('')") == 0x51D8E999
    assert isinstance(q1(sess, "md5sum_numeric(s)"), int)
    assert q1(sess, "inet_aton(ip)") == (1 << 24) + (2 << 16) + (3 << 8) + 4
    assert q1(sess, "inet_aton(ip)", "i = 255") == (1 << 32) - 1
    assert q1(sess, "inet_aton(ip)", "i = 4096") == 0
    assert q1(sess, "crc32_hash('abc')") == q1(sess, "crc32('abc')")
    r = sess.sql("select uuid_numeric(), uuid_numeric() from t "
                 "where i is not null").rows()
    assert len({x for row in r for x in row}) > 1  # distinct streams
    assert q1(sess, "dict_encode(s)") >= 0
    assert q1(sess, "current_timezone()") == "UTC"
    assert q1(sess, "materialize(i)") == 5


def test_datetime_fns(sess):
    assert q1(sess, "week_iso(d)") == 1          # 2024-01-04 -> ISO week 1
    assert q1(sess, "week_iso(d)", "i = 255") == 52   # 2023-12-31
    assert q1(sess, "to_iso8601(d)") == "2024-01-04"
    assert q1(sess, "jodatime_format(d, 'yyyy/MM/dd')") == "2024/01/04"
    assert q1(sess, "hour_from_unixtime(7200)") == 2
    assert str(q1(sess, "from_unixtime_ms(86400000)")).startswith(
        "1970-01-02")
    assert len(q1(sess, "curtime()")) == 8


def test_vector_fns(sess):
    assert q1(sess, "cosine_similarity(arr, arr)") == pytest.approx(1.0)
    assert q1(sess, "l2_distance(arr, arr)") == pytest.approx(0.0)
    r = sess.sql("select cosine_similarity(a.arr, b.arr) from t a, t b "
                 "where a.i = 5 and b.i = 4096").rows()[0][0]
    expect = (np.dot([1, 2, 3], [0, 0, 1])
              / (np.linalg.norm([1, 2, 3]) * 1.0))
    assert r == pytest.approx(expect)


def test_array_fns(sess):
    assert q1(sess, "array_append(ia, 9)") == [1, 2, 3, 9]
    assert q1(sess, "array_concat(ia, ia)") == [1, 2, 3, 1, 2, 3]
    assert q1(sess, "array_remove(ia, 2)") == [1, 3]
    assert q1(sess, "array_slice(ia, 2, 2)") == [2, 3]
    assert q1(sess, "array_slice(ia, -2)") == [2, 3]
    assert q1(sess, "array_repeat(7, 3)") == [7, 7, 7]
    assert q1(sess, "array_generate(3)") == [1, 2, 3]
    assert q1(sess, "array_generate(2, 6, 2)") == [2, 4, 6]
    assert q1(sess, "array_difference(ia)") == [0, 1, 1]
    assert q1(sess, "array_cum_sum(ia)") == [1, 3, 6]
    assert q1(sess, "array_contains_all(ia, array(1, 3))") is True
    assert q1(sess, "array_contains_all(ia, array(1, 9))") is False
    assert q1(sess, "arrays_overlap(ia, array(9, 3))") is True
    assert q1(sess, "arrays_overlap(ia, array(9))") is False
    assert q1(sess, "array_intersect(ia, array(3, 1, 8))") == [1, 3]


def test_json_fns(sess):
    where = "i = 4096"
    assert q1(sess, "get_json_object(s, '$.k.x')", where) == "1"
    assert q1(sess, "json_length(s)", where) == 2
    assert q1(sess, "json_keys(s)", where) == '["arr","k"]'
    assert q1(sess, "json_exists(s, '$.k')", where) is True
    assert q1(sess, "json_exists(s, '$.nope')", where) is False
    assert q1(sess, "is_json_scalar(s)", where) is False
    assert q1(sess, "is_json_scalar('3')") is True
    assert q1(sess, "get_json_bool(s, '$.k.x')", where) is True
    assert q1(sess, "json_contains(s, '{\"arr\": [1, 2]}')", where) is True
    assert q1(sess, "parse_json(s)", where) == \
        '{"k": {"x": 1}, "arr": [1, 2]}'


def test_bitmap_fns(sess):
    assert q1(sess, "bitmap_count(bitmap_empty())") == 0
    assert q1(sess, "bitmap_count(bitmap_from_string('1,5,9'))") == 3
    assert q1(sess, "bitmap_min(bitmap_from_string('4,2,9'))") == 2
    assert q1(sess, "bitmap_max(bitmap_from_string('4,2,9'))") == 9
    assert q1(sess,
              "bitmap_count(bitmap_remove(bitmap_from_string('1,2'), 2))") \
        == 1
    assert q1(sess, "bitmap_has_any(bitmap_from_string('1,2'), "
                    "bitmap_from_string('2,3'))") is True
    assert q1(sess, "bitmap_has_any(bitmap_from_string('1'), "
                    "bitmap_from_string('2'))") is False
    assert q1(sess, "bitmap_count(sub_bitmap(bitmap_from_string("
                    "'10,20,30,40'), 1, 2))") == 2
    assert q1(sess, "bitmap_count(bitmap_subset_in_range("
                    "bitmap_from_string('10,20,30'), 15, 35))") == 2
    assert q1(sess, "bitmap_count(bitmap_subset_limit("
                    "bitmap_from_string('10,20,30'), 15, 1))") == 1
    assert q1(sess, "bitmap_count(bitmap_hash(s))") == 1
    assert q1(sess, "bitmap_count(array_to_bitmap(ia))") == 3
    assert q1(sess, "hll_cardinality(hll_serialize(hll_hash(s)))") == 1


def test_bitmap_to_array_gated_domain():
    from starrocks_tpu.runtime.config import config

    cat = Catalog()
    cat.register("b", HostTable.from_pydict({"v": [1, 5, 9]}))
    s = Session(cat)
    config.set("bitmap_default_domain", 1024)
    try:
        r = s.sql("select bitmap_to_array(bitmap_from_string('1,5,9')) "
                  "from b where v = 1").rows()[0][0]
        assert r == [1, 5, 9]
    finally:
        config.set("bitmap_default_domain", 65536)


def test_string_array_dict_alignment():
    """Code-space bug regression: ops combining string arrays from
    DIFFERENT dictionaries must compare/concat by VALUE, not raw code."""
    cat = Catalog()
    cat.register("x", HostTable.from_pydict({
        "s1": ["red blue", "green"], "s2": ["blue", "yellow red"]}))
    s = Session(cat)
    q = ("select array_concat(tokenize('standard', s1), "
         "tokenize('standard', s2)) from x order by s1")
    rows = s.sql(q).rows()
    assert rows[0][0] == ["green", "yellow", "red"]
    assert rows[1][0] == ["red", "blue", "blue"]
    q2 = ("select arrays_overlap(tokenize('standard', s1), "
          "tokenize('standard', s2)) from x order by s1")
    assert [r[0] for r in s.sql(q2).rows()] == [False, True]
    q3 = ("select array_remove(tokenize('standard', s1), 'red') from x "
          "order by s1")
    assert [r[0] for r in s.sql(q3).rows()] == [["green"], ["blue"]]


def test_hll_hash_non_ascii():
    cat = Catalog()
    cat.register("u", HostTable.from_pydict({"s": ["café", "café", "naïve"]}))
    s = Session(cat)
    assert s.sql("select approx_count_distinct(s) from u").rows() == [(2,)]


def test_xxh64_long_input_vector():
    # spec vector: xxh64 of 32+ bytes exercises the mergeRound path
    from starrocks_tpu.exprs.functions_wave4 import _xxh64_py

    assert _xxh64_py(b"") == 0xEF46DB3751D8E999
    assert _xxh64_py(b"a" * 32) != _xxh64_py(b"a" * 31)
    # cross-checked reference value for b'x'*32
    assert _xxh64_py(b"x" * 32) == 0xE2DF261FC2EC30EB


def test_regexp_and_utility_longtail(sess):
    assert q1(sess, "regexp_count(s, 'l')") == 3  # 'hello world'
    assert q1(sess, "regexp_position(s, 'wor')") == 7
    assert q1(sess, "regexp_split('a1b22c', '[0-9]+')") == ["a", "b", "c"]
    assert q1(sess, "regexp_extract_all('a1b22', '([0-9]+)')") == ["1", "22"]
    assert q1(sess, "equiwidth_bucket(i, 0, 100, 10)") == 1
    assert q1(sess, "equiwidth_bucket(i, 0, 100, 10)", "i = 255") == 11
    assert q1(sess, "bit_shift_right_logical(-1, 63)") == 1
    assert q1(sess, "sec_to_time(i)", "i = 4096") == "01:08:16"
    assert q1(sess, "query_id()") == ""
