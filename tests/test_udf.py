"""Python scalar UDFs: CREATE FUNCTION -> host callback inside compiled
plans (VERDICT r4 item 7; reference: be/src/exprs/udf/python/ +
fe sql/ast/CreateFunctionStmt.java)."""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture()
def sess():
    cat = Catalog()
    cat.register("t", HostTable.from_pydict({
        "a": [1, 2, None, 4],
        "b": [10.0, 2.5, 3.0, None],
        "s": ["x", "yy", "zzz", None],
    }))
    s = Session(cat)
    yield s
    from starrocks_tpu.runtime.udf import _REGISTRY

    _REGISTRY.clear()


def test_udf_in_select_and_where(sess):
    sess.sql("""create function my_mix(a bigint, b double) returns double as '
def my_mix(a, b):
    return a * b + 0.5
'""")
    rows = sess.sql("select a, my_mix(a, b) from t order by a").rows()
    # strict NULLs: any NULL argument -> NULL result
    assert rows == [(1, 10.5), (2, 5.5), (4, None), (None, None)]
    rows = sess.sql("select a from t where my_mix(a, b) > 6 order by a").rows()
    assert rows == [(1,)]


def test_udf_string_args_and_none_result(sess):
    sess.sql("""create function odd_len(s varchar) returns boolean as '
def odd_len(s):
    if s == "zzz":
        return None
    return len(s) % 2 == 1
'""")
    rows = sess.sql("select s, odd_len(s) from t order by a").rows()
    # row order follows a = 1, 2, 4, NULL
    assert rows == [("x", True), ("yy", False), (None, None), ("zzz", None)]


def test_udf_composes_with_aggregates(sess):
    sess.sql("""create function twice(a bigint) returns bigint as '
def twice(a):
    return 2 * a
'""")
    r = sess.sql("select sum(twice(a)) from t").rows()
    assert r == [(14,)]


def test_udf_replace_and_drop(sess):
    sess.sql("create function f1(a bigint) returns bigint as '\ndef f1(a):\n    return a + 1\n'")
    assert sess.sql("select f1(1) from t limit 1").rows() == [(2,)]
    with pytest.raises(ValueError, match="already exists"):
        sess.sql("create function f1(a bigint) returns bigint as '\ndef f1(a):\n    return a\n'")
    sess.sql("create or replace function f1(a bigint) returns bigint as '\ndef f1(a):\n    return a + 10\n'")
    assert sess.sql("select f1(1) from t limit 1").rows() == [(11,)]
    sess.sql("drop function f1")
    with pytest.raises(Exception, match="unknown function"):
        sess.sql("select f1(1) from t")


def test_udf_distributed_matches_single_chip(sess, eight_devices):
    sess.sql("""create function rank_bucket(a bigint) returns bigint as '
def rank_bucket(a):
    return a % 3
'""")
    rng = np.random.default_rng(2)
    big = Catalog()
    big.register("u", HostTable.from_pydict(
        {"v": rng.integers(0, 1000, 20_000)}))
    from starrocks_tpu.runtime.udf import get_udf

    assert get_udf("rank_bucket") is not None
    q = ("select rank_bucket(v) as g, count(*) from u group by g "
         "order by g")
    single = Session(big).sql(q).rows()
    dist = Session(big, dist_shards=8).sql(q).rows()
    assert dist == single
