"""Differential tests: scatter-free segment reductions vs jax.ops.segment_*.

The toolkit (ops/segment.py) must match the scatter formulation bit-exactly
for integers (mod-2^64 contract) and to float tolerance for doubles, across
all strategy branches: one-hot limb matmul, broadcast-reduce, sorted prefix
tricks, and the scatter fallback itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from starrocks_tpu.ops.segment import (
    seg_count, seg_first_index, seg_max, seg_min, seg_sum,
)
from starrocks_tpu.runtime.config import config


@pytest.fixture(autouse=True)
def _force_mxu_strategies():
    """On CPU `auto` routes everything to plain scatters; pin the MXU
    strategies so the differential tests keep covering those branches."""
    config.set("segment_strategy", "mxu")
    try:
        yield
    finally:
        config.set("segment_strategy", "auto")


def _rand_case(n, g, rng, big=False):
    gid = rng.integers(0, g + 1, size=n)  # g == dead marker
    if big:
        vals = rng.integers(-(2**62), 2**62, size=n, dtype=np.int64)
    else:
        vals = rng.integers(-1000, 1000, size=n, dtype=np.int64)
    return jnp.asarray(vals), jnp.asarray(gid, jnp.int32)


@pytest.mark.parametrize("n,g,big", [
    (4096, 8, False),      # matmul path, small G
    (4096, 8, True),       # matmul path, full-range int64 (wrap contract)
    (8192, 600, False),    # matmul path, medium G
    (1024 * 3, 7, False),  # non-power-of-two rows (block = 1024)
    (256, 5, False),       # tiny rows -> fallback
])
def test_seg_sum_int_matches_scatter(n, g, big):
    rng = np.random.default_rng(42 + n + g)
    vals, gid = _rand_case(n, g, rng, big)
    want = jax.ops.segment_sum(vals, gid, num_segments=g)
    got = jax.jit(lambda v, i: seg_sum(v, i, g))(vals, gid)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_seg_sum_sorted_int():
    rng = np.random.default_rng(7)
    n, g = 8192, 3000  # too many groups for matmul -> sorted cumsum path
    gid = np.sort(rng.integers(0, g, size=n)).astype(np.int32)
    vals = rng.integers(-(2**40), 2**40, size=n, dtype=np.int64)
    want = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(gid), num_segments=g)
    got = jax.jit(lambda v, i: seg_sum(v, i, g, sorted_gid=True))(
        jnp.asarray(vals), jnp.asarray(gid))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_seg_sum_float_paths():
    rng = np.random.default_rng(3)
    n = 4096
    vals = jnp.asarray(rng.normal(size=n) * 1e3)
    # broadcast path (g <= 64)
    gid = jnp.asarray(rng.integers(0, 9, size=n), jnp.int32)
    want = jax.ops.segment_sum(vals, gid, num_segments=8)  # gid==8 dead
    got = seg_sum(vals, gid, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-12)
    # sorted path
    g2 = 500
    gid2 = jnp.asarray(np.sort(rng.integers(0, g2, size=n)), jnp.int32)
    want2 = jax.ops.segment_sum(vals, gid2, num_segments=g2)
    got2 = seg_sum(vals, gid2, g2, sorted_gid=True)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), rtol=1e-9)


def test_seg_count_single_limb():
    rng = np.random.default_rng(11)
    n, g = 65536, 40
    gid = jnp.asarray(rng.integers(0, g + 1, size=n), jnp.int32)
    live = jnp.asarray(rng.integers(0, 2, size=n), jnp.bool_)
    masked_gid = jnp.where(live, gid, g)
    want = jax.ops.segment_sum(jnp.asarray(live, jnp.int64), masked_gid,
                               num_segments=g)
    got = seg_count(live, masked_gid, g)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("sorted_gid", [False, True])
@pytest.mark.parametrize("is_min", [False, True])
def test_seg_minmax(sorted_gid, is_min):
    rng = np.random.default_rng(5)
    n, g = 4096, 20 if not sorted_gid else 300
    raw = rng.integers(0, g, size=n)
    gid = np.sort(raw) if sorted_gid else raw
    gid = jnp.asarray(gid, jnp.int32)
    ident = np.int64(2**62) if is_min else np.int64(-(2**62))
    vals = jnp.asarray(rng.integers(-10000, 10000, size=n, dtype=np.int64))
    ref = (jax.ops.segment_min if is_min else jax.ops.segment_max)(
        vals, gid, num_segments=g)
    fn = seg_min if is_min else seg_max
    got = fn(vals, gid, g, identity=ident, sorted_gid=sorted_gid)
    # empty groups: toolkit yields identity, scatter yields +/-inf-equivalent
    # extremes; compare only non-empty groups
    counts = np.asarray(jax.ops.segment_sum(jnp.ones(n, jnp.int32), gid,
                                            num_segments=g))
    mask = counts > 0
    np.testing.assert_array_equal(np.asarray(got)[mask], np.asarray(ref)[mask])


def test_seg_first_index():
    gid = jnp.asarray(np.array([0, 0, 2, 2, 2, 5], np.int32))
    got = np.asarray(seg_first_index(gid, 6, 6))
    np.testing.assert_array_equal(got, [0, 6, 2, 6, 6, 5])


def test_disabled_falls_back():
    config.set("enable_scatter_free_segments", False)
    try:
        rng = np.random.default_rng(1)
        vals, gid = _rand_case(2048, 8, rng)
        want = jax.ops.segment_sum(vals, gid, num_segments=8)
        got = seg_sum(vals, gid, 8)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    finally:
        config.set("enable_scatter_free_segments", True)


def test_seg_sum_float_sorted_no_cancellation():
    """A small group after a huge-magnitude group must not lose precision
    to a global prefix sum (regression: cumsum-diff cancellation)."""
    n, g = 2048, 300  # > bcast max -> sorted float path
    gid = np.sort(np.concatenate([
        np.zeros(20, np.int32), np.ones(20, np.int32),
        np.random.default_rng(0).integers(2, g, size=n - 40).astype(np.int32)]))
    vals = np.where(gid == 0, 1e16, 1.0)
    got = seg_sum(jnp.asarray(vals), jnp.asarray(gid), g, sorted_gid=True)
    assert float(got[1]) == 20.0
