"""Distributed SQL execution vs single-node results on the 8-device mesh
(the PseudoCluster-style multi-node equivalence tier)."""

import numpy as np
import pytest

import starrocks_tpu.sql.distributed as D
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import tpch_catalog
from starrocks_tpu.storage.datagen.ssb import ssb_catalog

from tpch_queries import QUERIES
from ssb_queries import FLAT_QUERIES


@pytest.fixture(scope="module")
def sessions(eight_devices):
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000  # SF0.01: lineitem+orders(≥15k) shard
    cat = tpch_catalog(sf=0.01)
    yield Session(cat), Session(cat, dist_shards=8)
    D.SHARD_THRESHOLD_ROWS = old


def _same(r1, r8, qid):
    assert len(r1) == len(r8), f"{qid}: {len(r1)} vs {len(r8)} rows"
    a = sorted(r1, key=str)
    b = sorted(r8, key=str)
    for i, (x, y) in enumerate(zip(a, b)):
        for xv, yv in zip(x, y):
            if isinstance(xv, float) and isinstance(yv, float):
                assert abs(xv - yv) <= max(abs(xv), 1) * 1e-9, f"{qid} row {i}"
            else:
                assert xv == yv, f"{qid} row {i}: {xv!r} vs {yv!r}"


# Q1 scan-agg; Q3/Q5/Q10 sharded lineitem x sharded orders (shuffle join) +
# replicated dims; Q6 filter-agg; Q12 shuffle join + conditional agg;
# Q14/Q19 part joins; Q18 IN-subquery semi join over sharded tables
DIST_TPCH = [1, 3, 5, 6, 10, 12, 14, 19, 18]


@pytest.mark.parametrize("qid", DIST_TPCH)
def test_tpch_distributed_matches_single(sessions, qid):
    s1, s8 = sessions
    r1 = s1.sql(QUERIES[qid]).rows()
    r8 = s8.sql(QUERIES[qid]).rows()
    _same(r1, r8, f"Q{qid}")


def test_ssb_distributed(eight_devices):
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = ssb_catalog(sf=0.005)
        s1, s8 = Session(cat), Session(cat, dist_shards=8)
        for qid in ["q1.1", "q2.1", "q3.1", "q4.1"]:
            _same(s1.sql(FLAT_QUERIES[qid]).rows(),
                  s8.sql(FLAT_QUERIES[qid]).rows(), qid)
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_distributed_adaptive_recompile(sessions):
    s1, s8 = sessions
    # high-cardinality group-by forces group-capacity overflow + recompile
    q = """select l_orderkey, sum(l_quantity) q from lineitem
           group by l_orderkey order by q desc limit 5"""
    r1, r8 = s1.sql(q).rows(), s8.sql(q).rows()
    assert [r[1] for r in r1] == [r[1] for r in r8]
    prof = s8.last_profile
    assert prof.find("attempt_1") is not None  # at least one recompile happened


def test_colocate_join_no_shuffle(eight_devices):
    """lineitem/orders share hash distribution on orderkey -> the join
    compiles with ZERO all-to-all collectives (colocate join)."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from starrocks_tpu.sql.analyzer import Analyzer
    from starrocks_tpu.sql.distributed import compile_distributed
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse
    from starrocks_tpu.sql.physical import Caps

    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = tpch_catalog(sf=0.01)
        s1, s8 = Session(cat), Session(cat, dist_shards=8)
        q = """select o_orderpriority, count(*) c, sum(l_quantity) q
               from orders, lineitem where o_orderkey = l_orderkey
               group by o_orderpriority order by 1"""
        assert s1.sql(q).rows() == s8.sql(q).rows()

        plan = optimize(Analyzer(cat).analyze(parse(q)), cat)
        ex = s8._dist_executor
        comp = compile_distributed(plan, cat, Caps({}), 8)
        meta = tuple(zip(comp.scans, comp.scan_modes))
        inputs = ex._place(meta)
        in_specs = tuple(
            jax.tree_util.tree_map(
                lambda _, mm=m: P() if mm == "replicated" else P("d"), c
            )
            for c, (_, m) in zip(inputs, meta)
        )
        low = jax.jit(shard_map(
            comp.fn, mesh=ex.mesh, in_specs=(in_specs,),
            out_specs=(P(), P("d")), check_vma=False,
        )).lower(inputs)
        assert low.as_text().count("all-to-all") == 0
        # at least one scan went through hash placement
        assert any(isinstance(m, tuple) and m[0] == "hash"
                   for m in comp.scan_modes)
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_distributed_fuzz(eight_devices):
    """Random query specs agree between single-chip and the 8-shard mesh."""
    import numpy as np

    from test_fuzz_sql import _norm, gen_spec, load_session, make_tables, spec_to_sql

    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 300
    try:
        rng = np.random.default_rng(777)
        t1, t2 = make_tables(rng)
        s1 = load_session(t1, t2)
        s8 = Session(s1.catalog, dist_shards=8)
        for _ in range(10):
            sql = spec_to_sql(gen_spec(rng))
            assert _norm(s1.sql(sql).rows()) == _norm(s8.sql(sql).rows()), sql
    finally:
        D.SHARD_THRESHOLD_ROWS = old
