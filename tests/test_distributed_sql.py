"""Distributed SQL execution vs single-node results on the 8-device mesh
(the PseudoCluster-style multi-node equivalence tier)."""

import numpy as np
import pytest

import starrocks_tpu.sql.distributed as D
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import tpch_catalog
from starrocks_tpu.storage.datagen.ssb import ssb_catalog

from tpch_queries import QUERIES
from ssb_queries import FLAT_QUERIES


@pytest.fixture(scope="module")
def sessions(eight_devices):
    old = D.SHARD_THRESHOLD_ROWS
    old_sh = D.SHUFFLE_AGG_MIN_GROUPS
    D.SHARD_THRESHOLD_ROWS = 10_000  # SF0.01: lineitem+orders(≥15k) shard
    D.SHUFFLE_AGG_MIN_GROUPS = 4_000  # SF0.01 orderkeys (15k) take SHUFFLE
    cat = tpch_catalog(sf=0.01)
    yield Session(cat), Session(cat, dist_shards=8)
    D.SHARD_THRESHOLD_ROWS = old
    D.SHUFFLE_AGG_MIN_GROUPS = old_sh


def _same(r1, r8, qid):
    assert len(r1) == len(r8), f"{qid}: {len(r1)} vs {len(r8)} rows"
    a = sorted(r1, key=str)
    b = sorted(r8, key=str)
    for i, (x, y) in enumerate(zip(a, b)):
        for xv, yv in zip(x, y):
            if isinstance(xv, float) and isinstance(yv, float):
                assert abs(xv - yv) <= max(abs(xv), 1) * 1e-9, f"{qid} row {i}"
            else:
                assert xv == yv, f"{qid} row {i}: {xv!r} vs {yv!r}"


# Q1 scan-agg; Q3/Q5/Q10 sharded lineitem x sharded orders (shuffle join) +
# replicated dims; Q6 filter-agg; Q12 shuffle join + conditional agg;
# Q14/Q19 part joins; Q18 IN-subquery semi join over sharded tables
DIST_TPCH = [1, 3, 5, 6, 10, 12, 14, 19, 18]


@pytest.mark.parametrize("qid", DIST_TPCH)
def test_tpch_distributed_matches_single(sessions, qid):
    s1, s8 = sessions
    r1 = s1.sql(QUERIES[qid]).rows()
    r8 = s8.sql(QUERIES[qid]).rows()
    _same(r1, r8, f"Q{qid}")


def test_ssb_distributed(eight_devices):
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = ssb_catalog(sf=0.005)
        s1, s8 = Session(cat), Session(cat, dist_shards=8)
        for qid in ["q1.1", "q2.1", "q3.1", "q4.1"]:
            _same(s1.sql(FLAT_QUERIES[qid]).rows(),
                  s8.sql(FLAT_QUERIES[qid]).rows(), qid)
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_distributed_topn_counter_sums_shards(sessions):
    s1, s8 = sessions
    q = "select l_orderkey from lineitem order by l_orderkey limit 7"
    assert s1.sql(q).rows() == s8.sql(q).rows()
    c1 = s1.last_profile.counters.get("topn_rows_pruned", (0,))[0]
    c8 = s8.last_profile.counters.get("topn_rows_pruned", (0,))[0]
    assert c1 > 0
    # per-shard pruned counts are psum'd in the traced program so the
    # host's max-merge reports the cross-shard SUM; a plain max would
    # report a single shard's count (~1/8 of the single-node total)
    assert c8 > 0.55 * c1


def test_distributed_adaptive_recompile(sessions):
    s1, s8 = sessions
    # high-cardinality group-by on an EXPRESSION (no NDV stats -> the planner
    # can't seed capacity) forces group-capacity overflow + recompile
    q = """select l_orderkey % 3000 k, sum(l_quantity) q from lineitem
           group by l_orderkey % 3000 order by q desc, k limit 5"""
    r1, r8 = s1.sql(q).rows(), s8.sql(q).rows()
    assert [r[1] for r in r1] == [r[1] for r in r8]
    prof = s8.last_profile
    assert prof.find("attempt_1") is not None  # at least one recompile happened


def _lowered_hlo(s8, cat, q, return_modes=False):
    """Compile a query through the distributed planner and return HLO text."""
    import jax

    from starrocks_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    from starrocks_tpu.sql.analyzer import Analyzer
    from starrocks_tpu.sql.distributed import compile_distributed
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse
    from starrocks_tpu.sql.physical import Caps

    plan = optimize(Analyzer(cat).analyze(parse(q)), cat)
    ex = s8._dist_executor
    comp = compile_distributed(plan, cat, Caps({}), 8)
    meta = tuple(zip(comp.scans, comp.scan_modes))
    inputs = ex._place(meta)
    in_specs = tuple(
        jax.tree_util.tree_map(
            lambda _, mm=m: P() if mm == "replicated" else P("d"), c
        )
        for c, (_, m) in zip(inputs, meta)
    )
    low = jax.jit(shard_map(
        comp.fn, mesh=ex.mesh, in_specs=(in_specs,),
        out_specs=(P(), P("d")), check_vma=False,
    )).lower(inputs)
    txt = low.as_text()
    return (txt, comp.scan_modes) if return_modes else txt


def test_colocate_join_no_shuffle(eight_devices):
    """lineitem/orders share hash distribution on orderkey -> the join
    compiles with ZERO all-to-all collectives (colocate join)."""
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = tpch_catalog(sf=0.01)
        s1, s8 = Session(cat), Session(cat, dist_shards=8)
        q = """select o_orderpriority, count(*) c, sum(l_quantity) q
               from orders, lineitem where o_orderkey = l_orderkey
               group by o_orderpriority order by 1"""
        assert s1.sql(q).rows() == s8.sql(q).rows()

        txt, scan_modes = _lowered_hlo(s8, cat, q, return_modes=True)
        assert txt.count("all_to_all") + txt.count("all-to-all") == 0
        # at least one scan went through hash placement
        assert any(isinstance(m, tuple) and m[0] == "hash" for m in scan_modes)
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_shuffle_final_agg(sessions):
    """High-cardinality GROUP BY on an UNALIGNED key routes partial states
    through the HASH_PARTITIONED exchange (all_to_all in the HLO) instead of
    all_gathering them, and still matches single-chip results."""
    s1, s8 = sessions
    old = D.SHUFFLE_AGG_MIN_GROUPS
    D.SHUFFLE_AGG_MIN_GROUPS = 1_000  # SF0.01 partkeys (2000) take SHUFFLE
    try:
        q = ("select l_partkey, sum(l_quantity) q, count(*) c "
             "from lineitem group by l_partkey")
        _same(s1.sql(q).rows(), s8.sql(q).rows(), "shuffle-agg")
        hlo = _lowered_hlo(s8, s1.catalog, q)
        assert hlo.count("all_to_all") + hlo.count("all-to-all") >= 1
    finally:
        D.SHUFFLE_AGG_MIN_GROUPS = old


def test_colocate_aggregation_no_exchange(sessions):
    """GROUP BY on the table's hash-distribution key aggregates fully
    shard-local: zero all_to_all AND zero partial/final split needed."""
    s1, s8 = sessions
    q = "select l_orderkey, sum(l_quantity) q, count(*) c from lineitem group by l_orderkey"
    _same(s1.sql(q).rows(), s8.sql(q).rows(), "colocate-agg")
    hlo = _lowered_hlo(s8, s1.catalog, q)
    assert hlo.count("all_to_all") + hlo.count("all-to-all") == 0


def test_distributed_full_sort_global_order(sessions):
    """Full ORDER BY over a sharded table: range exchange + local sort must
    produce EXACT global order (not just the right multiset)."""
    s1, s8 = sessions
    q = "select l_extendedprice from lineitem order by l_extendedprice desc"
    r1, r8 = s1.sql(q).rows(), s8.sql(q).rows()
    assert [r[0] for r in r1] == [r[0] for r in r8]
    hlo = _lowered_hlo(s8, s1.catalog, q)
    assert hlo.count("all_to_all") + hlo.count("all-to-all") >= 1  # the range exchange

    # asc path over a date key, exact order again
    q2 = "select l_shipdate from lineitem order by l_shipdate"
    assert s1.sql(q2).rows() == s8.sql(q2).rows()


def test_distributed_sort_nulls_and_dict_keys(eight_devices):
    """Exact global order through the range exchange for the NULL-sentinel
    branch (nullable int key, NULLS FIRST/LAST) and dict-encoded varchar
    keys — the branches of _single_sort_rank the TPC-H columns never hit."""
    import numpy as np

    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 300
    try:
        rng = np.random.default_rng(42)
        n = 4000
        s = Session()
        s.sql("create table tnull (v int, g varchar)")
        words = ["amber", "brick", "coral", "dune", "ember", "frost"]
        rows = []
        for i in range(n):
            v = "null" if rng.random() < 0.1 else str(int(rng.integers(-500, 500)))
            g = f"'{words[int(rng.integers(0, len(words)))]}'"
            rows.append(f"({v}, {g})")
        s.sql("insert into tnull values " + ", ".join(rows))
        s8 = Session(s.catalog, dist_shards=8)
        for q in [
            "select v from tnull order by v",                    # nulls last (asc default)
            "select v from tnull order by v desc",               # nulls first
            "select v from tnull order by v asc nulls first",
            "select v from tnull order by v desc nulls last",
            "select g from tnull order by g",                    # dict codes
            "select g from tnull order by g desc",
        ]:
            assert s.sql(q).rows() == s8.sql(q).rows(), q
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_distributed_topn_gathers_topk_only(sessions):
    """ORDER BY..LIMIT: per-shard TopN + compact means the gather moves only
    ~limit rows per shard, and the exact rows match single-chip."""
    import re

    s1, s8 = sessions
    q = """select l_orderkey, l_linenumber, l_extendedprice from lineitem
           order by l_extendedprice desc, l_orderkey, l_linenumber limit 37"""
    assert s1.sql(q).rows() == s8.sql(q).rows()
    # pin the optimization, not just the result: every all_gather operand
    # must be the compacted pad_capacity(37)=1024 buffer, never the full
    # per-shard scan capacity
    hlo = _lowered_hlo(s8, s1.catalog, q)
    dims = [int(m) for m in re.findall(r"all_gather\"?[^\n]*?tensor<(\d+)x", hlo)]
    assert dims, "expected all_gather ops in the TopN plan"
    assert max(dims) <= 1024, f"TopN gather moved full buffers: {dims}"


def test_distributed_window_partition_shuffle(sessions):
    """PARTITION BY windows run shard-local after a partition-key shuffle —
    results must match the single-chip gather-everything plan."""
    s1, s8 = sessions
    q = """select l_orderkey, l_linenumber,
                  sum(l_quantity) over (partition by l_orderkey
                                        order by l_linenumber) rq,
                  row_number() over (partition by l_orderkey
                                     order by l_extendedprice desc) rn
           from lineitem where l_orderkey < 1000"""
    _same(s1.sql(q).rows(), s8.sql(q).rows(), "window-shuffle")


def test_distributed_fuzz(eight_devices):
    """Random query specs agree between single-chip and the 8-shard mesh."""
    import numpy as np

    from test_fuzz_sql import _norm, gen_spec, load_session, make_tables, spec_to_sql

    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 300
    try:
        rng = np.random.default_rng(777)
        t1, t2 = make_tables(rng)
        s1 = load_session(t1, t2)
        s8 = Session(s1.catalog, dist_shards=8)
        for _ in range(10):
            sql = spec_to_sql(gen_spec(rng))
            assert _norm(s1.sql(sql).rows()) == _norm(s8.sql(sql).rows()), sql
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_string_key_join_dict_alignment(eight_devices):
    """Join keys that are dict-encoded strings from DIFFERENT tables must
    compare by VALUE, not by per-column code (regression: raw-code equality
    silently matched t1.'a' with t2.'b'). Distributed shuffles must route
    both sides' equal strings to the same shard."""
    import numpy as np

    from starrocks_tpu.column import HostTable
    from starrocks_tpu.storage.catalog import Catalog

    rng = np.random.default_rng(7)
    words1 = [f"w{i:03d}" for i in range(40)]
    words2 = [f"w{i:03d}" for i in range(20, 60)]  # overlapping, shifted codes
    n = 30_000  # above the lowered shard threshold so both sides shard
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = Catalog()
        cat.register("s1", HostTable.from_pydict({
            "k": [words1[i] for i in rng.integers(0, 40, n)],
            "x": list(range(n)),
        }))
        cat.register("s2", HostTable.from_pydict({
            "k": [words2[i] for i in rng.integers(0, 40, n)],
            "y": list(rng.integers(0, 1000, n)),
        }))
        q = ("SELECT s1.k AS k, count(*) AS c, sum(y) AS sy FROM s1 "
             "JOIN s2 ON s1.k = s2.k GROUP BY s1.k ORDER BY k")
        single = Session(cat).sql(q).rows()
        dist = Session(cat, dist_shards=8).sql(q).rows()
        # pandas oracle
        import pandas as pd

        d1 = cat.get_table("s1").table.to_pandas()
        d2 = cat.get_table("s2").table.to_pandas()
        m = d1.merge(d2, on="k")
        exp = (m.groupby("k").agg(c=("y", "size"), sy=("y", "sum"))
               .reset_index().sort_values("k"))
        expected = [(r.k, int(r.c), int(r.sy)) for r in exp.itertuples()]
        assert [(k, int(c), int(sy)) for k, c, sy in single] == expected
        _same(single, dist, "string_join")
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_unpackable_multikey_join_hash_fallback(eight_devices):
    """Key tuples that exceed 63 packed bits (floats/strings/no stats) join
    via a splitmix64 fingerprint + equality residuals — single-chip and
    mesh agree and match a pandas oracle."""
    import numpy as np
    import pandas as pd

    from starrocks_tpu.column import HostTable
    from starrocks_tpu.storage.catalog import Catalog

    rng = np.random.default_rng(11)
    n = 30_000
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = Catalog()
        a = rng.integers(0, 500, n)
        b = rng.choice([0.5, 1.5, 2.5, -3.0, 1e12], n)
        cat.register("f1", HostTable.from_pydict(
            {"a": list(a), "b": list(b), "x": list(range(n))}))
        a2 = rng.integers(0, 500, n)
        b2 = rng.choice([0.5, 1.5, 2.5, -3.0, 7.0], n)
        cat.register("f2", HostTable.from_pydict(
            {"a": list(a2), "b": list(b2), "y": list(range(n))}))
        q = ("SELECT a, count(*) AS c, sum(y) AS sy FROM ("
             "SELECT f1.a AS a, y FROM f1 JOIN f2 "
             "ON f1.a = f2.a AND f1.b = f2.b) t GROUP BY a ORDER BY a")
        single = Session(cat).sql(q).rows()
        dist = Session(cat, dist_shards=8).sql(q).rows()
        d1 = pd.DataFrame({"a": a, "b": b})
        d2 = pd.DataFrame({"a": a2, "b": b2, "y": range(n)})
        m = d1.merge(d2, on=["a", "b"])
        exp = (m.groupby("a").agg(c=("y", "size"), sy=("y", "sum"))
               .reset_index().sort_values("a"))
        expected = [(int(r.a), int(r.c), int(r.sy)) for r in exp.itertuples()]
        assert [(int(aa), int(c), int(sy)) for aa, c, sy in single] == expected
        _same(single, dist, "hash_multikey")
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_string_expression_key_join_distributed(eight_devices):
    """Join keys that are string EXPRESSIONS (fresh per-side dicts) can't be
    aligned at the column level — the planner must gather the build side
    rather than shuffle both sides by incomparable codes."""
    import numpy as np

    from starrocks_tpu.column import HostTable
    from starrocks_tpu.storage.catalog import Catalog

    rng = np.random.default_rng(13)
    n = 30_000
    w1 = [f"K{i:03d}" for i in range(40)]
    w2 = [f"k{i:03d}" for i in range(20, 60)]
    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        cat = Catalog()
        cat.register("e1", HostTable.from_pydict({
            "k": [w1[i] for i in rng.integers(0, 40, n)],
            "x": list(range(n))}))
        cat.register("e2", HostTable.from_pydict({
            "k": [w2[i] for i in rng.integers(0, 40, n)],
            "y": list(rng.integers(0, 100, n))}))
        q = ("SELECT count(*) AS c, sum(y) AS sy FROM e1 JOIN e2 "
             "ON lower(e1.k) = lower(e2.k)")
        single = Session(cat).sql(q).rows()
        dist = Session(cat, dist_shards=8).sql(q).rows()
        assert single[0][0] > 0
        _same(single, dist, "string_expr_join")
    finally:
        D.SHARD_THRESHOLD_ROWS = old
