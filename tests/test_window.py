"""Window function tests vs pandas (reference analog: be/test/exec analytic
tests) + a TPC-DS Q67-shaped query (rank over rollup-style aggregates)."""

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import tpch_catalog


@pytest.fixture(scope="module")
def sess():
    s = Session()
    rng = np.random.default_rng(7)
    n = 500
    g = rng.integers(0, 12, n)
    x = rng.integers(0, 40, n)
    v = np.round(rng.normal(10, 3, n), 2)
    s.sql("create table w (g int, x int, v double)")
    rows = ", ".join(f"({a}, {b}, {c})" for a, b, c in zip(g, x, v))
    s.sql(f"insert into w values {rows}")
    s._df = pd.DataFrame({"g": g, "x": x, "v": v})
    return s


def test_row_number_rank_vs_pandas(sess):
    r = sess.sql("""select g, x, v,
        row_number() over (partition by g order by x, v) rn,
        rank() over (partition by g order by x) rk
        from w order by g, x, v""")
    got = pd.DataFrame(r.rows(), columns=["g", "x", "v", "rn", "rk"])
    df = sess._df.sort_values(["g", "x", "v"]).reset_index(drop=True)
    exp_rn = df.groupby("g").cumcount() + 1
    exp_rk = df.groupby("g")["x"].rank(method="min").astype(int)
    np.testing.assert_array_equal(got["rn"], exp_rn)
    np.testing.assert_array_equal(got["rk"], exp_rk)


def test_partition_agg_vs_pandas(sess):
    r = sess.sql("""select g, v, sum(v) over (partition by g) t,
        avg(v) over (partition by g) a,
        count(*) over (partition by g) c,
        max(v) over (partition by g) mx
        from w order by g, v""")
    got = pd.DataFrame(r.rows(), columns=["g", "v", "t", "a", "c", "mx"])
    df = sess._df.sort_values(["g", "v"]).reset_index(drop=True)
    np.testing.assert_allclose(got["t"], df.groupby("g")["v"].transform("sum"), rtol=1e-9)
    np.testing.assert_allclose(got["a"], df.groupby("g")["v"].transform("mean"), rtol=1e-9)
    np.testing.assert_array_equal(got["c"], df.groupby("g")["v"].transform("size"))
    np.testing.assert_allclose(got["mx"], df.groupby("g")["v"].transform("max"), rtol=1e-12)


def test_running_sum_vs_pandas(sess):
    r = sess.sql("""select g, x, sum(x) over (partition by g order by x) rs
        from w order by g, x""")
    got = pd.DataFrame(r.rows(), columns=["g", "x", "rs"])
    df = sess._df.sort_values(["g", "x"]).reset_index(drop=True)
    # RANGE frame: peers (equal x) share the value -> groupby cumsum per peer
    exp = df.groupby("g")["x"].cumsum()
    peers = df.groupby(["g", "x"])["x"].transform("size")
    # compute peer-extended cumsum: last cumsum within each (g, x) group
    exp_ext = df.assign(cs=exp).groupby(["g", "x"])["cs"].transform("max")
    np.testing.assert_array_equal(got["rs"], exp_ext)


def test_q67_shape(sess):
    """TPC-DS Q67 shape: rank over grouped sums, filter rank <= k."""
    s = Session(tpch_catalog(sf=0.01))
    r = s.sql("""
      select * from (
        select l_returnflag, l_suppkey, sumqty,
               rank() over (partition by l_returnflag order by sumqty desc) rk
        from (select l_returnflag, l_suppkey, sum(l_quantity) sumqty
              from lineitem group by l_returnflag, l_suppkey) agg
      ) ranked
      where rk <= 3
      order by l_returnflag, rk, l_suppkey""")
    rows = r.rows()
    df = s.catalog.get_table("lineitem").table.to_pandas()
    g = df.groupby(["l_returnflag", "l_suppkey"], as_index=False).agg(
        sumqty=("l_quantity", "sum"))
    g["rk"] = g.groupby("l_returnflag")["sumqty"].rank(method="min", ascending=False).astype(int)
    exp = g[g.rk <= 3].sort_values(["l_returnflag", "rk", "l_suppkey"])
    assert len(rows) == len(exp)
    for got_row, exp_row in zip(rows, exp.itertuples(index=False)):
        assert got_row[0] == exp_row.l_returnflag
        assert got_row[1] == exp_row.l_suppkey
        assert abs(got_row[2] - exp_row.sumqty) < 1e-6
        assert got_row[3] == exp_row.rk


def test_lead_lag_first_last_ntile():
    s = Session()
    s.sql("create table wt (g varchar, x int)")
    s.sql("insert into wt values ('a',1),('a',2),('a',3),('b',10),('b',20)")
    r = s.sql("""select g, x, lag(x) over (partition by g order by x) lg,
      lead(x) over (partition by g order by x) ld,
      lead(x, 2) over (partition by g order by x) ld2,
      first_value(x) over (partition by g order by x) fv,
      last_value(x) over (partition by g order by x) lv,
      ntile(2) over (partition by g order by x) nt
      from wt order by g, x""")
    assert r.rows() == [
        ("a", 1, None, 2, 3, 1, 1, 1),
        ("a", 2, 1, 3, None, 1, 2, 1),
        ("a", 3, 2, None, None, 1, 3, 2),
        ("b", 10, None, 20, None, 10, 10, 1),
        ("b", 20, 10, None, None, 10, 20, 2),
    ]
    # running min with the dead-aware peer extension (regression for
    # _part_count scoping)
    r2 = s.sql("select g, x, min(x) over (partition by g order by x) m from wt order by g, x")
    assert [row[2] for row in r2.rows()] == [1, 1, 1, 10, 10]


def test_lead_lag_defaults_and_hidden_order_columns():
    s = Session()
    s.sql("create table wh (g varchar, x int, y int)")
    s.sql("insert into wh values ('a',1,100),('a',2,200),('b',3,300)")
    # default value fills out-of-partition slots
    assert [r[1] for r in s.sql(
        "select g, lag(x, 1, 0) over (partition by g order by x) d from wh order by g, x"
    ).rows()] == [0, 1, 0]
    # lead arg columns survive pruning even when select-list-only
    assert [r[1] for r in s.sql(
        "select g, lead(y, 1) over (partition by g order by x) l from wh order by g, x"
    ).rows()] == [200, None, None]
    # 2-arg lead inside a GROUP BY query
    assert s.sql(
        "select g, lead(g, 1) over (order by g) n from wh group by g order by g"
    ).rows() == [("a", "b"), ("b", None)]
    # plain hidden ORDER BY column
    assert s.sql("select g from wh order by x desc").rows() == [("b",), ("a",), ("a",)]
