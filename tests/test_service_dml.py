"""HTTP SQL service + DML (DELETE/TRUNCATE/CTAS) + scalar function tests."""

import json
import urllib.request

import pytest

from starrocks_tpu.runtime.http_service import SqlHttpServer
from starrocks_tpu.runtime.session import Session


def _post(port, sql):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/query",
        data=json.dumps({"sql": sql}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_service_end_to_end():
    srv = SqlHttpServer(Session()).start()
    try:
        code, _ = _post(srv.port, "create table t (a int, b varchar)")
        assert code == 200
        _post(srv.port, "insert into t values (1, 'x'), (2, 'y')")
        code, body = _post(srv.port, "select b, count(*) c from t group by b order by b")
        assert code == 200
        assert body["columns"] == ["b", "c"]
        assert body["rows"] == [["x", 1], ["y", 1]]
        # error surface
        code, body = _post(srv.port, "select nope from t")
        assert code == 400 and "unknown column" in body["error"]
        # metrics + profile + tables endpoints
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics") as r:
            assert b"sr_tpu_queries_total" in r.read()
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/profile") as r:
            assert b"compile_and_run" in r.read()
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/tables") as r:
            assert json.loads(r.read()) == ["t"]
    finally:
        srv.stop()


def test_delete_truncate_ctas(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table t (a int, b varchar)")
    s.sql("insert into t values (1,'x'),(2,'y'),(3,'x'),(null,'z')")
    assert s.sql("delete from t where b = 'x'") == 2
    assert s.sql("select a from t order by a nulls last").rows() == [(2,), (None,)]
    # NULL-predicate rows are kept (a > 10 is NULL for a=NULL)
    assert s.sql("delete from t where a > 10") == 0
    # persistence across restart
    s2 = Session(data_dir=str(tmp_path / "db"))
    assert s2.sql("select count(*) c from t").rows() == [(2,)]
    assert s2.sql("create table t2 as select b, count(*) c from t group by b") == 2
    assert s2.sql("select b, c from t2 order by b").rows() == [("y", 1), ("z", 1)]
    assert s2.sql("truncate table t") == 2
    assert s2.sql("select count(*) c from t").rows() == [(0,)]


def test_scalar_function_breadth():
    s = Session()
    s.sql("create table f (s varchar, x double, d date, n decimal(10,2))")
    s.sql("insert into f values ('  Hello ', 2.7182, '2023-07-15', 12.34)")
    r = s.sql("""select length(trim(s)), upper(trim(s)),
        replace(trim(s), 'l', 'L'), concat('<', trim(s), '>'),
        round(x, 2), floor(x), ceil(x), sqrt(4.0), power(2, 10),
        greatest(x, 3.0), least(x, 1.0), round(n, 1),
        datediff(d, date '2023-07-01'), dayofweek(d), quarter(d)
        from f""")
    assert r.rows() == [(5, "HELLO", "HeLLo", "<Hello>", 2.72, 2.0, 3.0, 2.0,
                         1024.0, 3.0, 1.0, 12.3, 14, 7, 3)]
    # NULL propagation through math fns: sqrt(-1) and ln(0) -> NULL
    r2 = s.sql("select sqrt(0.0 - 1.0), ln(0.0) from f")
    assert r2.rows() == [(None, None)]


def test_primary_key_upsert_update_set(tmp_path):
    d = str(tmp_path / "pkdb")
    s = Session(data_dir=d)
    s.sql("create table pk (k int, v varchar, n int, primary key(k))")
    s.sql("insert into pk values (1, 'a', 10), (2, 'b', 20)")
    s.sql("insert into pk values (2, 'B', 99), (3, 'c', 30)")
    assert s.sql("select k, v, n from pk order by k").rows() == [
        (1, "a", 10), (2, "B", 99), (3, "c", 30)]
    s.sql("update pk set n = n * 2 where k >= 2")
    assert s.sql("select k, n from pk order by k").rows() == [(1, 10), (2, 198), (3, 60)]
    # restart: PK metadata survives; upsert still applies
    s2 = Session(data_dir=d)
    s2.sql("insert into pk values (1, 'A!', 1)")
    assert s2.sql("select k, v, n from pk order by k").rows() == [
        (1, "A!", 1), (2, "B", 198), (3, "c", 60)]
    # SET + config/metrics virtual tables
    s2.sql("set max_recompiles = 5")
    assert s2.sql(
        "select value from information_schema.be_configs where name = 'max_recompiles'"
    ).rows() == [("5",)]
    s2.sql("set max_recompiles = 6")
    assert s2.sql("select count(*) c from information_schema.metrics").rows()[0][0] > 0
    # planner uses the PK for unique-build joins
    plan = s2.sql("explain select pk.v from pk, pk p2 where pk.k = p2.k")
    assert "Join[inner" in plan
