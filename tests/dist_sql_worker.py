"""Worker process for the cross-process fragment-IR SQL test.

Launched twice by tests/test_dist_fragments.py. Each process joins the
global mesh via jax.distributed (2 processes x 4 virtual CPU devices = 8
global shards; on TPU pods the same code spans hosts over DCN), builds
the SAME deterministic TPC-H catalog, and runs one SQL statement through
the fragment-IR executor:

    sharded lineitem scan -> hash-partition exchange (shuffle-final
    aggregation by l_orderkey) -> TopN gather

Placement goes through make_array_from_callback, so each process
materializes only ITS shards of the table (the per-process TabletStore
slice); the hash exchange and the runtime counters' psums run over the
full 8-shard axis, crossing the process boundary on gloo (the CPU
stand-in for DCN). Both processes must agree with a host-side numpy
oracle computed from the full table.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def main():
    pid = int(sys.argv[1])
    coord = sys.argv[2]  # jax.distributed coordinator addr

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")
    from starrocks_tpu.runtime.cluster import init_multihost

    devices = init_multihost(coord, num_processes=2, process_id=pid,
                             local_device_count=4)
    assert len(devices) == 8, devices

    import starrocks_tpu.sql.distributed as D

    # tiny tables must still take the distributed path, and the multi-key
    # group-by must take the shuffle-final (hash exchange) strategy
    D.SHARD_THRESHOLD_ROWS = 10_000
    D.SHUFFLE_AGG_MIN_GROUPS = 100

    from starrocks_tpu.parallel.mesh import mesh_spans_processes
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.storage.catalog import tpch_catalog

    cat = tpch_catalog(sf=0.01)
    sess = Session(cat, dist_shards=8)
    sql = ("select l_suppkey, l_linestatus, sum(l_quantity) q "
           "from lineitem group by l_suppkey, l_linestatus "
           "order by q desc, l_suppkey, l_linestatus limit 5")

    def run(fragments):
        config.set("dist_fragments", fragments)
        rs = sess.sql(sql)
        return [list(r.values()) if isinstance(r, dict) else list(r)
                for r in rs.rows()]

    rows = run(True)
    rows_mono = run(False)  # pre-IR monolithic program, same global mesh
    config.set("dist_fragments", True)
    ok = rows == rows_mono and len(rows) == 5

    # host-side oracle: the global sum must cover EVERY process's rows
    # (a per-process partial would be ~half of it)
    total = sess.sql("select sum(l_quantity) t from lineitem").rows()
    tv = list(total[0].values())[0] if isinstance(total[0], dict) \
        else total[0][0]
    ht = cat.get_table("lineitem").table
    expected_total = float(np.asarray(
        ht.arrays["l_quantity"], dtype=np.float64).sum())
    ok = ok and np.isclose(float(tv), expected_total)

    de = sess._dist_executor
    spans = mesh_spans_processes(de.mesh)
    kinds = sorted({ev.kind for (ir, _) in de._frag_ir_memo.values()
                    for ev in ir.events})
    nfrag = max(len(ir.fragments)
                for (ir, _) in de._frag_ir_memo.values())
    print(f"proc {pid}: sql ok={ok} spans_processes={spans} "
          f"exchange_kinds={kinds} fragments={nfrag} rows={rows}",
          flush=True)
    if not (ok and spans and "hash" in kinds and nfrag >= 2):
        sys.exit(3)


if __name__ == "__main__":
    main()
