"""Multithreaded stress + lock-witness tests (ISSUE 6).

The runtime half of the concurrency contract: N threads hammer the
engine's shared registries — QueryRegistry register/cancel/snapshot,
MemoryAccountant charge/release, query-cache get/put/invalidate — with
the DebugLock witness recording every acquisition order (conftest enables
it process-wide). Afterward the accountant books must balance to zero and
the global order graph must be acyclic. Plus regression tests for the two
pre-existing races this round fixed (MetricRegistry get-or-create,
QueryRegistry.last_kill_result) and unit tests for the witness itself.
"""

from __future__ import annotations

import threading

import pytest

from starrocks_tpu import lockdep
from starrocks_tpu.cache.query_cache import QueryCache
from starrocks_tpu.runtime.lifecycle import (
    MemoryAccountant,
    QueryContext,
    QueryRegistry,
)
from starrocks_tpu.runtime.metrics import Counter, Gauge, MetricRegistry

N_THREADS = 8
N_ITERS = 150


def _run_threads(fn, n=N_THREADS):
    errs = []

    def wrap(i):
        try:
            fn(i)
        except Exception as e:  # noqa: BLE001 — surfaced via the assert
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in ts), "stress thread wedged"
    assert errs == [], errs[:3]


# --- the witness itself --------------------------------------------------------

def test_witness_enabled_for_suite():
    assert lockdep.enabled()
    assert isinstance(Counter("t_w_enabled")._lock, lockdep.DebugLock)


def test_factories_plain_when_disabled():
    lockdep.disable()
    try:
        assert type(lockdep.lock("x")).__name__ == "lock"
        assert not isinstance(lockdep.rlock("x"), lockdep.DebugRLock)
    finally:
        lockdep.enable()


def test_seeded_inversion_reports_cycle_with_both_stacks():
    w = lockdep.Witness()  # private graph: the session gate stays clean
    a = lockdep.DebugLock("T.A", w)
    b = lockdep.DebugLock("T.B", w)
    order_ab = threading.Event()

    def t1():
        with a:
            with b:
                pass
        order_ab.set()

    def t2():
        order_ab.wait(5)
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start()
    th2.start()
    th1.join(5)
    th2.join(5)
    cycles = w.order_cycles()
    assert cycles == [["T.A", "T.B"]]
    report = w.render(cycles)
    # both stacks: where the held lock was taken, and the acquirer's stack
    assert "T.A -> T.B" in report and "T.B -> T.A" in report
    assert "held at" in report and "acquired at" in report
    assert "test_concurrency.py" in report


def test_one_way_nesting_no_cycle():
    w = lockdep.Witness()
    outer = lockdep.DebugLock("T.outer", w)
    inner = lockdep.DebugLock("T.inner", w)

    def worker(_i):
        for _ in range(50):
            with outer:
                with inner:
                    pass

    _run_threads(worker, n=4)
    assert w.order_cycles() == []
    assert ("T.outer", "T.inner") in w.edges()


def test_event_handoff_inversion_reports_cycle():
    """Seeded Event-handoff deadlock shape (the NEXT "witness coverage
    for threading.Event-based handoffs" item): thread 1 parks on the
    event while holding A — edge A -> E via before_block — and thread 2
    fires the event under A — edge E -> A via on_event_set. Neither run
    hangs (the wait has a timeout and the ordering is seeded), but the
    two edges close the waiter-holds-lock-the-setter-needs cycle."""
    w = lockdep.Witness()
    a = lockdep.DebugLock("T.A", w)
    done = lockdep.DebugEvent("T.done", w)
    waited = threading.Event()

    def waiter():
        with a:
            done.wait(0.05)   # parks holding A: records A -> T.done
        waited.set()

    def setter():
        waited.wait(5)        # seeded order: the wait edge lands first
        with a:
            done.set()        # fires under A: records T.done -> A

    th1 = threading.Thread(target=waiter)
    th2 = threading.Thread(target=setter)
    th1.start()
    th2.start()
    th1.join(5)
    th2.join(5)
    assert ["T.A", "T.done"] in w.order_cycles()
    edges = w.edges()
    assert ("T.A", "T.done") in edges and ("T.done", "T.A") in edges


def test_event_handoff_correct_order_no_cycle():
    """The serving-pool shape: the worker sets the done event holding no
    lock, the connection thread waits holding no lock — no edges at all,
    let alone a cycle."""
    w = lockdep.Witness()
    a = lockdep.DebugLock("T.A", w)
    done = lockdep.DebugEvent("T.done", w)
    with a:
        pass           # the lock is used, but never across the handoff
    done.set()
    assert done.wait(1)
    assert w.edges() == {} and w.order_cycles() == []


def test_event_factory_obeys_witness_toggle():
    assert isinstance(lockdep.event("t_ev"), lockdep.DebugEvent)
    lockdep.disable()
    try:
        ev = lockdep.event("t_ev_plain")
        assert not isinstance(ev, lockdep.DebugEvent)
        assert type(ev).__name__ == "Event"
    finally:
        lockdep.enable()


def test_self_deadlock_raises_instead_of_hanging():
    w = lockdep.Witness()
    mu = lockdep.DebugLock("T.mu", w)
    mu.acquire()
    try:
        with pytest.raises(lockdep.LockOrderError, match="self-deadlock"):
            mu.acquire()
    finally:
        mu.release()


def test_debug_rlock_is_reentrant_and_condition_capable():
    w = lockdep.Witness()
    rl = lockdep.DebugRLock("T.rl", w)
    with rl:
        with rl:
            assert rl._is_owned()
    assert not rl._is_owned()
    # Condition protocol: wait() must fully release (another thread can
    # acquire) and re-acquire on notify
    cond = threading.Condition(lockdep.DebugRLock("T.cond", w))
    ready = []

    def waiter():
        with cond:
            ready.append("waiting")
            cond.wait(timeout=10)
            ready.append("woken")

    t = threading.Thread(target=waiter)
    t.start()
    while "waiting" not in ready:
        pass
    with cond:  # acquirable only because wait() released the lock
        cond.notify_all()
    t.join(10)
    assert ready == ["waiting", "woken"]


# --- regression: the two pre-existing races ------------------------------------

def test_metric_registry_get_or_create_race():
    """runtime/metrics.py:26 (pre-fix): an unlocked setdefault minted
    divergent Counter instances under contention and constructed a
    throwaway per call. Every thread must get the SAME instance and no
    increment may be lost."""
    reg = MetricRegistry()
    instances = []
    mu = threading.Lock()

    def worker(_i):
        c = reg.counter("stress_total", "the contended one")
        with mu:
            instances.append(c)
        for _ in range(N_ITERS):
            c.inc()

    _run_threads(worker)
    assert len({id(c) for c in instances}) == 1
    assert reg.counter("stress_total").value == N_THREADS * N_ITERS
    # gauge twin, and kind is stable across get-or-create
    g = reg.gauge("stress_gauge")
    assert isinstance(g, Gauge) and reg.gauge("stress_gauge") is g


def test_last_kill_result_under_lock():
    """runtime/lifecycle.py (pre-fix): last_kill_result was mutated
    outside _lock. Now folded under it (and annotated guarded_by, which
    tools/concur_lint.py enforces): hammer cancels against a churning
    registry and read through the locked accessor."""
    reg = QueryRegistry()

    def worker(i):
        for k in range(N_ITERS):
            if i % 2 == 0:
                ctx = reg.register(QueryContext(f"select {i}"))
                reg.cancel(ctx.qid)
                reg.deregister(ctx)
            else:
                reg.cancel(10_000_000 + k)  # never-registered: no-op path
                assert reg.kill_result() in ("delivered", "not-running")

    _run_threads(worker)
    # the last writer is interleaving-dependent, but the value must be a
    # coherent one (never None/torn after thousands of cross-thread kills)
    assert reg.kill_result() in ("delivered", "not-running")
    assert reg.snapshot() == []

    ctx = reg.register(QueryContext("select 1"))
    assert reg.cancel(ctx.qid) is True
    assert reg.kill_result() == "delivered"


# --- the combined stress: registries + accountant + cache under DebugLock ------

class _FakeTable:
    """Minimal HostTable shape for cache byte accounting."""

    arrays: dict = {}
    valids: dict = {}
    schema = ()


class _FakeCatalog:
    def __init__(self):
        self._v = {}

    def bump(self, t):
        self._v[t] = self._v.get(t, 0) + 1

    def data_version(self, t):
        return self._v.get(t, 0)


def test_stress_registry_accountant_cache_balance_and_no_cycles():
    reg = QueryRegistry()
    acct = MemoryAccountant()
    cache = QueryCache()
    cat = _FakeCatalog()
    before = acct.snapshot()

    def worker(i):
        for k in range(N_ITERS):
            ctx = reg.register(QueryContext(f"select {i} /* {k} */",
                                            group=f"g{i % 3}"))
            try:
                acct.charge(ctx, 1024 * (1 + i), f"stage{k % 4}")
                acct.charge(ctx, 512, "merge")
                tbl = f"t{k % 5}"
                skey = (i % 4, k % 7)
                hit = cache.lookup_result(skey, cat)
                if hit is None:
                    cache.store_result(
                        skey, _FakeTable(), plan=None,
                        versions={tbl: cat.data_version(tbl)})
                cache.put_partial(("frag", i % 3), ("seg", k % 5),
                                  _FakeTable(), rows=10)
                cache.get_partial(("frag", i % 3), ("seg", k % 5))
                if k % 11 == 0:
                    cat.bump(tbl)
                    cache.invalidate_table(tbl)
                if k % 3 == 0:
                    reg.cancel(ctx.qid)
                reg.snapshot()
            finally:
                acct.release_query(ctx)
                reg.deregister(ctx)

    _run_threads(worker)
    after = acct.snapshot()
    assert after["process_bytes"] == before["process_bytes"] == 0
    assert after["group_bytes"] == {}
    assert reg.snapshot() == []
    # every lock in this path ran through DebugLock: the global order
    # graph must stay acyclic (the session-teardown gate re-asserts this
    # over the WHOLE suite's interleavings)
    assert lockdep.WITNESS.order_cycles() == []


def test_accountant_charge_is_atomic_under_contention():
    acct = MemoryAccountant()
    ctxs = [QueryContext(f"q{i}", group="g") for i in range(N_THREADS)]
    for i, c in enumerate(ctxs):
        c.qid = i + 1

    def worker(i):
        for _ in range(N_ITERS):
            acct.charge(ctxs[i], 100, "s")

    _run_threads(worker)
    snap = acct.snapshot()
    assert snap["process_bytes"] == N_THREADS * N_ITERS * 100
    assert snap["group_bytes"]["g"] == N_THREADS * N_ITERS * 100
    for c in ctxs:
        acct.release_query(c)
    assert acct.snapshot() == {"process_bytes": 0, "group_bytes": {}}
