"""Operator tests with pandas as differential oracle
(reference analog: be/test/exec/ operator unit tests)."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from starrocks_tpu import types as T
from starrocks_tpu.column import HostTable
from starrocks_tpu.exprs import AggExpr, col, gt, lit, mul
from starrocks_tpu.ops import (
    COMPLETE, FINAL, PARTIAL,
    INNER, LEFT_ANTI, LEFT_OUTER, LEFT_SEMI,
    compact, filter_chunk, final_agg_exprs, hash_aggregate,
    hash_join_expand, hash_join_unique, limit_chunk, project, sort_chunk,
)


def _res(chunk):
    return HostTable.from_chunk(chunk).to_pylist()


def test_filter_project():
    c = HostTable.from_pydict({"a": [1, 2, 3, 4], "b": [10.0, 20.0, 30.0, 40.0]}).to_chunk()
    f = filter_chunk(c, gt(col("a"), lit(2)))
    assert int(f.num_rows()) == 2
    p = project(f, [mul(col("b"), lit(2.0))], ["b2"])
    assert _res(p) == [(60.0,), (80.0,)]


def test_compact():
    c = HostTable.from_pydict({"a": list(range(10))}).to_chunk()
    f = filter_chunk(c, gt(col("a"), lit(6)))
    k, kn = compact(f)
    assert int(kn) == 3
    arr = np.asarray(k.col("a")[0])
    assert list(arr[:3]) == [7, 8, 9]
    assert int(k.num_rows()) == 3


def test_aggregate_basic_vs_pandas():
    rng = np.random.default_rng(0)
    n = 5000
    df = pd.DataFrame({
        "k1": rng.integers(0, 7, n),
        "k2": rng.integers(0, 3, n),
        "v": rng.normal(size=n),
        "w": rng.integers(0, 100, n),
    })
    c = HostTable.from_pydict({k: df[k].to_numpy() for k in df}).to_chunk()
    out, ng = hash_aggregate(
        c,
        group_by=(("k1", col("k1")), ("k2", col("k2"))),
        aggs=(
            ("s", AggExpr("sum", col("v"))),
            ("cnt", AggExpr("count", None)),
            ("mn", AggExpr("min", col("w"))),
            ("mx", AggExpr("max", col("w"))),
            ("av", AggExpr("avg", col("v"))),
        ),
        num_groups=64,
    )
    assert int(ng) == 21
    got = pd.DataFrame(
        _res(out), columns=["k1", "k2", "s", "cnt", "mn", "mx", "av"]
    ).sort_values(["k1", "k2"]).reset_index(drop=True)
    exp = (
        df.groupby(["k1", "k2"], as_index=False)
        .agg(s=("v", "sum"), cnt=("v", "size"), mn=("w", "min"), mx=("w", "max"), av=("v", "mean"))
        .sort_values(["k1", "k2"]).reset_index(drop=True)
    )
    np.testing.assert_allclose(got["s"], exp["s"], rtol=1e-9)
    np.testing.assert_array_equal(got["cnt"], exp["cnt"])
    np.testing.assert_array_equal(got["mn"], exp["mn"])
    np.testing.assert_array_equal(got["mx"], exp["mx"])
    np.testing.assert_allclose(got["av"], exp["av"], rtol=1e-9)


def test_aggregate_nulls_and_dead_rows():
    c = HostTable.from_pydict(
        {"k": [1, 1, 2, 2, 2], "v": [1.0, None, 3.0, None, 5.0]}
    ).to_chunk()
    c = filter_chunk(c, gt(col("k"), lit(0)))  # all live; then kill row 4
    c = c.and_sel(jnp.arange(c.capacity) != 4)
    out, ng = hash_aggregate(
        c, (("k", col("k")),),
        (("s", AggExpr("sum", col("v"))), ("c", AggExpr("count", col("v"))),
         ("cs", AggExpr("count", None))),
        num_groups=8,
    )
    rows = sorted(_res(out))
    assert int(ng) == 2
    assert rows == [(1, 1.0, 1, 2), (2, 3.0, 1, 2)]


def test_aggregate_null_group_key():
    c = HostTable.from_pydict({"k": [1, None, None, 2], "v": [1, 2, 3, 4]}).to_chunk()
    out, ng = hash_aggregate(
        c, (("k", col("k")),), (("s", AggExpr("sum", col("v"))),), num_groups=8
    )
    assert int(ng) == 3
    rows = _res(out)
    bynull = {r[0]: r[1] for r in rows}
    assert bynull[None] == 5 and bynull[1] == 1 and bynull[2] == 4


def test_global_aggregate_empty_input():
    c = HostTable.from_pydict({"v": [1.0, 2.0]}).to_chunk()
    c = c.and_sel(jnp.zeros((c.capacity,), jnp.bool_))
    out, ng = hash_aggregate(
        c, (), (("c", AggExpr("count", None)), ("s", AggExpr("sum", col("v")))),
        num_groups=1,
    )
    rows = _res(out)
    assert rows == [(0, None)]  # COUNT=0, SUM=NULL over empty set


def test_two_phase_aggregate():
    rng = np.random.default_rng(1)
    n = 2000
    k = rng.integers(0, 5, n)
    v = rng.normal(size=n)
    full = HostTable.from_pydict({"k": k, "v": v}).to_chunk()
    aggs = (("s", AggExpr("sum", col("v"))), ("a", AggExpr("avg", col("v"))),
            ("c", AggExpr("count", None)))
    # single phase
    ref, _ = hash_aggregate(full, (("k", col("k")),), aggs, num_groups=8)
    # two phase: split rows in half, partial each, concat states, final
    h1 = HostTable.from_pydict({"k": k[:1000], "v": v[:1000]}).to_chunk()
    h2 = HostTable.from_pydict({"k": k[1000:], "v": v[1000:]}).to_chunk()
    p1, _ = hash_aggregate(h1, (("k", col("k")),), aggs, num_groups=8, mode=PARTIAL)
    p2, _ = hash_aggregate(h2, (("k", col("k")),), aggs, num_groups=8, mode=PARTIAL)
    # concat the two partial chunks host-side (exchange analog)
    t1, t2 = HostTable.from_chunk(p1), HostTable.from_chunk(p2)
    merged = HostTable(
        t1.schema,
        {f.name: np.concatenate([t1.arrays[f.name], t2.arrays[f.name]]) for f in t1.schema},
        {k2: np.concatenate([t1.valids[k2], t2.valids[k2]]) for k2 in t1.valids},
    ).to_chunk()
    fin, _ = hash_aggregate(
        merged, (("k", col("k")),), final_agg_exprs(aggs), num_groups=8, mode=FINAL
    )
    a = sorted(_res(ref))
    b = sorted(_res(fin))
    for ra, rb in zip(a, b):
        np.testing.assert_allclose(ra, rb, rtol=1e-9)


def _join_inputs():
    probe = HostTable.from_pydict(
        {"pk": [1, 2, 3, 4, 5], "pv": [10, 20, 30, 40, 50]}
    ).to_chunk()
    build = HostTable.from_pydict(
        {"bk": [2, 4, 6], "bv": ["x", "y", "z"]}
    ).to_chunk()
    return probe, build


def test_join_unique_inner():
    probe, build = _join_inputs()
    out = hash_join_unique(probe, build, (col("pk"),), (col("bk"),), INNER,
                           payload=["bv"])
    assert sorted(_res(out)) == [(2, 20, "x"), (4, 40, "y")]


def test_join_unique_left_outer():
    probe, build = _join_inputs()
    out = hash_join_unique(probe, build, (col("pk"),), (col("bk"),), LEFT_OUTER,
                           payload=["bv"])
    rows = sorted(_res(out))
    assert rows == [(1, 10, None), (2, 20, "x"), (3, 30, None), (4, 40, "y"), (5, 50, None)]


def test_join_semi_anti():
    probe, build = _join_inputs()
    semi = hash_join_unique(probe, build, (col("pk"),), (col("bk"),), LEFT_SEMI)
    assert sorted(r[0] for r in _res(semi)) == [2, 4]
    anti = hash_join_unique(probe, build, (col("pk"),), (col("bk"),), LEFT_ANTI)
    assert sorted(r[0] for r in _res(anti)) == [1, 3, 5]


def test_join_null_keys_never_match():
    probe = HostTable.from_pydict({"pk": [1, None, 3]}).to_chunk()
    build = HostTable.from_pydict({"bk": [None, 3], "bv": [7, 8]}).to_chunk()
    out = hash_join_unique(probe, build, (col("pk"),), (col("bk"),), INNER,
                           payload=["bv"])
    assert _res(out) == [(3, 8)]
    lo = hash_join_unique(probe, build, (col("pk"),), (col("bk"),), LEFT_OUTER,
                          payload=["bv"])
    assert sorted(_res(lo), key=str) == sorted([(1, None), (None, None), (3, 8)], key=str)


def test_join_expand_duplicates_vs_pandas():
    rng = np.random.default_rng(2)
    pdf = pd.DataFrame({"k": rng.integers(0, 10, 200), "pv": np.arange(200)})
    bdf = pd.DataFrame({"k": rng.integers(0, 10, 30), "bv": np.arange(30) * 10})
    probe = HostTable.from_pydict({"pk": pdf["k"].to_numpy(), "pv": pdf["pv"].to_numpy()}).to_chunk()
    build = HostTable.from_pydict({"bk": bdf["k"].to_numpy(), "bv": bdf["bv"].to_numpy()}).to_chunk()
    out, total = hash_join_expand(
        probe, build, (col("pk"),), (col("bk"),), out_capacity=2048, join_type=INNER,
        payload=["bv"],
    )
    exp = pdf.merge(bdf, on="k")
    assert int(total) == len(exp)
    got = sorted(_res(out))
    expected = sorted(zip(exp["k"], exp["pv"], exp["bv"]))
    assert got == [tuple(map(int, e)) for e in expected]


def test_join_expand_left_outer():
    probe = HostTable.from_pydict({"pk": [1, 2, 2, 9]}).to_chunk()
    build = HostTable.from_pydict({"bk": [2, 2, 3], "bv": [5, 6, 7]}).to_chunk()
    out, total = hash_join_expand(
        probe, build, (col("pk"),), (col("bk"),), out_capacity=1024,
        join_type=LEFT_OUTER, payload=["bv"],
    )
    rows = sorted(_res(out), key=str)
    assert (1, None) in rows and (9, None) in rows
    assert (2, 5) in rows and (2, 6) in rows
    assert int(total) == 6  # 1,9 -> 1 row each; each 2 -> 2 rows


def test_multi_key_join_packed():
    probe = HostTable.from_pydict({"a": [1, 1, 2], "b": [5, 6, 5], "v": [1, 2, 3]}).to_chunk()
    build = HostTable.from_pydict({"x": [1, 2], "y": [6, 5], "w": [100, 200]}).to_chunk()
    out = hash_join_unique(
        probe, build, (col("a"), col("b")), (col("x"), col("y")), INNER,
        payload=["w"], bit_widths=(20, 20),
    )
    assert sorted(_res(out)) == [(1, 6, 2, 100), (2, 5, 3, 200)]


def test_sort_and_limit():
    c = HostTable.from_pydict(
        {"a": [3, 1, None, 2], "b": [1.0, 2.0, 3.0, 4.0]}
    ).to_chunk()
    s = sort_chunk(c, ((col("a"), True, False),))  # asc, nulls last
    rows = _res(s)
    assert [r[0] for r in rows] == [1, 2, 3, None]
    s2 = sort_chunk(c, ((col("a"), False, True),))  # desc, nulls first
    assert [r[0] for r in _res(s2)] == [None, 3, 2, 1]
    s3 = sort_chunk(c, ((col("a"), True, False),), limit=2)
    assert [r[0] for r in _res(s3)] == [1, 2]
    l = limit_chunk(c, 2, offset=1)
    assert [r[0] for r in _res(l)] == [1, None]


def test_sort_multi_key_vs_pandas():
    rng = np.random.default_rng(3)
    df = pd.DataFrame({
        "a": rng.integers(0, 4, 100),
        "b": rng.normal(size=100),
    })
    c = HostTable.from_pydict({k: df[k].to_numpy() for k in df}).to_chunk()
    s = sort_chunk(c, ((col("a"), True, False), (col("b"), False, False)))
    got = pd.DataFrame(_res(s), columns=["a", "b"])
    exp = df.sort_values(["a", "b"], ascending=[True, False]).reset_index(drop=True)
    np.testing.assert_array_equal(got["a"], exp["a"])
    np.testing.assert_allclose(got["b"], exp["b"])


def test_aggregate_jit_composable():
    c = HostTable.from_pydict({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]}).to_chunk()

    @jax.jit
    def q(ch):
        f = filter_chunk(ch, gt(col("v"), lit(0.5)))
        out, ng = hash_aggregate(
            f, (("k", col("k")),), (("s", AggExpr("sum", col("v"))),), num_groups=8
        )
        return out, ng

    out, ng = q(c)
    assert int(ng) == 2
    assert sorted(_res(out)) == [(1, 4.0), (2, 2.0)]


def test_join_expand_null_probe_key_left_outer():
    # regression: NULL-key probe rows must not match the build sentinel run
    probe = HostTable.from_pydict({"pk": [None, 2]}).to_chunk()
    build = HostTable.from_pydict({"bk": [None, 2], "bv": [999, 5]}).to_chunk()
    out, total = hash_join_expand(
        probe, build, (col("pk"),), (col("bk"),), out_capacity=1024,
        join_type=LEFT_OUTER, payload=["bv"],
    )
    rows = sorted(_res(out), key=str)
    assert (None, None) in rows and (2, 5) in rows
    assert (None, 999) not in rows


def test_dense_runtime_filter_exactness():
    # an exact IN-set filter passes ONLY surviving build keys (min/max can't)
    from starrocks_tpu.ops.join import runtime_filter_mask

    probe = HostTable.from_pydict({"pk": [1, 2, 3, 4, 5, 6]}).to_chunk()
    build = HostTable.from_pydict({"bk": [1, 3, 5, 6]}).to_chunk()
    build = build.and_sel(jnp.asarray(
        [True, True, False, True] + [False] * (build.capacity - 4)))  # drop 5
    m = runtime_filter_mask(probe, build, (col("pk"),), (col("bk"),),
                            dense_range=(1, 6))
    assert list(np.asarray(m)[:6]) == [True, False, True, False, False, True]
    # min/max only bounds the range
    m2 = runtime_filter_mask(probe, build, (col("pk"),), (col("bk"),))
    assert list(np.asarray(m2)[:6]) == [True, True, True, True, True, True]


def test_stale_stats_program_eviction():
    # regression: INSERT must evict cached programs whose traces baked
    # stats-derived constants (dense RF ranges)
    from starrocks_tpu.runtime.session import Session

    s = Session()
    s.sql("create table dl (k int)")
    s.sql("create table dr (k int, v int)")
    s.sql("insert into dl values (1), (2)")
    s.sql("insert into dr values (1, 10), (2, 20)")
    q = "select dl.k, dr.v from dl, dr where dl.k = dr.k order by 1"
    assert s.sql(q).rows() == [(1, 10), (2, 20)]
    # extend the key range WITHOUT changing padded capacities
    s.sql("insert into dl values (99)")
    s.sql("insert into dr values (99, 990)")
    assert s.sql(q).rows() == [(1, 10), (2, 20), (99, 990)]
