"""Transparent MV rewrite: golden plans + staleness + rollup correctness.

Reference analog: MV rewrite tests around
fe sql/optimizer/rule/transformation/materialization/MaterializedViewRewriter.java
(same scan set, predicate containment, group-by subset + agg rollup).
"""

import pytest

from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog
from starrocks_tpu.column import HostTable


@pytest.fixture()
def sess():
    cat = Catalog()
    n = 500
    cat.register("sales", HostTable.from_pydict({
        "region": [["east", "west", "north"][i % 3] for i in range(n)],
        "prod": [f"p{i % 7}" for i in range(n)],
        "qty": [(i * 13) % 50 for i in range(n)],
        "price": [float((i * 7) % 90) + 0.5 for i in range(n)],
    }))
    s = Session(cat)
    s.sql("""create materialized view mv_sales as
        select region, prod, sum(qty) as sum_qty, count(qty) as cnt_qty,
               sum(price) as sum_price, count(*) as n_rows,
               min(price) as min_price, max(price) as max_price
        from sales group by region, prod""")
    return s


def _uses_mv(s, q, mv="mv_sales"):
    return f"Scan[{mv}" in s.sql("explain " + q)


def _rows_with_and_without(s, q):
    got = s.sql(q).rows()
    config.set("enable_mv_rewrite", False)
    try:
        base = s.sql(q).rows()
    finally:
        config.set("enable_mv_rewrite", True)
    return got, base


def test_exact_group_match_uses_mv(sess):
    q = ("select region, prod, sum(qty) from sales "
         "group by region, prod order by 1, 2")
    assert _uses_mv(sess, q)
    got, base = _rows_with_and_without(sess, q)
    assert got == base


def test_rollup_to_coarser_groups(sess):
    q = ("select region, sum(qty), count(*), min(price), max(price) "
         "from sales group by region order by 1")
    assert _uses_mv(sess, q)
    got, base = _rows_with_and_without(sess, q)
    assert got == base


def test_global_agg_rollup(sess):
    q = "select sum(qty), count(*) from sales"
    assert _uses_mv(sess, q)
    got, base = _rows_with_and_without(sess, q)
    assert got == base


def test_avg_decomposes_to_sum_over_count(sess):
    q = "select region, avg(qty) from sales group by region order by 1"
    assert _uses_mv(sess, q)
    got, base = _rows_with_and_without(sess, q)
    assert len(got) == len(base)
    for g, b in zip(got, base):
        assert g[0] == b[0] and g[1] == pytest.approx(b[1], rel=1e-12)


def test_compensating_filter_on_group_key(sess):
    q = ("select prod, sum(price) from sales where region = 'east' "
         "group by prod order by 1")
    assert _uses_mv(sess, q)
    got, base = _rows_with_and_without(sess, q)
    assert len(got) == len(base)
    for g, b in zip(got, base):
        assert g[0] == b[0] and g[1] == pytest.approx(b[1], rel=1e-12)


def test_no_rewrite_when_filter_not_derivable(sess):
    # qty is aggregated away — a row-level qty filter cannot be compensated
    q = "select region, sum(price) from sales where qty > 10 group by region"
    assert not _uses_mv(sess, q)
    got, base = _rows_with_and_without(sess, q)
    assert sorted(got) == sorted(base)


def test_staleness_disables_until_refresh(sess):
    q = ("select region, prod, sum(qty) from sales "
         "group by region, prod order by 1, 2")
    assert _uses_mv(sess, q)
    sess.sql("insert into sales values ('east', 'p0', 999, 1.0)")
    assert not _uses_mv(sess, q)  # base moved: MV is stale
    got, base = _rows_with_and_without(sess, q)
    assert got == base  # and the answer reflects the new row
    assert any(r[2] >= 999 for r in got)
    sess.sql("refresh materialized view mv_sales")
    assert _uses_mv(sess, q)
    got2, base2 = _rows_with_and_without(sess, q)
    assert got2 == base2 == got


def test_mv_filter_containment(sess):
    sess.sql("""create materialized view mv_east as
        select prod, sum(qty) as sum_qty from sales
        where region = 'east' group by prod""")
    q = "select prod, sum(qty) from sales where region = 'east' group by prod order by 1"
    assert _uses_mv(sess, q, "mv_east")
    got, base = _rows_with_and_without(sess, q)
    assert got == base
    # different predicate: NOT contained, must not use mv_east
    q2 = "select prod, sum(qty) from sales where region = 'west' group by prod"
    assert not _uses_mv(sess, q2, "mv_east")


def test_tpch_query_reads_mv():
    """Golden-plan check on a real TPC-H shape (VERDICT r4 done-criterion)."""
    from starrocks_tpu.storage.catalog import tpch_catalog
    from tests.tpch_queries import QUERIES

    s = Session(tpch_catalog(sf=0.01))
    s.sql("""create materialized view mv_q1 as
        select l_returnflag, l_linestatus,
               sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               count(l_quantity) as cnt_qty,
               count(l_extendedprice) as cnt_price,
               count(*) as count_order
        from lineitem
        where l_shipdate <= date '1998-09-02'
        group by l_returnflag, l_linestatus""")
    q = """select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
              sum(l_extendedprice) as sum_base_price,
              avg(l_quantity) as avg_qty, count(*) as count_order
           from lineitem where l_shipdate <= date '1998-09-02'
           group by l_returnflag, l_linestatus
           order by l_returnflag, l_linestatus"""
    assert "Scan[mv_q1" in s.sql("explain " + q)
    got = s.sql(q).rows()
    config.set("enable_mv_rewrite", False)
    try:
        base = s.sql(q).rows()
    finally:
        config.set("enable_mv_rewrite", True)
    assert len(got) == len(base)
    for g, b in zip(got, base):
        assert g[:2] == b[:2]
        for gv, bv in zip(g[2:], b[2:]):
            assert gv == pytest.approx(bv, rel=1e-9)
