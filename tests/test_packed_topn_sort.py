"""Packed-key sort + threshold TopN vs the lexsort reference.

Property tests: random key-type mixes (dict strings, bools, bounded ints),
NULLs, ASC/DESC and NULLS FIRST/LAST combinations — the packed single-key
argsort, the threshold top-N partial select, and the Pallas block-select
kernel must all reproduce the stable lexsort order EXACTLY (ties resolve
to input order on every path). Plus the rank()<=k window rewrite vs a
brute-force oracle, and the new profile counters.
"""

import dataclasses

import numpy as np
import pytest

from starrocks_tpu import types as T
from starrocks_tpu.column import HostTable
from starrocks_tpu.column.column import Chunk, Schema, pad_capacity
from starrocks_tpu.exprs import col
from starrocks_tpu.ops import sort_chunk
from starrocks_tpu.ops.sort import packed_order_key
from starrocks_tpu.ops.common import eval_keys
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture(autouse=True)
def _restore_flags():
    saved = {k: config.get(k) for k in
             ("enable_packed_sort_keys", "topn_strategy",
              "enable_window_topn", "enable_sort_timing")}
    yield
    for k, v in saved.items():
        config.set(k, v)


def _with_int_bounds(chunk: Chunk, bounds: dict) -> Chunk:
    """Attach catalog-style (lo, hi) bounds to integer fields (the tests
    build chunks directly, bypassing the catalog stats path)."""
    fields = tuple(
        dataclasses.replace(f, bounds=bounds.get(f.name, f.bounds))
        for f in chunk.schema.fields
    )
    return Chunk(Schema(fields), chunk.data, chunk.valid, chunk.sel)


def _gen_columns(rng, n, spec):
    """spec: list of (name, kind) with kind in int|str|bool; ~15% NULLs."""
    data = {}
    ref = {}
    for name, kind in spec:
        nulls = rng.random(n) < 0.15
        if kind == "int":
            v = rng.integers(0, 40, n)
            data[name] = [None if m else int(x) for m, x in zip(nulls, v)]
        elif kind == "bool":
            v = rng.integers(0, 2, n).astype(bool)
            data[name] = [None if m else bool(x) for m, x in zip(nulls, v)]
        else:
            words = ["ash", "birch", "cedar", "dogwood", "elm", "fir"]
            v = rng.integers(0, len(words), n)
            data[name] = [None if m else words[x] for m, x in zip(nulls, v)]
        ref[name] = data[name]
    return data, ref


def _expected_order(ref, sort_keys, n):
    """Stable python sort of row indices under SQL ORDER BY semantics."""
    def keyf(i):
        parts = []
        for name, asc, nulls_first in sort_keys:
            v = ref[name][i]
            null = v is None
            null_rank = (0 if nulls_first else 1) if null else \
                (1 if nulls_first else 0)
            if null:
                num = 0.0
            elif isinstance(v, str):
                num = float(sorted({x for x in ref[name] if x is not None}
                                   ).index(v))
            else:
                num = float(v)
            parts.append((null_rank, num if asc else -num))
        return tuple(parts)

    return sorted(range(n), key=keyf)


def _rows_in_order(chunk, names):
    ht = HostTable.from_chunk(chunk)
    rows = ht.to_pylist()
    idx = [f.name for f in ht.schema]
    pos = [idx.index(nm) for nm in names]
    return [tuple(r[p] for p in pos) for r in rows]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_packed_sort_matches_lexsort_property(seed):
    rng = np.random.default_rng(seed)
    n = 257 + int(rng.integers(0, 200))
    kinds = ["int", "str", "bool"]
    nk = int(rng.integers(1, 4))
    spec = [(f"k{i}", kinds[int(rng.integers(0, 3))]) for i in range(nk)]
    data, ref = _gen_columns(rng, n, spec)
    chunk = HostTable.from_pydict(data).to_chunk()
    # python bools infer as BIGINT through from_pydict: bound them like
    # the catalog stats would
    chunk = _with_int_bounds(
        chunk, {nm: (0, 39) if kind == "int" else (0, 1)
                for nm, kind in spec if kind in ("int", "bool")})

    sort_keys = tuple(
        (col(nm), bool(rng.integers(0, 2)), bool(rng.integers(0, 2)))
        for nm, _ in spec
    )
    named = [(nm, asc, nf) for (nm, _), (_, asc, nf) in zip(spec, sort_keys)]
    want = _expected_order(ref, named, n)
    names = [nm for nm, _ in spec]
    want_rows = [tuple(ref[nm][i] for nm in names) for i in want]

    # the packed path must actually engage for this all-bounded key mix
    keys = eval_keys(chunk, tuple(e for e, _, _ in sort_keys))
    assert packed_order_key(keys, sort_keys, chunk.sel_mask()) is not None

    config.set("topn_strategy", "auto")
    config.set("enable_packed_sort_keys", True)
    got_packed = _rows_in_order(sort_chunk(chunk, sort_keys), names)
    config.set("enable_packed_sort_keys", False)
    got_lex = _rows_in_order(sort_chunk(chunk, sort_keys), names)

    assert got_packed == want_rows
    assert got_lex == want_rows


@pytest.mark.parametrize("strategy", ["auto", "pallas"])
def test_threshold_topn_matches_full_sort(strategy):
    rng = np.random.default_rng(7)
    n = 5000
    data = {
        "k": [None if m else int(x) for m, x in
              zip(rng.random(n) < 0.1, rng.integers(0, 1000, n))],
        "payload": list(rng.integers(0, 10**6, n)),
    }
    chunk = HostTable.from_pydict(data).to_chunk()
    chunk = _with_int_bounds(chunk, {"k": (0, 999)})
    sort_keys = ((col("k"), False, False),)  # DESC NULLS LAST

    config.set("enable_packed_sort_keys", True)
    config.set("topn_strategy", "lexsort")
    full = _rows_in_order(sort_chunk(chunk, sort_keys, limit=37),
                          ["k", "payload"])
    config.set("topn_strategy", strategy)
    ctrs = {}
    out = sort_chunk(chunk, sort_keys, limit=37, counters=ctrs)
    got = _rows_in_order(out, ["k", "payload"])

    assert got == full
    # the threshold path SHRINKS the output capacity and reports pruning
    assert out.capacity == pad_capacity(37) < chunk.capacity
    assert int(ctrs["topn_rows_pruned"]) == n - 37


def test_topn_limit_beyond_live_rows():
    chunk = HostTable.from_pydict({"k": [3, 1, 2]}).to_chunk()
    chunk = _with_int_bounds(chunk, {"k": (1, 3)})
    out = sort_chunk(chunk, ((col("k"), True, False),), limit=2000)
    assert _rows_in_order(out, ["k"]) == [(1,), (2,), (3,)]


def _rank_catalog(rng, n=4000):
    cat = Catalog()
    cat.register("t", HostTable.from_pydict({
        "p": [int(x) for x in rng.integers(0, 23, n)],
        "v": [float(x) for x in rng.normal(size=n)],
    }))
    return cat


RANK_TOPN_Q = """
select * from (
  select p, v, rank() over (partition by p order by v desc) rk from t
) x where rk <= 5 order by p, v desc, rk limit 10000
"""


def test_window_topn_rewrite_matches_unrewritten():
    rng = np.random.default_rng(11)
    cat = _rank_catalog(rng)

    config.set("enable_window_topn", False)
    base = Session(cat).sql(RANK_TOPN_Q).rows()
    config.set("enable_window_topn", True)
    s = Session(cat)
    got = s.sql(RANK_TOPN_Q).rows()
    assert got == base
    assert len(got) >= 23 * 5  # every partition keeps its (tied) top 5

    # the rewrite fired; between the pre-sort threshold filter and the
    # in-window rank mask, the dropped rows land in the profile counters
    prof = s.last_profile
    pruned = sum(
        prof.counters.get(nm, (0,))[0]
        for nm in ("window_topn_pruned", "window_topn_prefiltered"))
    assert pruned > 0
    assert "topn=5" in s.sql("explain " + RANK_TOPN_Q)


DENSE_TOPN_Q = """
select * from (
  select p, v, dense_rank() over (partition by p order by v desc) dr from d
) x where dr <= 2 order by p, v desc limit 1000
"""


def test_window_topn_dense_rank_duplicates():
    # dense_rank counts DISTINCT order keys: with scores [10,10,9] and
    # dense_rank()<=2 the 9-row must survive — a per-partition k-th ROW
    # threshold (10) would drop it before the window ever ranks it
    cat = Catalog()
    cat.register("d", HostTable.from_pydict({
        "p": [0, 0, 0, 0, 1, 1, 1],
        "v": [10, 10, 9, 8, 7, 7, 6],
    }))
    config.set("enable_window_topn", False)
    base = Session(cat).sql(DENSE_TOPN_Q).rows()
    config.set("enable_window_topn", True)
    got = Session(cat).sql(DENSE_TOPN_Q).rows()
    assert got == base
    assert (0, 9, 2) in got and (1, 6, 2) in got


def test_window_topn_coresident_funcs_unpruned():
    # the analyzer merges every window func sharing (partition, order)
    # into one LWindow; lead() on a rank-limited node reads rows past
    # rank k, so the pre-sort prefilter must stand down (the exact
    # in-window mask still applies) and surviving rows keep the values
    # computed over the FULL partition
    rng = np.random.default_rng(5)
    cat = _rank_catalog(rng, n=800)
    q = """
    select * from (
      select p, v,
             rank() over (partition by p order by v desc) rk,
             lead(v, 1) over (partition by p order by v desc) nxt,
             sum(v) over (partition by p order by v desc) run
      from t
    ) x where rk <= 3 order by p, v desc limit 10000
    """
    config.set("enable_window_topn", False)
    base = Session(cat).sql(q).rows()
    config.set("enable_window_topn", True)
    got = Session(cat).sql(q).rows()
    assert got == base
    # lead() at the last kept rank must see the (filtered-out) rank-4 row
    assert any(r[3] is not None for r in got)


def test_window_topn_prefilter_nan_scores():
    from starrocks_tpu.ops.window import window_topn_prefilter

    nan = float("nan")
    chunk = HostTable.from_pydict({
        "p": [0, 0, 0, 0, 1, 1],
        "v": [5.0, 4.0, 3.0, nan, 1.0, nan],
    }).to_chunk()
    chunk = _with_int_bounds(chunk, {"p": (0, 1)})
    pre = window_topn_prefilter(
        chunk, (col("p"),), ((col("v"), False, False),), 2)
    assert pre is not None
    keep = np.asarray(pre[0])[:6]
    # partition 0: top-2 by v desc = {5,4}; 3 and the NaN row (the sort
    # places NaN last in either direction) fall past the threshold.
    # partition 1 has fewer than k non-NaN rows: its NaN row ranks 2 and
    # must survive, not fail a NaN-poisoned `>= kth` compare
    assert keep.tolist() == [True, True, False, False, True, True]

    # >= k NaN scores in one partition must not poison the k-th key
    # (a NaN threshold would drop the whole partition)
    c2 = HostTable.from_pydict({"p": [0, 0, 0], "v": [nan, nan, nan]}
                               ).to_chunk()
    c2 = _with_int_bounds(c2, {"p": (0, 0)})
    pre2 = window_topn_prefilter(
        c2, (col("p"),), ((col("v"), True, False),), 2)
    assert pre2 is not None
    assert np.asarray(pre2[0])[:3].all()


def test_sort_timing_counter():
    rng = np.random.default_rng(3)
    cat = _rank_catalog(rng, n=2000)
    config.set("enable_sort_timing", True)
    s = Session(cat)
    s.sql("select p, v from t order by p, v limit 50")
    prof = s.last_profile
    ms = prof.counters.get("sort_ms")
    assert ms is not None and ms[0] > 0
