"""The 13 SSB-flat queries (denormalized lineorder_flat formulation — the
reference's headline SSB benchmark, docs/en/benchmarking/SSB_Benchmarking.md)."""

FLAT_QUERIES = {
    "q1.1": """select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder_flat
        where lo_orderdate_year = 1993 and lo_discount between 1 and 3
          and lo_quantity < 25""",
    "q1.2": """select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder_flat
        where lo_orderdate_yearmonthnum = 199401
          and lo_discount between 4 and 6 and lo_quantity between 26 and 35""",
    "q1.3": """select sum(lo_extendedprice * lo_discount) as revenue
        from lineorder_flat
        where lo_orderdate_weeknuminyear = 6 and lo_orderdate_year = 1994
          and lo_discount between 5 and 7 and lo_quantity between 26 and 35""",
    "q2.1": """select sum(lo_revenue) as lo_revenue, lo_orderdate_year as year, p_brand
        from lineorder_flat
        where p_category = 'MFGR#12' and s_region = 'AMERICA'
        group by lo_orderdate_year, p_brand
        order by lo_orderdate_year, p_brand""",
    "q2.2": """select sum(lo_revenue) as lo_revenue, lo_orderdate_year as year, p_brand
        from lineorder_flat
        where p_brand >= 'MFGR#2221' and p_brand <= 'MFGR#2228' and s_region = 'ASIA'
        group by lo_orderdate_year, p_brand
        order by lo_orderdate_year, p_brand""",
    "q2.3": """select sum(lo_revenue) as lo_revenue, lo_orderdate_year as year, p_brand
        from lineorder_flat
        where p_brand = 'MFGR#2239' and s_region = 'EUROPE'
        group by lo_orderdate_year, p_brand
        order by lo_orderdate_year, p_brand""",
    "q3.1": """select c_nation, s_nation, lo_orderdate_year as year,
          sum(lo_revenue) as lo_revenue
        from lineorder_flat
        where c_region = 'ASIA' and s_region = 'ASIA'
          and lo_orderdate_year >= 1992 and lo_orderdate_year <= 1997
        group by c_nation, s_nation, lo_orderdate_year
        order by lo_orderdate_year asc, lo_revenue desc""",
    "q3.2": """select c_city, s_city, lo_orderdate_year as year,
          sum(lo_revenue) as lo_revenue
        from lineorder_flat
        where c_nation = 'UNITED STATES' and s_nation = 'UNITED STATES'
          and lo_orderdate_year >= 1992 and lo_orderdate_year <= 1997
        group by c_city, s_city, lo_orderdate_year
        order by lo_orderdate_year asc, lo_revenue desc""",
    "q3.3": """select c_city, s_city, lo_orderdate_year as year,
          sum(lo_revenue) as lo_revenue
        from lineorder_flat
        where (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
          and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
          and lo_orderdate_year >= 1992 and lo_orderdate_year <= 1997
        group by c_city, s_city, lo_orderdate_year
        order by lo_orderdate_year asc, lo_revenue desc""",
    "q3.4": """select c_city, s_city, lo_orderdate_year as year,
          sum(lo_revenue) as lo_revenue
        from lineorder_flat
        where (c_city = 'UNITED KI1' or c_city = 'UNITED KI5')
          and (s_city = 'UNITED KI1' or s_city = 'UNITED KI5')
          and lo_orderdate_yearmonth = 'Dec1997'
        group by c_city, s_city, lo_orderdate_year
        order by lo_orderdate_year asc, lo_revenue desc""",
    "q4.1": """select lo_orderdate_year as year, c_nation,
          sum(lo_revenue - lo_supplycost) as profit
        from lineorder_flat
        where c_region = 'AMERICA' and s_region = 'AMERICA'
          and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
        group by lo_orderdate_year, c_nation
        order by lo_orderdate_year, c_nation""",
    "q4.2": """select lo_orderdate_year as year, s_nation, p_category,
          sum(lo_revenue - lo_supplycost) as profit
        from lineorder_flat
        where c_region = 'AMERICA' and s_region = 'AMERICA'
          and (lo_orderdate_year = 1997 or lo_orderdate_year = 1998)
          and (p_mfgr = 'MFGR#1' or p_mfgr = 'MFGR#2')
        group by lo_orderdate_year, s_nation, p_category
        order by lo_orderdate_year, s_nation, p_category""",
    "q4.3": """select lo_orderdate_year as year, s_city, p_brand,
          sum(lo_revenue - lo_supplycost) as profit
        from lineorder_flat
        where s_nation = 'UNITED STATES'
          and (lo_orderdate_year = 1997 or lo_orderdate_year = 1998)
          and p_category = 'MFGR#14'
        group by lo_orderdate_year, s_city, p_brand
        order by lo_orderdate_year, s_city, p_brand""",
}
