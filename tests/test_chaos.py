"""Failpoint-driven chaos suite for the query lifecycle manager.

Every scenario injects a fault (raise at a named failpoint, KILL
mid-stage, deadline mid-spill, memory limit mid-join) and asserts the
lifecycle contract (runtime/lifecycle.py):

1. the query fails CLEANLY with a typed error;
2. the NEXT query on the same session returns oracle-correct results;
3. nothing leaked: admission slots back to zero, the TabletStore journal
   lock acquirable, the memory accountant's before/after snapshots
   identical, and no stray query-cache bytes.

Reference behavior: StarRocks' failpoint-scripted SQL regression suites
(be/src/base/failpoint/fail_point.h) + the kill/timeout paths of
qe/ConnectContext and the BE fragment cancellation plane.
"""

import threading
import time

import pytest

from starrocks_tpu.runtime import failpoint, lifecycle
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.failpoint import FailPointError
from starrocks_tpu.runtime.lifecycle import (
    ACCOUNTANT, REGISTRY, MemLimitExceeded, QueryCancelledError,
    QueryTimeoutError,
)
from starrocks_tpu.runtime.session import Session

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _reset_lifecycle_knobs():
    """Every scenario leaves the process exactly as it found it."""
    yield
    config.set("query_timeout_s", 0.0)
    config.set("query_mem_limit_bytes", 0)
    config.set("query_mem_soft_limit_bytes", 0)
    config.set("process_mem_limit_bytes", 0)
    config.set("batch_rows_threshold", 0)
    config.set("spill_batch_rows", 0)
    config.set("enable_query_cache", False)
    # ingest knobs exist only once starrocks_tpu.ingest imported
    for knob, dflt in (("ingest_batch_age_ms", 200),
                       ("ingest_batch_rows", 4096),
                       ("ingest_staging_limit_bytes", 64 << 20),
                       ("enable_ingest_plane", True)):
        try:
            config.set(knob, dflt)
        except KeyError:
            pass


def _mk_session(rows: int = 8) -> Session:
    s = Session()
    s.sql("create table t (a int, b int)")
    vals = ", ".join(f"({i}, {i % 3})" for i in range(1, rows + 1))
    s.sql(f"insert into t values {vals}")
    return s


def _leak_snapshot(s: Session) -> dict:
    wm = getattr(s.catalog, "workgroups", None)
    return {
        "process_bytes": ACCOUNTANT.snapshot()["process_bytes"],
        "slots": sum(wm.running.values()) if wm is not None else 0,
        "qcache_bytes": s.cache.qcache.resident_bytes,
        "registry": len(REGISTRY.snapshot()),
    }


def _assert_clean(s: Session, before: dict):
    assert _leak_snapshot(s) == before
    if s.store is not None:
        assert s.store._journal_lock.acquire(blocking=False), \
            "journal lock leaked"
        s.store._journal_lock.release()


def _probe_correct(s: Session, rows: int = 8):
    """Oracle check for the standard fixture table."""
    got = s.sql("select b, sum(a) from t group by b order by b").rows()
    exp = {}
    for i in range(1, rows + 1):
        exp[i % 3] = exp.get(i % 3, 0) + i
    assert got == sorted(exp.items())


# --- 1..5: injected raise at every executor-stage family ---------------------


@pytest.mark.parametrize("site", [
    "executor::before_run",
    "executor::before_compile",
    "executor::before_dispatch",
    "executor::fetch_results",
    "scan::chunk_to_device",
])
def test_raise_at_stage_fails_clean_and_next_query_correct(site):
    s = _mk_session()
    before = _leak_snapshot(s)
    with failpoint.scoped(site):
        with pytest.raises(FailPointError, match=site):
            s.sql("select b, sum(a) from t group by b")
    _assert_clean(s, before)
    _probe_correct(s)


# --- 6: raise inside the spill/batched loop ----------------------------------


def test_raise_mid_spill_batch_loop():
    s = _mk_session(rows=64)
    config.set("batch_rows_threshold", 16)
    before = _leak_snapshot(s)
    with failpoint.scoped("spill::batch_loop"):
        with pytest.raises(FailPointError):
            s.sql("select b, sum(a) from t group by b")
    _assert_clean(s, before)
    config.set("batch_rows_threshold", 0)
    _probe_correct(s, rows=64)


# --- 7: journal-write fault leaves the lock free and the store serving -------


def test_journal_write_fault_releases_lock(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1, 0), (2, 1)")
    before = _leak_snapshot(s)
    with failpoint.scoped("journal::write"):
        with pytest.raises(FailPointError):
            s.sql("insert into t values (3, 2)")
    _assert_clean(s, before)
    # the journal lock is free and the session immediately reusable
    s.sql("insert into t values (4, 0)")
    got = s.sql("select sum(a) from t").rows()
    assert got[0][0] in (7, 10)  # the faulted row may or may not have landed
    # whatever landed, the store must be internally consistent on replay
    s2 = Session(data_dir=str(tmp_path / "db"))
    assert s2.sql("select sum(a) from t").rows() == got


# --- 8: cache-store fault with the query cache enabled -----------------------


def test_qcache_store_fault_leaks_no_bytes():
    s = _mk_session()
    config.set("enable_query_cache", True)
    before = _leak_snapshot(s)
    with failpoint.scoped("qcache::store_result"):
        with pytest.raises(FailPointError):
            s.sql("select b, sum(a) from t group by b")
    _assert_clean(s, before)
    _probe_correct(s)


# --- 9: KILL mid-stage (cooperative cancellation) ----------------------------


def test_kill_mid_stage_unwinds_and_session_reusable():
    s = _mk_session()

    def kill_current():
        ctx = lifecycle.current()
        assert ctx is not None
        REGISTRY.cancel(ctx.qid, requester="root", admin=True)

    before = _leak_snapshot(s)
    with failpoint.scoped("executor::before_dispatch", action=kill_current):
        with pytest.raises(QueryCancelledError, match="cancelled at stage"):
            s.sql("select b, sum(a) from t group by b")
    _assert_clean(s, before)
    _probe_correct(s)


# --- 10: KILL landing after the last checkpoint is a documented no-op --------


def test_kill_race_after_last_checkpoint_is_noop():
    s = _mk_session()
    killed = []

    def late_kill():
        ctx = lifecycle.current()
        killed.append(REGISTRY.cancel(ctx.qid, requester="root", admin=True))

    # executor::result_ready sits AFTER the final checkpoint by design: a
    # kill delivered there finds a query with no checkpoints left, so the
    # query completes and the kill is a no-op (the documented race result)
    with failpoint.scoped("executor::result_ready", action=late_kill):
        got = s.sql("select sum(a) from t").rows()
    assert killed == [True]  # delivered...
    assert got == [(36,)]    # ...but the query completed normally
    # and a later kill of the finished id reports not-running
    assert s.sql("kill query 999999").endswith("KILL is a no-op")


# --- 11: deadline firing mid-spill -------------------------------------------


def test_deadline_mid_spill_raises_timeout():
    s = _mk_session(rows=64)
    config.set("batch_rows_threshold", 16)
    config.set("query_timeout_s", 0.05)
    before = _leak_snapshot(s)
    with failpoint.scoped("spill::batch_loop",
                          action=lambda: time.sleep(0.06)):
        with pytest.raises(QueryTimeoutError, match="query_timeout_s"):
            s.sql("select b, sum(a) from t group by b")
    _assert_clean(s, before)
    config.set("query_timeout_s", 0.0)
    config.set("batch_rows_threshold", 0)
    _probe_correct(s, rows=64)


# --- 12: hard memory limit mid-grace-join ------------------------------------


def test_mem_limit_mid_grace_join_names_stage():
    s = Session()
    s.sql("create table l (k int, v int)")
    s.sql("create table r (k int, w int)")
    lv = ", ".join(f"({i % 7}, {i})" for i in range(200))
    rv = ", ".join(f"({i % 7}, {i * 2})" for i in range(200))
    s.sql(f"insert into l values {lv}")
    s.sql(f"insert into r values {rv}")
    config.set("batch_rows_threshold", 50)  # force the Grace join path
    exp = s.sql("select sum(l.v + r.w) from l, r where l.k = r.k").rows()
    config.set("query_mem_limit_bytes", 1)  # any charge breaks it
    before = _leak_snapshot(s)
    with pytest.raises(MemLimitExceeded) as ei:
        s.sql("select sum(l.v + r.w) from l, r where l.k = r.k")
    assert "at stage" in str(ei.value)  # names the offending stage
    _assert_clean(s, before)
    config.set("query_mem_limit_bytes", 0)
    assert s.sql(
        "select sum(l.v + r.w) from l, r where l.k = r.k").rows() == exp


# --- 13: deadline inside the per-segment partial-agg cache path --------------


def test_deadline_in_partial_cache_admits_no_partial_entries(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table seg (a int, b int)")
    # two inserts -> two manifest segments, so the partial tier iterates
    s.sql("insert into seg values " + ", ".join(
        f"({i}, {i % 4})" for i in range(40)))
    s.sql("insert into seg values " + ", ".join(
        f"({i}, {i % 4})" for i in range(40, 80)))
    config.set("enable_query_cache", True)
    before = _leak_snapshot(s)
    config.set("query_timeout_s", 0.05)
    with failpoint.scoped("qcache::partial_segment",
                          action=lambda: time.sleep(0.06)):
        with pytest.raises(QueryTimeoutError):
            s.sql("select b, sum(a) from seg group by b")
    # deferred LRU admission: the aborted attempt left NO partial entries
    assert not [k for k in s.cache.qcache._entries if k[0] == "p"]
    _assert_clean(s, before)
    config.set("query_timeout_s", 0.0)
    got = s.sql("select b, sum(a) from seg group by b order by b").rows()
    exp = {}
    for i in range(80):
        exp[i % 4] = exp.get(i % 4, 0) + i
    assert got == sorted(exp.items())
    # and the healthy rerun DID populate the partial tier
    assert [k for k in s.cache.qcache._entries if k[0] == "p"]


# --- 14: admission-slot release is exception-safe (the leak regression) ------


def test_admission_slot_released_when_query_raises_after_admission():
    s = _mk_session()
    s.sql("create resource group rg_one with (concurrency_limit = 1)")
    s.sql("set resource_group = 'rg_one'")
    wm = s.workgroups()
    before_timeouts = wm.timeout_total
    with failpoint.scoped("executor::before_run"):
        with pytest.raises(FailPointError):
            s.sql("select sum(a) from t")
    assert wm.running.get("rg_one", 0) == 0, "admission slot leaked"
    # the single slot is immediately reusable — no queue timeout
    t0 = time.monotonic()
    _probe_correct(s)
    assert time.monotonic() - t0 < 5.0
    assert wm.timeout_total == before_timeouts
    s.sql("set resource_group = ''")
    s.sql("drop resource group rg_one")


# --- 15: KILL unblocks a query QUEUED on admission ---------------------------


def test_kill_unblocks_admission_queue():
    s = _mk_session()
    s.sql("create resource group rg_q with (concurrency_limit = 1)")
    config.set("query_queue_timeout_s", 30.0)
    wm = s.workgroups()
    hold_release = wm.admit("rg_q")  # occupy the only slot out-of-band
    try:
        errors, started = [], threading.Event()
        blocked = Session(s.catalog)
        blocked.sql("set resource_group = 'rg_q'")

        def run_blocked():
            started.set()
            try:
                blocked.sql("select count(*) from t")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        th = threading.Thread(target=run_blocked)
        th.start()
        started.wait()
        # find the queued query and kill it: it must unblock well before
        # the 30s queue timeout
        deadline = time.monotonic() + 5
        qid = None
        while qid is None and time.monotonic() < deadline:
            snap = [r for r in REGISTRY.snapshot() if "count(*)" in r[7]]
            if snap:
                qid = snap[0][0]
            time.sleep(0.01)
        assert qid is not None
        REGISTRY.cancel(qid, requester="root", admin=True)
        th.join(timeout=5)
        assert not th.is_alive()
        assert errors and isinstance(errors[0], QueryCancelledError)
    finally:
        hold_release()
        config.set("query_queue_timeout_s", 10.0)
        s.sql("drop resource group rg_q")


# --- 16: soft memory limit degrades instead of failing -----------------------


def test_soft_limit_degrades_declines_cache_admission():
    s = _mk_session()
    config.set("enable_query_cache", True)
    config.set("query_mem_soft_limit_bytes", 1)  # degrade on first charge
    got = s.sql("select b, sum(a) from t group by b order by b").rows()
    assert got  # the query SUCCEEDS (soft limit never fails a query)
    # ...but declined full-result cache admission under memory pressure
    assert not [k for k in s.cache.qcache._entries if k[0] == "r"]
    assert s.cache.qcache.resident_bytes == 0
    config.set("query_mem_soft_limit_bytes", 0)
    # without pressure the same query is admitted
    s.sql("select b, sum(a) from t group by b order by b")
    assert [k for k in s.cache.qcache._entries if k[0] == "r"]


# --- 17: KILL QUERY over the live MySQL wire ---------------------------------


def test_kill_query_over_mysql_service_interrupts_within_one_stage():
    from test_mysql_protocol import MiniMySQLClient

    from starrocks_tpu.runtime.mysql_service import MySQLServer

    s = _mk_session(rows=64)
    config.set("batch_rows_threshold", 8)  # multi-stage: 8 spill batches
    srv = MySQLServer(s, port=0).start()
    try:
        a = MiniMySQLClient("127.0.0.1", srv.port)
        b = MiniMySQLClient("127.0.0.1", srv.port)
        result = {}

        def run_victim():
            try:
                result["rows"] = a.query("select b, sum(a) from t group by b")
            except RuntimeError as e:
                result["err"] = str(e)

        # each spill batch takes >=50ms, so the query runs ~0.5s — the
        # kill from connection B lands mid-stream and takes effect at the
        # next batch boundary
        with failpoint.scoped("spill::batch_loop",
                              action=lambda: time.sleep(0.05)):
            th = threading.Thread(target=run_victim)
            th.start()
            qid = None
            deadline = time.monotonic() + 5
            while qid is None and time.monotonic() < deadline:
                cols, rows = b.query("show processlist")
                live = [r for r in rows if "group by" in r[-1]]
                if live:
                    qid = int(live[0][0])
                time.sleep(0.01)
            assert qid is not None, "victim query never appeared"
            t_kill = time.monotonic()
            b.query(f"kill query {qid}")
            th.join(timeout=10)
            assert not th.is_alive()
        assert "err" in result, f"expected kill, got {result}"
        assert "QueryCancelledError" in result["err"]
        # interrupted within ~one stage boundary, not after the full query
        assert time.monotonic() - t_kill < 2.0
        # the connection and session survive: next query is correct
        cols, rows = a.query("select sum(a) from t")
        assert rows == [(str(sum(range(1, 65))),)]
    finally:
        srv.shutdown()
        config.set("batch_rows_threshold", 0)


# --- 18: POST /api/query/{id}/cancel over the HTTP service -------------------


def test_http_cancel_endpoint():
    import http.client
    import json as _json

    from starrocks_tpu.runtime.http_service import SqlHttpServer

    s = _mk_session(rows=64)
    srv = SqlHttpServer(s).start()
    try:
        # unknown id: documented no-op
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("POST", "/api/query/999999/cancel")
        resp = _json.loads(conn.getresponse().read())
        assert resp["cancelled"] is False
        # live query (driven directly on the shared session from a worker
        # thread; the registry is process-wide so HTTP sees it)
        config.set("batch_rows_threshold", 8)
        errors = []

        def victim():
            try:
                s.sql("select b, sum(a) from t group by b")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        with failpoint.scoped("spill::batch_loop",
                              action=lambda: time.sleep(0.05)):
            th = threading.Thread(target=victim)
            th.start()
            qid = None
            deadline = time.monotonic() + 5
            while qid is None and time.monotonic() < deadline:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=10)
                conn.request("GET", "/api/queries")
                for row in _json.loads(conn.getresponse().read()):
                    if "group by" in row["sql"]:
                        qid = row["id"]
                time.sleep(0.01)
            assert qid is not None
            conn = http.client.HTTPConnection(
                "127.0.0.1", srv.port, timeout=10)
            conn.request("POST", f"/api/query/{qid}/cancel")
            assert _json.loads(conn.getresponse().read())["cancelled"] is True
            th.join(timeout=10)
        assert errors and isinstance(errors[0], QueryCancelledError)
        _probe_correct(s, rows=64)
    finally:
        srv.stop()
        config.set("batch_rows_threshold", 0)


# --- 19: the TPC-H q1 acceptance pair: timeout, then oracle-correct rerun ----


def test_tpch_q1_timeout_then_correct_rerun():
    import pandas as pd

    from starrocks_tpu.storage.catalog import tpch_catalog
    from test_tpch_q1 import q1_pandas
    from tpch_queries import QUERIES

    cat = tpch_catalog(sf=0.01)
    s = Session(cat)
    before = _leak_snapshot(s)
    s.sql("set query_timeout_s = 0.01")
    with pytest.raises(QueryTimeoutError):
        s.sql(QUERIES[1])
    _assert_clean(s, before)
    s.sql("set query_timeout_s = 0")
    got = s.sql(QUERIES[1])
    df = cat.get_table("lineitem").table.to_pandas()
    exp = q1_pandas(df, pd.Timestamp("1998-09-02"))
    rows = got.rows()
    assert len(rows) == len(exp)
    for row, (_, e) in zip(rows, exp.iterrows()):
        assert row[0] == e["l_returnflag"] and row[1] == e["l_linestatus"]
        assert abs(row[2] - e["sum_qty"]) / max(abs(e["sum_qty"]), 1) < 1e-9
        assert abs(row[3] - e["sum_base_price"]) \
            / max(abs(e["sum_base_price"]), 1) < 1e-9


# --- 20: ADMIN SET failpoint surface + information_schema accounting ---------


def test_admin_set_failpoint_times_and_introspection():
    s = _mk_session()
    s.sql("admin set failpoint 'executor::before_run' = 'enable:times=2'")
    for _ in range(2):
        with pytest.raises(FailPointError):
            s.sql("select count(*) from t")
    # times exhausted: the third run passes
    assert s.sql("select count(*) from t").rows() == [(8,)]
    rows = dict(
        (r[0], (r[1], r[3])) for r in s.sql(
            "select name, armed, times_remaining, hits "
            "from information_schema.fail_points").rows()
        if r[0] == "executor::before_run")
    armed, hits = rows["executor::before_run"]
    assert armed == 1 and hits >= 3
    s.sql("admin set failpoint 'executor::before_run' = 'disable'")
    with pytest.raises(ValueError, match="unknown failpoint action"):
        s.sql("admin set failpoint 'x' = 'frobnicate'")


# --- 21: non-admin users cannot kill other users' queries --------------------


def test_kill_permissions():
    s = _mk_session()
    s.sql("create user 'bob' identified by 'pw'")
    s.sql("grant select on t to 'bob'")
    seen = {}

    def cross_kill():
        ctx = lifecycle.current()
        try:
            REGISTRY.cancel(ctx.qid, requester="bob", admin=False)
        except PermissionError as e:
            seen["err"] = str(e)
        # owner (or admin) succeeds where the stranger failed
        seen["own"] = REGISTRY.cancel(ctx.qid, requester="root", admin=False)

    with failpoint.scoped("executor::before_dispatch", action=cross_kill):
        with pytest.raises(QueryCancelledError):
            s.sql("select b, sum(a) from t group by b")
    assert "cannot kill" in seen["err"] and seen["own"] is True
    _probe_correct(s)


# --- 22: hybrid skew-aware join — zero leaked spill partitions on unwind -----


def _mk_skew_join_session(rows: int = 400) -> Session:
    """Two join tables sized past a 50-row threshold, the build side
    carrying one heavy-hitter key (half its rows), so the hybrid executor
    runs all three lanes: broadcast, resident, and spilled partitions."""
    s = Session()
    s.sql("create table jl (k int, v int)")
    s.sql("create table jr (k int, w int)")
    # jr is the SMALLER relation (so the optimizer keeps it on the build
    # side); hot key 1 owns half of it, cold keys appear ~2x each (below
    # the 50-row-batch skew threshold), spread over several partitions
    lv = ", ".join(f"({i % 101}, {i})" for i in range(2 * rows))
    rv = ", ".join(f"({1 if i % 2 else i % 101}, {i})" for i in range(rows))
    s.sql(f"insert into jl values {lv}")
    s.sql(f"insert into jr values {rv}")
    return s


_Q_HYBRID = "select sum(jl.v + jr.w) from jl, jr where jl.k = jr.k"


def _join_counters(s: Session) -> dict:
    out = {}

    def walk(p):
        out.update({k: v for k, (v, _) in p.counters.items()})
        for c in p.children:
            walk(c)

    walk(s.last_profile)
    return out


def test_hybrid_spill_fault_leaks_no_partitions():
    from starrocks_tpu.runtime import batched

    s = _mk_skew_join_session()
    config.set("batch_rows_threshold", 50)
    exp = s.sql(_Q_HYBRID).rows()
    cs = _join_counters(s)
    assert cs.get("join_skew_keys", 0) >= 1, cs       # the lane under test
    assert cs.get("join_spilled_partitions", 0) >= 1, cs
    before = _leak_snapshot(s)
    with failpoint.scoped("hybrid::spill_partition"):
        with pytest.raises(FailPointError):
            s.sql(_Q_HYBRID)
    # the unwind released every materialized-but-unconsumed partition
    assert batched.SPILL_PARTS_LIVE.value == 0
    _assert_clean(s, before)
    assert s.sql(_Q_HYBRID).rows() == exp


def test_hybrid_kill_mid_broadcast_lane_unwinds_clean():
    from starrocks_tpu.runtime import batched

    s = _mk_skew_join_session()
    config.set("batch_rows_threshold", 50)
    exp = s.sql(_Q_HYBRID).rows()

    def kill_current():
        ctx = lifecycle.current()
        assert ctx is not None
        REGISTRY.cancel(ctx.qid, requester="root", admin=True)

    before = _leak_snapshot(s)
    with failpoint.scoped("hybrid::broadcast_lane", action=kill_current):
        with pytest.raises(QueryCancelledError, match="cancelled at stage"):
            s.sql(_Q_HYBRID)
    assert batched.SPILL_PARTS_LIVE.value == 0
    _assert_clean(s, before)
    assert s.sql(_Q_HYBRID).rows() == exp


def test_hybrid_deadline_mid_spill_partition():
    from starrocks_tpu.runtime import batched

    s = _mk_skew_join_session()
    config.set("batch_rows_threshold", 50)
    exp = s.sql(_Q_HYBRID).rows()
    config.set("query_timeout_s", 0.05)
    before = _leak_snapshot(s)
    with failpoint.scoped("hybrid::spill_partition",
                          action=lambda: time.sleep(0.06)):
        with pytest.raises(QueryTimeoutError, match="query_timeout_s"):
            s.sql(_Q_HYBRID)
    assert batched.SPILL_PARTS_LIVE.value == 0
    _assert_clean(s, before)
    config.set("query_timeout_s", 0.0)
    assert s.sql(_Q_HYBRID).rows() == exp


def test_hybrid_mem_hard_limit_names_stage_and_frees_partitions():
    from starrocks_tpu.runtime import batched

    s = _mk_skew_join_session()
    config.set("batch_rows_threshold", 50)
    exp = s.sql(_Q_HYBRID).rows()
    config.set("query_mem_limit_bytes", 1)  # any charge breaks it
    before = _leak_snapshot(s)
    with pytest.raises(MemLimitExceeded) as ei:
        s.sql(_Q_HYBRID)
    assert "at stage" in str(ei.value)
    assert batched.SPILL_PARTS_LIVE.value == 0
    _assert_clean(s, before)
    config.set("query_mem_limit_bytes", 0)
    assert s.sql(_Q_HYBRID).rows() == exp


# --- audit-log terminal records under chaos (observability plane) ------------
#
# The audit contract (runtime/audit.py): EVERY terminal state leaves
# exactly ONE record, registered at the same unwind hook that releases
# slots/bytes — so a chaos kill that leaks nothing must still be fully
# accounted for in the flight recorder.


def _audit_records_for(qid: int) -> list:
    from starrocks_tpu.runtime.audit import AUDIT

    return [r for r in AUDIT.snapshot() if r["query_id"] == qid]


def test_killed_query_leaves_exactly_one_audit_record():
    s = _mk_session()
    seen = []

    def kill_current():
        ctx = lifecycle.current()
        seen.append(ctx.qid)
        REGISTRY.cancel(ctx.qid, requester="root", admin=True)

    before = _leak_snapshot(s)
    with failpoint.scoped("executor::before_dispatch", action=kill_current):
        with pytest.raises(QueryCancelledError):
            s.sql("select b, sum(a) from t group by b")
    recs = _audit_records_for(seen[0])
    assert len(recs) == 1
    assert recs[0]["state"] == "cancelled"
    assert recs[0]["error"]  # the kill reason rides the record
    assert recs[0]["stage"]  # ... and the stage it landed in
    _assert_clean(s, before)
    _probe_correct(s)


def test_timed_out_query_leaves_exactly_one_audit_record():
    from starrocks_tpu.runtime.audit import AUDIT

    s = _mk_session(rows=64)
    config.set("batch_rows_threshold", 16)
    config.set("query_timeout_s", 0.05)
    before = _leak_snapshot(s)
    n0 = AUDIT.stats()["registered"]
    with failpoint.scoped("spill::batch_loop",
                          action=lambda: time.sleep(0.06)):
        with pytest.raises(QueryTimeoutError):
            s.sql("select b, sum(a) from t group by b")
    assert AUDIT.stats()["registered"] - n0 == 1
    rec = AUDIT.snapshot()[-1]
    assert rec["state"] == "timeout"
    assert "query_timeout_s" in rec["error"]
    assert rec["ms"] >= 50
    _assert_clean(s, before)
    config.set("query_timeout_s", 0.0)
    config.set("batch_rows_threshold", 0)
    _probe_correct(s, rows=64)


def test_failpoint_failed_query_leaves_exactly_one_audit_record():
    s = _mk_session()
    seen = []
    before = _leak_snapshot(s)

    def note_qid():
        seen.append(lifecycle.current().qid)
        raise FailPointError("executor::fetch_results (chaos)")

    with failpoint.scoped("executor::fetch_results", action=note_qid):
        with pytest.raises(FailPointError):
            s.sql("select b, sum(a) from t group by b")
    recs = _audit_records_for(seen[0])
    assert len(recs) == 1
    assert recs[0]["state"] == "error"
    assert recs[0]["stage"]  # terminal stage attributed (unwind-dependent)
    _assert_clean(s, before)
    _probe_correct(s)


# --- ingest plane: faults at stage/commit/label-journal ----------------------


def _mk_ingest(s=None):
    """PK fixture table + the catalog-attached ingest plane, micro-batch
    age tightened so single loads commit promptly."""
    s = s or Session()
    s.sql("create table ti (k int, v int, primary key (k))")
    plane = s.ingest_plane()
    config.set("ingest_batch_age_ms", 5)
    return s, plane


def _ingest_leaks(s, plane) -> dict:
    d = _leak_snapshot(s)
    d["ingest_staged"] = plane.stats()["staged_bytes"]
    return d


def test_ingest_commit_fault_fails_whole_batch_atomically():
    """A fault before the append fails the WHOLE batch: no partial rows
    become visible, nothing stays staged, and a retry with the SAME
    label commits exactly once (not a replay — the label never landed)."""
    from starrocks_tpu.ingest import IngestError

    s, plane = _mk_ingest()
    plane.load(s, "ti", [{"k": 1, "v": 1}], label="seed")
    before = _ingest_leaks(s, plane)
    # the committer re-raises the raw fault (so kill/timeout keep their
    # typed classification); waiters in the same batch get IngestError
    with failpoint.scoped("ingest::commit"):
        with pytest.raises((IngestError, FailPointError)):
            plane.load(s, "ti", [{"k": 2, "v": 2}, {"k": 3, "v": 3}],
                       label="L")
    assert s.sql("select count(*) from ti").rows() == [(1,)]
    assert _ingest_leaks(s, plane) == before
    r = plane.load(s, "ti", [{"k": 2, "v": 2}, {"k": 3, "v": 3}],
                   label="L")
    assert "replayed" not in r
    assert s.sql("select count(*) from ti").rows() == [(3,)]


def test_ingest_label_journal_fault_retry_is_idempotent(tmp_path):
    """A fault AFTER the append but BEFORE the label journal is the
    at-least-once window: the retry re-upserts the same keys (PK delta
    path), so the net effect is exactly-once — and the label then
    replays as a durable no-op, including across a restart."""
    from starrocks_tpu.ingest import IngestError

    s = Session(data_dir=str(tmp_path / "db"))
    s, plane = _mk_ingest(s)
    before = _ingest_leaks(s, plane)
    with failpoint.scoped("ingest::label_journal"):
        with pytest.raises((IngestError, FailPointError)):
            plane.load(s, "ti", [{"k": 1, "v": 1}], label="L1")
    assert _ingest_leaks(s, plane) == before
    r = plane.load(s, "ti", [{"k": 1, "v": 1}], label="L1")
    assert "replayed" not in r  # the faulted attempt never journaled it
    assert s.sql("select k, v from ti").rows() == [(1, 1)]
    r2 = plane.load(s, "ti", [{"k": 1, "v": 9}], label="L1")
    assert r2["replayed"] is True
    assert s.sql("select k, v from ti").rows() == [(1, 1)]
    # restart: journal tail replays the ledger; still a durable no-op
    s2 = Session(data_dir=str(tmp_path / "db"))
    config.set("ingest_batch_age_ms", 5)
    r3 = s2.ingest_plane().load(s2, "ti", [{"k": 1, "v": 9}], label="L1")
    assert r3["replayed"] is True
    assert s2.sql("select k, v from ti").rows() == [(1, 1)]


def test_ingest_kill_while_staged_unwinds_clean():
    """KILL lands while the load waits for its micro-batch: the staged
    rows unstage (no leak), nothing commits, and the load leaves exactly
    one audit record in state 'cancelled' with stmt_class load."""
    s, plane = _mk_ingest()
    config.set("ingest_batch_age_ms", 60_000)
    config.set("ingest_batch_rows", 1_000_000)
    before = _ingest_leaks(s, plane)
    qids = []

    def note():
        qids.append(lifecycle.current().qid)

    def killer():
        time.sleep(0.15)
        REGISTRY.cancel(qids[0], requester="root", admin=True)

    t = threading.Thread(target=killer, daemon=True)
    with failpoint.scoped("ingest::stage", action=note):
        t.start()
        with pytest.raises(QueryCancelledError):
            plane.load(s, "ti", [{"k": 5, "v": 5}], label="K")
    t.join()
    assert _ingest_leaks(s, plane) == before
    assert s.sql("select count(*) from ti").rows() == [(0,)]
    recs = _audit_records_for(qids[0])
    assert len(recs) == 1
    assert recs[0]["state"] == "cancelled"


def test_ingest_backpressure_rejects_before_staging():
    """Over-budget staging rejects the load BEFORE anything stages
    (zero leak) and emits the ingest_backpressure event; after the
    budget is restored the SAME label loads normally."""
    from starrocks_tpu.ingest import IngestBackpressure
    from starrocks_tpu.runtime.events import EVENTS

    s, plane = _mk_ingest()
    config.set("ingest_staging_limit_bytes", 1)
    before = _ingest_leaks(s, plane)
    n0 = EVENTS.stats().get("ingest_backpressure", 0)
    with pytest.raises(IngestBackpressure):
        plane.load(s, "ti", [{"k": 1, "v": 1}], label="B")
    assert EVENTS.stats().get("ingest_backpressure", 0) == n0 + 1
    assert _ingest_leaks(s, plane) == before
    assert s.sql("select count(*) from ti").rows() == [(0,)]
    config.set("ingest_staging_limit_bytes", 64 << 20)
    r = plane.load(s, "ti", [{"k": 1, "v": 1}], label="B")
    assert "replayed" not in r
    assert s.sql("select count(*) from ti").rows() == [(1,)]


def test_ingest_group_commit_audits_once_per_load():
    """Loads folded into ONE micro-batch commit still audit once EACH
    (each has its own query_scope); the shared commit is visible in the
    matching commit_seq on their receipts."""
    from starrocks_tpu.runtime.audit import AUDIT

    s, plane = _mk_ingest()
    config.set("ingest_batch_age_ms", 150)
    config.set("ingest_batch_rows", 1_000_000)
    AUDIT.flush()
    n0 = AUDIT.stats()["registered"]
    out = {}

    def load(i):
        out[i] = plane.load(s, "ti", [{"k": i, "v": i}], label=f"g{i}")

    threads = [threading.Thread(target=load, args=(i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    AUDIT.flush()
    assert AUDIT.stats()["registered"] - n0 == 3
    assert len({r["commit_seq"] for r in out.values()}) == 1  # one batch
    assert s.sql("select count(*) from ti").rows() == [(3,)]
    assert plane.stats()["staged_bytes"] == 0


def test_ingest_poller_fault_surfaces_on_job_and_loop_survives(tmp_path):
    """A fault at ingest::poll fails that tick, journals an
    ingest_job_error event, and the NEXT tick (fault disarmed) loads the
    file — the poll loop never dies with its job."""
    import json as _json

    from starrocks_tpu.runtime.events import EVENTS

    s = Session(data_dir=str(tmp_path / "db"))
    s, plane = _mk_ingest(s)
    config.set("ingest_poll_interval_s", 0.05)
    src = tmp_path / "in.csv"
    src.write_text("1,10\n2,20\n")
    n0 = EVENTS.stats().get("ingest_job_error", 0)
    spec = _json.dumps({"table": "ti", "path": str(src)})
    with failpoint.scoped("ingest::poll", times=2):
        s.sql(f"admin set ingest_job 'j' = '{spec}'")
        deadline = time.monotonic() + 5
        while (EVENTS.stats().get("ingest_job_error", 0) <= n0
               and time.monotonic() < deadline):
            time.sleep(0.02)
    assert EVENTS.stats().get("ingest_job_error", 0) > n0
    deadline = time.monotonic() + 5
    while (s.sql("select count(*) from ti").rows() != [(2,)]
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert s.sql("select count(*) from ti").rows() == [(2,)]
    s.sql("admin set ingest_job 'j' = 'drop'")
    assert plane.poller.stats() == {"jobs": 0, "running": False}
