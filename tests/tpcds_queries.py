"""TPC-DS query texts for the differential suite (tests/test_tpcds_suite.py).

Shapes follow the official qualification queries (the reference runs all 99:
docs/en/benchmarking/TPC_DS_Benchmark.md); literals are adjusted to this
repo's synthetic datagen value domains (storage/datagen/tpcds.py), and a few
columns absent from the generated schema subset are substituted with
same-typed siblings (noted per query). Query numbers match the spec.
"""

QUERIES = {}

QUERIES["q3"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 7
  and dt.d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
"""

QUERIES["q7"] = """
select i_item_id,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) itemrevenue,
       sum(ws_ext_sales_price) * 100 /
         sum(sum(ws_ext_sales_price)) over (partition by i_class)
         revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_year = 1999 and d_moy in (2, 3)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

QUERIES["q15"] = """
select ca_zip, sum(cs_sales_price) total
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 2) in ('10', '22', '34', '85')
       or ca_state in ('CA', 'GA')
       or cs_sales_price > 90)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
"""

QUERIES["q19"] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11 and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ss_store_sk = s_store_sk
  and ca_city <> s_city
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, brand_id, i_manufact_id
limit 100
"""

QUERIES["q21"] = """
select w_warehouse_name, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) inv_before,
       sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) inv_after
from inventory, warehouse, item, date_dim
where i_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk
  and inv_date_sk = d_date_sk
  and i_current_price between 10 and 60
  and d_date between date '2000-02-10' and date '2000-04-10'
group by w_warehouse_name, i_item_id
having sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) > 0
   and sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 3 >=
       sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 2
   and sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 3 >=
       sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 2
order by w_warehouse_name, i_item_id
limit 100
"""

QUERIES["q22"] = """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 24 and 35
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 10000
"""

QUERIES["q26"] = """
select i_item_id,
       avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'F' and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 10000
"""

QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (
         partition by grouping(i_category) + grouping(i_class),
                      case when grouping(i_class) = 1
                           then i_category end
         order by sum(ss_net_profit) / sum(ss_ext_sales_price) asc
       ) rank_within_parent
from store_sales, date_dim, item, store
where d_year = 2001
  and d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state in ('TN', 'CA', 'NY', 'TX')
group by rollup(i_category, i_class)
order by lochierarchy desc, i_category, i_class, rank_within_parent
limit 10000
"""

QUERIES["q42"] = """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) s
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11 and dt.d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
"""

QUERIES["q43"] = """
select s_store_name, s_store_id,
  sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
  sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
  sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
  sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
  sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
  sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
  sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_gmt_offset = -5
  and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
limit 100
"""

QUERIES["q52"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11 and dt.d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
"""

QUERIES["q53"] = """
select * from (
  select i_manufact_id,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_manufact_id) avg_quarterly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq in (24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35)
    and i_category in ('Books', 'Children', 'Electronics')
  group by i_manufact_id, d_qoy
) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""

QUERIES["q55"] = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
"""

QUERIES["q62"] = """
select w_warehouse_name, sm_type, web_name,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30) then 1 else 0 end)
    as d30,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
            and (ws_ship_date_sk - ws_sold_date_sk <= 60) then 1 else 0 end)
    as d60,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
            and (ws_ship_date_sk - ws_sold_date_sk <= 90) then 1 else 0 end)
    as d90,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90) then 1 else 0 end)
    as d120
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 24 and 35
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by w_warehouse_name, sm_type, web_name
limit 100
"""

QUERIES["q89"] = """
select * from (
  select i_category, i_class, i_brand, s_store_name, s_city, d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (
           partition by i_category, i_brand, s_store_name, s_city
         ) avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and ((i_category in ('Books', 'Electronics', 'Sports')
          and i_class in ('class01', 'class03', 'class05'))
      or (i_category in ('Men', 'Jewelry', 'Women')
          and i_class in ('class02', 'class04', 'class06')))
  group by i_category, i_class, i_brand, s_store_name, s_city, d_moy
) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name
limit 10000
"""

QUERIES["q96"] = """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 7
  and store.s_store_name = 'store a'
order by count(*)
limit 100
"""

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100 /
         sum(sum(ss_ext_sales_price)) over (partition by i_class)
         revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_year = 1999 and d_moy in (2, 3)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

QUERIES["q16"] = """
select count(distinct cs_order_number) order_count,
       sum(cs_ext_list_price) total_shipping_cost,
       sum(cs_net_profit) total_net_profit
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2002-02-01' and date '2002-04-02'
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_bill_addr_sk = ca_address_sk
  and ca_state = 'GA'
  and cs1.cs_call_center_sk = cc_call_center_sk
  and exists (select * from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select * from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
order by count(distinct cs_order_number)
limit 100
"""

QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) itemrevenue,
       sum(cs_ext_sales_price) * 100 /
         sum(sum(cs_ext_sales_price)) over (partition by i_class)
         revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_year = 1999 and d_moy in (2, 3)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

QUERIES["q25"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) store_sales_profit,
       sum(sr_net_loss) store_returns_loss,
       sum(cs_net_profit) catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4 and d1.d_year = 2000
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10 and d2.d_year = 2000
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10 and d3.d_year = 2000
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

QUERIES["q32"] = """
select sum(cs_ext_discount_amt) excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 7
  and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (
    select 1.3 * avg(cs_ext_discount_amt)
    from catalog_sales, date_dim
    where cs_item_sk = i_item_sk
      and d_date between date '2000-01-27' and date '2000-04-26'
      and d_date_sk = cs_sold_date_sk)
limit 100
"""

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 20 and 50
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and i_manufact_id in (1, 2, 3, 4, 5, 6, 7, 8)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q38"] = """
select count(*) cnt from (
  select distinct c_last_name, c_first_name, d_date
  from store_sales, date_dim, customer
  where store_sales.ss_sold_date_sk = date_dim.d_date_sk
    and store_sales.ss_customer_sk = customer.c_customer_sk
    and d_month_seq between 24 and 35
  intersect
  select distinct c_last_name, c_first_name, d_date
  from catalog_sales, date_dim, customer
  where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    and d_month_seq between 24 and 35
  intersect
  select distinct c_last_name, c_first_name, d_date
  from web_sales, date_dim, customer
  where web_sales.ws_sold_date_sk = date_dim.d_date_sk
    and web_sales.ws_bill_customer_sk = customer.c_customer_sk
    and d_month_seq between 24 and 35
) hot_cust
limit 100
"""

QUERIES["q45"] = """
select ca_zip, ca_city, sum(ws_sales_price) total
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and (substr(ca_zip, 1, 5) in
         ('85669', '86197', '88274', '83405', '86475',
          '85392', '85460', '80348', '81792')
       or i_item_id in (select i_item_id from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23)))
  and ws_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
"""

QUERIES["q50"] = """
select s_store_name, s_store_id, s_state,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30)
           then 1 else 0 end) d30,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30)
            and (sr_returned_date_sk - ss_sold_date_sk <= 60)
           then 1 else 0 end) d60,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60)
            and (sr_returned_date_sk - ss_sold_date_sk <= 90)
           then 1 else 0 end) d90,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90)
           then 1 else 0 end) d120
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number
  and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk
  and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk
  and ss_store_sk = s_store_sk
group by s_store_name, s_store_id, s_state
order by s_store_name, s_store_id, s_state
limit 100
"""

QUERIES["q61"] = """
select promotions, total, promotions / total * 100 ratio
from (select sum(ss_ext_sales_price) promotions
      from store_sales, store, promotion, date_dim, customer,
           customer_address, item
      where ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and ss_promo_sk = p_promo_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk
        and ss_item_sk = i_item_sk
        and ca_gmt_offset = -5
        and i_category = 'Jewelry'
        and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
             or p_channel_tv = 'Y')
        and s_gmt_offset = -5
        and d_year = 1998 and d_moy = 11) promotional_sales,
     (select sum(ss_ext_sales_price) total
      from store_sales, store, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk
        and ss_item_sk = i_item_sk
        and ca_gmt_offset = -5
        and i_category = 'Jewelry'
        and s_gmt_offset = -5
        and d_year = 1998 and d_moy = 11) all_sales
order by promotions, total
limit 100
"""

QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk and d_month_seq between 24 and 35
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_month_seq between 24 and 35
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc
limit 100
"""

QUERIES["q68"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_dep_count = 4
             or household_demographics.hd_vehicle_count = 3)
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_city in ('Midway', 'Fairview')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

QUERIES["q69"] = """
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('KS', 'GA', 'NY')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2001 and d_moy between 4 and 6)
  and not exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
  and not exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_bill_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
"""

QUERIES["q79"] = """
select c_last_name, c_first_name, substr(s_city, 1, 30) city30,
       ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = 6
             or household_demographics.hd_vehicle_count > 2)
        and date_dim.d_dow = 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city30, profit, ss_ticket_number
limit 100
"""

QUERIES["q82"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 30 and 60
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_manufact_id in (1, 2, 3, 4, 5, 6, 7, 8)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q87"] = """
select count(*) cnt from (
  (select distinct c_last_name, c_first_name, d_date
   from store_sales, date_dim, customer
   where store_sales.ss_sold_date_sk = date_dim.d_date_sk
     and store_sales.ss_customer_sk = customer.c_customer_sk
     and d_month_seq between 24 and 35)
  except
  (select distinct c_last_name, c_first_name, d_date
   from catalog_sales, date_dim, customer
   where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
     and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
     and d_month_seq between 24 and 35)
  except
  (select distinct c_last_name, c_first_name, d_date
   from web_sales, date_dim, customer
   where web_sales.ws_sold_date_sk = date_dim.d_date_sk
     and web_sales.ws_bill_customer_sk = customer.c_customer_sk
     and d_month_seq between 24 and 35)
) cool_cust
"""

QUERIES["q88"] = """
select *
from (select count(*) h8_30_to_9
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 8 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s1,
     (select count(*) h9_to_9_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s2,
     (select count(*) h9_30_to_10
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s3,
     (select count(*) h10_to_10_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 10 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s4
"""

QUERIES["q92"] = """
select sum(ws_ext_discount_amt) excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 7
  and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (
    select 1.3 * avg(ws_ext_discount_amt)
    from web_sales, date_dim
    where ws_item_sk = i_item_sk
      and d_date between date '2000-01-27' and date '2000-04-26'
      and d_date_sk = ws_sold_date_sk)
order by sum(ws_ext_discount_amt)
limit 100
"""

QUERIES["q94"] = """
select count(distinct ws_order_number) order_count,
       sum(ws_ext_list_price) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_bill_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'pri0'
  and exists (select * from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select * from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
order by count(distinct ws_order_number)
limit 100
"""

QUERIES["q99"] = """
select substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
           then 1 else 0 end) d30,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
            and (cs_ship_date_sk - cs_sold_date_sk <= 60)
           then 1 else 0 end) d60,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
            and (cs_ship_date_sk - cs_sold_date_sk <= 90)
           then 1 else 0 end) d90,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
           then 1 else 0 end) d120
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 24 and 35
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
"""
