"""TPC-DS query texts for the differential suite (tests/test_tpcds_suite.py).

Shapes follow the official qualification queries (the reference runs all 99:
docs/en/benchmarking/TPC_DS_Benchmark.md); literals are adjusted to this
repo's synthetic datagen value domains (storage/datagen/tpcds.py), and a few
columns absent from the generated schema subset are substituted with
same-typed siblings (noted per query). Query numbers match the spec.
"""

QUERIES = {}

QUERIES["q3"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 7
  and dt.d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
"""

QUERIES["q7"] = """
select i_item_id,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) itemrevenue,
       sum(ws_ext_sales_price) * 100 /
         sum(sum(ws_ext_sales_price)) over (partition by i_class)
         revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_year = 1999 and d_moy in (2, 3)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

QUERIES["q15"] = """
select ca_zip, sum(cs_sales_price) total
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 2) in ('10', '22', '34', '85')
       or ca_state in ('CA', 'GA')
       or cs_sales_price > 90)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
"""

QUERIES["q19"] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11 and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ss_store_sk = s_store_sk
  and ca_city <> s_city
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, brand_id, i_manufact_id
limit 100
"""

QUERIES["q21"] = """
select w_warehouse_name, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) inv_before,
       sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) inv_after
from inventory, warehouse, item, date_dim
where i_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk
  and inv_date_sk = d_date_sk
  and i_current_price between 10 and 60
  and d_date between date '2000-02-10' and date '2000-04-10'
group by w_warehouse_name, i_item_id
having sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) > 0
   and sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 3 >=
       sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 2
   and sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 3 >=
       sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 2
order by w_warehouse_name, i_item_id
limit 100
"""

QUERIES["q22"] = """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 24 and 35
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 10000
"""

QUERIES["q26"] = """
select i_item_id,
       avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'F' and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 10000
"""

QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (
         partition by grouping(i_category) + grouping(i_class),
                      case when grouping(i_class) = 1
                           then i_category end
         order by sum(ss_net_profit) / sum(ss_ext_sales_price) asc
       ) rank_within_parent
from store_sales, date_dim, item, store
where d_year = 2001
  and d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state in ('TN', 'CA', 'NY', 'TX')
group by rollup(i_category, i_class)
order by lochierarchy desc, i_category, i_class, rank_within_parent
limit 10000
"""

QUERIES["q42"] = """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) s
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11 and dt.d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
"""

QUERIES["q43"] = """
select s_store_name, s_store_id,
  sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
  sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
  sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
  sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
  sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
  sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
  sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_gmt_offset = -5
  and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
limit 100
"""

QUERIES["q52"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11 and dt.d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
"""

QUERIES["q53"] = """
select * from (
  select i_manufact_id,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_manufact_id) avg_quarterly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq in (24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35)
    and i_category in ('Books', 'Children', 'Electronics')
  group by i_manufact_id, d_qoy
) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""

QUERIES["q55"] = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
"""

QUERIES["q62"] = """
select w_warehouse_name, sm_type, web_name,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30) then 1 else 0 end)
    as d30,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
            and (ws_ship_date_sk - ws_sold_date_sk <= 60) then 1 else 0 end)
    as d60,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
            and (ws_ship_date_sk - ws_sold_date_sk <= 90) then 1 else 0 end)
    as d90,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90) then 1 else 0 end)
    as d120
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 24 and 35
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by w_warehouse_name, sm_type, web_name
limit 100
"""

QUERIES["q89"] = """
select * from (
  select i_category, i_class, i_brand, s_store_name, s_city, d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (
           partition by i_category, i_brand, s_store_name, s_city
         ) avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year = 1999
    and ((i_category in ('Books', 'Electronics', 'Sports')
          and i_class in ('class01', 'class03', 'class05'))
      or (i_category in ('Men', 'Jewelry', 'Women')
          and i_class in ('class02', 'class04', 'class06')))
  group by i_category, i_class, i_brand, s_store_name, s_city, d_moy
) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name
limit 10000
"""

QUERIES["q96"] = """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 7
  and store.s_store_name = 'store a'
order by count(*)
limit 100
"""

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100 /
         sum(sum(ss_ext_sales_price)) over (partition by i_class)
         revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_year = 1999 and d_moy in (2, 3)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

QUERIES["q16"] = """
select count(distinct cs_order_number) order_count,
       sum(cs_ext_list_price) total_shipping_cost,
       sum(cs_net_profit) total_net_profit
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2002-02-01' and date '2002-04-02'
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_bill_addr_sk = ca_address_sk
  and ca_state = 'GA'
  and cs1.cs_call_center_sk = cc_call_center_sk
  and exists (select * from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select * from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
order by count(distinct cs_order_number)
limit 100
"""

QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) itemrevenue,
       sum(cs_ext_sales_price) * 100 /
         sum(sum(cs_ext_sales_price)) over (partition by i_class)
         revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_year = 1999 and d_moy in (2, 3)
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

QUERIES["q25"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) store_sales_profit,
       sum(sr_net_loss) store_returns_loss,
       sum(cs_net_profit) catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4 and d1.d_year = 2000
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10 and d2.d_year = 2000
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10 and d3.d_year = 2000
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

QUERIES["q32"] = """
select sum(cs_ext_discount_amt) excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 7
  and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (
    select 1.3 * avg(cs_ext_discount_amt)
    from catalog_sales, date_dim
    where cs_item_sk = i_item_sk
      and d_date between date '2000-01-27' and date '2000-04-26'
      and d_date_sk = cs_sold_date_sk)
limit 100
"""

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 20 and 50
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and i_manufact_id in (1, 2, 3, 4, 5, 6, 7, 8)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q38"] = """
select count(*) cnt from (
  select distinct c_last_name, c_first_name, d_date
  from store_sales, date_dim, customer
  where store_sales.ss_sold_date_sk = date_dim.d_date_sk
    and store_sales.ss_customer_sk = customer.c_customer_sk
    and d_month_seq between 24 and 35
  intersect
  select distinct c_last_name, c_first_name, d_date
  from catalog_sales, date_dim, customer
  where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    and d_month_seq between 24 and 35
  intersect
  select distinct c_last_name, c_first_name, d_date
  from web_sales, date_dim, customer
  where web_sales.ws_sold_date_sk = date_dim.d_date_sk
    and web_sales.ws_bill_customer_sk = customer.c_customer_sk
    and d_month_seq between 24 and 35
) hot_cust
limit 100
"""

QUERIES["q45"] = """
select ca_zip, ca_city, sum(ws_sales_price) total
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and (substr(ca_zip, 1, 5) in
         ('85669', '86197', '88274', '83405', '86475',
          '85392', '85460', '80348', '81792')
       or i_item_id in (select i_item_id from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23)))
  and ws_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
"""

QUERIES["q50"] = """
select s_store_name, s_store_id, s_state,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30)
           then 1 else 0 end) d30,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30)
            and (sr_returned_date_sk - ss_sold_date_sk <= 60)
           then 1 else 0 end) d60,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60)
            and (sr_returned_date_sk - ss_sold_date_sk <= 90)
           then 1 else 0 end) d90,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90)
           then 1 else 0 end) d120
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number
  and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk
  and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk
  and ss_store_sk = s_store_sk
group by s_store_name, s_store_id, s_state
order by s_store_name, s_store_id, s_state
limit 100
"""

QUERIES["q61"] = """
select promotions, total, promotions / total * 100 ratio
from (select sum(ss_ext_sales_price) promotions
      from store_sales, store, promotion, date_dim, customer,
           customer_address, item
      where ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and ss_promo_sk = p_promo_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk
        and ss_item_sk = i_item_sk
        and ca_gmt_offset = -5
        and i_category = 'Jewelry'
        and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
             or p_channel_tv = 'Y')
        and s_gmt_offset = -5
        and d_year = 1998 and d_moy = 11) promotional_sales,
     (select sum(ss_ext_sales_price) total
      from store_sales, store, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk
        and ss_item_sk = i_item_sk
        and ca_gmt_offset = -5
        and i_category = 'Jewelry'
        and s_gmt_offset = -5
        and d_year = 1998 and d_moy = 11) all_sales
order by promotions, total
limit 100
"""

QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk and d_month_seq between 24 and 35
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_month_seq between 24 and 35
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc
limit 100
"""

QUERIES["q68"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_dep_count = 4
             or household_demographics.hd_vehicle_count = 3)
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_city in ('Midway', 'Fairview')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

QUERIES["q69"] = """
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('KS', 'GA', 'NY')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk
                and d_year = 2001 and d_moy between 4 and 6)
  and not exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
  and not exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_bill_customer_sk
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
"""

QUERIES["q79"] = """
select c_last_name, c_first_name, substr(s_city, 1, 30) city30,
       ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = 6
             or household_demographics.hd_vehicle_count > 2)
        and date_dim.d_dow = 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city30, profit, ss_ticket_number
limit 100
"""

QUERIES["q82"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 30 and 60
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_manufact_id in (1, 2, 3, 4, 5, 6, 7, 8)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q87"] = """
select count(*) cnt from (
  (select distinct c_last_name, c_first_name, d_date
   from store_sales, date_dim, customer
   where store_sales.ss_sold_date_sk = date_dim.d_date_sk
     and store_sales.ss_customer_sk = customer.c_customer_sk
     and d_month_seq between 24 and 35)
  except
  (select distinct c_last_name, c_first_name, d_date
   from catalog_sales, date_dim, customer
   where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
     and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
     and d_month_seq between 24 and 35)
  except
  (select distinct c_last_name, c_first_name, d_date
   from web_sales, date_dim, customer
   where web_sales.ws_sold_date_sk = date_dim.d_date_sk
     and web_sales.ws_bill_customer_sk = customer.c_customer_sk
     and d_month_seq between 24 and 35)
) cool_cust
"""

QUERIES["q88"] = """
select *
from (select count(*) h8_30_to_9
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 8 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s1,
     (select count(*) h9_to_9_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s2,
     (select count(*) h9_30_to_10
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 9 and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s3,
     (select count(*) h10_to_10_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk
        and time_dim.t_hour = 10 and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 6)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 4)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 2))
        and store.s_store_name = 'store a') s4
"""

QUERIES["q92"] = """
select sum(ws_ext_discount_amt) excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 7
  and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (
    select 1.3 * avg(ws_ext_discount_amt)
    from web_sales, date_dim
    where ws_item_sk = i_item_sk
      and d_date between date '2000-01-27' and date '2000-04-26'
      and d_date_sk = ws_sold_date_sk)
order by sum(ws_ext_discount_amt)
limit 100
"""

QUERIES["q94"] = """
select count(distinct ws_order_number) order_count,
       sum(ws_ext_list_price) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_bill_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk
  and web_company_name = 'pri0'
  and exists (select * from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select * from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
order by count(distinct ws_order_number)
limit 100
"""

QUERIES["q99"] = """
select substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
           then 1 else 0 end) d30,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
            and (cs_ship_date_sk - cs_sold_date_sk <= 60)
           then 1 else 0 end) d60,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
            and (cs_ship_date_sk - cs_sold_date_sk <= 90)
           then 1 else 0 end) d90,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
           then 1 else 0 end) d120
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 24 and 35
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
"""

# --- round-3 expansion: correlated subqueries, EXISTS combos, band ORs ------

QUERIES["q1"] = """
WITH customer_total_return AS (
  SELECT sr_customer_sk AS ctr_customer_sk, sr_store_sk AS ctr_store_sk,
         sum(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
"""

QUERIES["q6"] = """
SELECT a.ca_state AS state, count(*) AS cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq = (SELECT DISTINCT d_month_seq FROM date_dim
                       WHERE d_year = 2001 AND d_moy = 1)
  AND i.i_current_price > 1.2 * (SELECT avg(j.i_current_price) FROM item j
                                 WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING count(*) >= 2
ORDER BY cnt, state
LIMIT 100
"""

QUERIES["q9"] = """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 5000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END AS bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 5000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END AS bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 5000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END AS bucket3
FROM reason
WHERE r_reason_sk = 1
"""

QUERIES["q10"] = """
SELECT cd_gender, cd_marital_status, cd_education_status, count(*) AS cnt1,
       cd_purchase_estimate, count(*) AS cnt2
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Bronx County', 'Barrow County', 'Daviess County')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2002
                AND d_moy BETWEEN 1 AND 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk AND d_year = 2002
                 AND d_moy BETWEEN 1 AND 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_bill_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2002
                    AND d_moy BETWEEN 1 AND 4))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
LIMIT 100
"""

QUERIES["q13"] = """
SELECT avg(ss_quantity) AS a1, avg(ss_ext_sales_price) AS a2,
       avg(ss_ext_wholesale_cost) AS a3, sum(ss_ext_wholesale_cost) AS s1
FROM store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
  AND ss_addr_sk = ca_address_sk AND ca_country = 'United States'
  AND ((cd_marital_status = 'M' AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 50.00 AND 100.00 AND hd_dep_count = 3)
       OR (cd_marital_status = 'S' AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 10.00 AND 60.00 AND hd_dep_count = 1)
       OR (cd_marital_status = 'W' AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 30.00 AND 80.00 AND hd_dep_count = 1))
  AND ((ca_state IN ('TX', 'OH', 'TN') AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ca_state IN ('AL', 'KS', 'MI') AND ss_net_profit BETWEEN 50 AND 3000)
       OR (ca_state IN ('CA', 'GA', 'NY') AND ss_net_profit BETWEEN 0 AND 25000))
"""

QUERIES["q28"] = """
SELECT b1.lp AS b1_lp, b1.cnt AS b1_cnt, b1.cntd AS b1_cntd,
       b2.lp AS b2_lp, b2.cnt AS b2_cnt, b2.cntd AS b2_cntd,
       b3.lp AS b3_lp, b3.cnt AS b3_cnt, b3.cntd AS b3_cntd
FROM (SELECT avg(ss_list_price) lp, count(ss_list_price) cnt,
             count(DISTINCT ss_list_price) cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 0 AND 5
        AND (ss_list_price BETWEEN 10 AND 50
             OR ss_coupon_amt BETWEEN 0 AND 200
             OR ss_wholesale_cost BETWEEN 10 AND 30)) b1,
     (SELECT avg(ss_list_price) lp, count(ss_list_price) cnt,
             count(DISTINCT ss_list_price) cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 6 AND 10
        AND (ss_list_price BETWEEN 20 AND 60
             OR ss_coupon_amt BETWEEN 0 AND 300
             OR ss_wholesale_cost BETWEEN 20 AND 40)) b2,
     (SELECT avg(ss_list_price) lp, count(ss_list_price) cnt,
             count(DISTINCT ss_list_price) cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 11 AND 15
        AND (ss_list_price BETWEEN 30 AND 70
             OR ss_coupon_amt BETWEEN 0 AND 400
             OR ss_wholesale_cost BETWEEN 30 AND 50)) b3
"""

QUERIES["q29"] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) AS store_sales_quantity,
       sum(sr_return_quantity) AS store_returns_quantity,
       sum(cs_quantity) AS catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
WHERE d1.d_year = 1999
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id
LIMIT 100
"""

QUERIES["q34"] = """
SELECT c_last_name, c_first_name, c_customer_id, cnt
FROM (SELECT ss_customer_sk, count(*) AS cnt
      FROM store_sales, store, household_demographics
      WHERE ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_buy_potential = '>10000' OR hd_buy_potential = 'Unknown')
        AND hd_vehicle_count > 0
        AND s_county IN ('Richland County', 'Daviess County',
                         'Maverick County')
      GROUP BY ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 5 AND 10
ORDER BY c_last_name, c_first_name, c_customer_id
LIMIT 1000
"""

QUERIES["q41"] = """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 5 AND 15
  AND (SELECT count(*) FROM item
       WHERE i_manufact = i1.i_manufact
         AND ((i_category = 'Women' AND i_color IN ('plum', 'pink'))
              OR (i_category = 'Men' AND i_color IN ('black', 'blue'))
              OR (i_category = 'Shoes'
                  AND i_color IN ('green', 'ivory')))) > 0
ORDER BY i_product_name
LIMIT 100
"""

QUERIES["q48"] = """
SELECT sum(ss_quantity) AS total
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2000
  AND cd_demo_sk = ss_cdemo_sk
  AND ss_addr_sk = ca_address_sk AND ca_country = 'United States'
  AND ((cd_marital_status = 'M' AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_marital_status = 'D' AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_marital_status = 'S' AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ca_state IN ('CO', 'OH', 'TX') AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ca_state IN ('OR', 'MN', 'KS') AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ca_state IN ('TX', 'MO', 'MI') AND ss_net_profit BETWEEN 50 AND 25000))
"""

QUERIES["q17"] = """
SELECT i_item_id, i_item_desc, s_state,
       count(ss_quantity) AS store_sales_quantitycount,
       avg(ss_quantity) AS store_sales_quantityave,
       stddev_samp(ss_quantity) AS store_sales_quantitystdev,
       count(sr_return_quantity) AS store_returns_quantitycount,
       avg(sr_return_quantity) AS store_returns_quantityave,
       count(cs_quantity) AS catalog_sales_quantitycount,
       avg(cs_quantity) AS catalog_sales_quantityave
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
WHERE d1.d_qoy = 1 AND d1.d_year = 1999 AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state
LIMIT 100
"""

QUERIES["q18"] = """
SELECT i_item_id, ca_state,
       avg(cs_quantity) AS agg1, avg(cs_list_price) AS agg2,
       avg(cs_coupon_amt) AS agg3, avg(cs_sales_price) AS agg4
FROM catalog_sales, customer_demographics cd1, customer, customer_address,
     date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
  AND c_current_addr_sk = ca_address_sk
  AND d_year = 1998
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
GROUP BY ROLLUP (i_item_id, ca_state)
ORDER BY ca_state, i_item_id
LIMIT 1000
"""

QUERIES["q30"] = """
WITH customer_total_return AS (
  SELECT wr_returning_cdemo_sk AS ctr_cdemo_sk,
         ca_state AS ctr_state,
         sum(wr_return_amt) AS ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk AND d_year = 2000
    AND wr_refunded_addr_sk = ca_address_sk
  GROUP BY wr_returning_cdemo_sk, ca_state)
SELECT ctr_cdemo_sk, ctr_state, ctr_total_return
FROM customer_total_return ctr1
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_state = ctr2.ctr_state)
ORDER BY ctr_cdemo_sk, ctr_state, ctr_total_return
LIMIT 100
"""

QUERIES["q31"] = """
WITH ss AS (
  SELECT ca_county, d_qoy, d_year, sum(ss_ext_sales_price) AS store_sales
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
ws AS (
  SELECT ca_county, d_qoy, d_year, sum(ws_ext_sales_price) AS web_sales
  FROM web_sales, date_dim, customer_address
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales AS web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales AS store_q1_q2_increase
FROM ss ss1, ss ss2, ws ws1, ws ws2
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2000
  AND ss2.d_qoy = 2 AND ss2.d_year = 2000
  AND ss1.ca_county = ss2.ca_county
  AND ws1.d_qoy = 1 AND ws1.d_year = 2000
  AND ws2.d_qoy = 2 AND ws2.d_year = 2000
  AND ws1.ca_county = ws2.ca_county
  AND ss1.ca_county = ws1.ca_county
  AND ws2.web_sales / ws1.web_sales > ss2.store_sales / ss1.store_sales
ORDER BY ss1.ca_county
LIMIT 100
"""

QUERIES["q33"] = """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
"""

QUERIES["q40"] = """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN cs_sales_price ELSE 0 END) AS sales_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN cs_sales_price ELSE 0 END) AS sales_after
FROM catalog_sales
LEFT OUTER JOIN catalog_returns
  ON cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk,
warehouse, item, date_dim
WHERE i_current_price BETWEEN 10 AND 90
  AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
"""

QUERIES["q44"] = """
WITH v AS (SELECT ss_item_sk item_sk, avg(ss_net_profit) rank_col
           FROM store_sales WHERE ss_store_sk = 2 GROUP BY ss_item_sk)
SELECT asceding.rnk AS rnk, i1.i_product_name AS best_performing,
       i2.i_product_name AS worst_performing
FROM (SELECT item_sk, rank() OVER (ORDER BY rank_col ASC) rnk
      FROM v) asceding,
     (SELECT item_sk, rank() OVER (ORDER BY rank_col DESC) rnk
      FROM v) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk AND asceding.rnk < 11
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
LIMIT 100
"""

QUERIES["q46"] = """
SELECT c_last_name, c_first_name, ca_city, bought_city, amt, profit
FROM (SELECT ss_customer_sk, ca_city AS bought_city,
             sum(ss_coupon_amt) AS amt, sum(ss_net_profit) AS profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk AND ss_addr_sk = ca_address_sk
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_dow IN (6, 0)
        AND d_year IN (1999, 2000, 2001)
      GROUP BY ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city, amt, profit
LIMIT 1000
"""

QUERIES["q47"] = """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, d_year, d_moy,
         sum(ss_sales_price) AS sum_sales,
         avg(sum(ss_sales_price)) OVER (PARTITION BY i_category, i_brand,
                                        s_store_name, d_year)
           AS avg_monthly_sales,
         rank() OVER (PARTITION BY i_category, i_brand, s_store_name
                      ORDER BY d_year, d_moy) AS rn
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 1999 OR (d_year = 1998 AND d_moy = 12)
         OR (d_year = 2000 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, d_year, d_moy)
SELECT v1.i_category, v1.i_brand, v1.s_store_name, v1.d_year, v1.d_moy,
       v1.avg_monthly_sales, v1.sum_sales,
       v1_lag.sum_sales AS psum, v1_lead.sum_sales AS nsum
FROM v1, v1 v1_lag, v1 v1_lead
WHERE v1.i_category = v1_lag.i_category
  AND v1.i_category = v1_lead.i_category
  AND v1.i_brand = v1_lag.i_brand AND v1.i_brand = v1_lead.i_brand
  AND v1.s_store_name = v1_lag.s_store_name
  AND v1.s_store_name = v1_lead.s_store_name
  AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1
  AND v1.d_year = 1999
  AND v1.avg_monthly_sales > 0
  AND abs(v1.sum_sales - v1.avg_monthly_sales) / v1.avg_monthly_sales > 0.1
ORDER BY v1.i_category, v1.i_brand, v1.s_store_name, v1.d_moy
LIMIT 100
"""

QUERIES["q51"] = """
WITH web_v1 AS (
  SELECT ws_item_sk item_sk, d_date,
         sum(sum(ws_sales_price)) OVER (PARTITION BY ws_item_sk
                                        ORDER BY d_date
                                        ROWS BETWEEN UNBOUNDED PRECEDING
                                        AND CURRENT ROW) cume_sales
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 24 AND 35
  GROUP BY ws_item_sk, d_date),
store_v1 AS (
  SELECT ss_item_sk item_sk, d_date,
         sum(sum(ss_sales_price)) OVER (PARTITION BY ss_item_sk
                                        ORDER BY d_date
                                        ROWS BETWEEN UNBOUNDED PRECEDING
                                        AND CURRENT ROW) cume_sales
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 24 AND 35
  GROUP BY ss_item_sk, d_date)
SELECT item_sk, d_date, web_sales, store_sales
FROM (SELECT CASE WHEN web.item_sk IS NOT NULL THEN web.item_sk
                  ELSE store.item_sk END item_sk,
             CASE WHEN web.d_date IS NOT NULL THEN web.d_date
                  ELSE store.d_date END d_date,
             web.cume_sales web_sales, store.cume_sales store_sales
      FROM web_v1 web FULL OUTER JOIN store_v1 store
        ON web.item_sk = store.item_sk AND web.d_date = store.d_date) x
WHERE web_sales > store_sales
ORDER BY item_sk, d_date
LIMIT 100
"""

QUERIES["q35"] = """
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) AS cnt1, avg(cd_dep_count) AS a1, max(cd_dep_count) AS m1,
       sum(cd_dep_count) AS s1
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2002
                AND d_qoy < 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk AND d_year = 2002
                 AND d_qoy < 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_bill_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2002
                    AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count
LIMIT 100
"""

QUERIES["q39"] = """
WITH inv AS (
  SELECT w_warehouse_sk, i_item_sk, d_moy, stddev_samp(inv_quantity_on_hand) stdev,
         avg(inv_quantity_on_hand) mean
  FROM inventory, item, warehouse, date_dim
  WHERE inv_item_sk = i_item_sk AND inv_warehouse_sk = w_warehouse_sk
    AND inv_date_sk = d_date_sk AND d_year = 1999
  GROUP BY w_warehouse_sk, i_item_sk, d_moy)
SELECT inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
       inv1.stdev / inv1.mean AS cov1,
       inv2.d_moy AS d_moy_2, inv2.mean AS mean2,
       inv2.stdev / inv2.mean AS cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = 1 AND inv2.d_moy = 2
  AND inv1.mean > 0 AND inv2.mean > 0
  AND inv1.stdev / inv1.mean > 0.5
ORDER BY inv1.w_warehouse_sk, inv1.i_item_sk
LIMIT 200
"""

QUERIES["q58"] = """
WITH ss_items AS (
  SELECT i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_month_seq = (SELECT d_month_seq FROM date_dim
                       WHERE d_date = DATE '2000-03-11')
  GROUP BY i_item_id),
cs_items AS (
  SELECT i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_month_seq = (SELECT d_month_seq FROM date_dim
                       WHERE d_date = DATE '2000-03-11')
  GROUP BY i_item_id),
ws_items AS (
  SELECT i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_month_seq = (SELECT d_month_seq FROM date_dim
                       WHERE d_date = DATE '2000-03-11')
  GROUP BY i_item_id)
SELECT ss_items.item_id, ss_item_rev, cs_item_rev, ws_item_rev
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.5 * cs_item_rev AND 2.0 * cs_item_rev
  AND ss_item_rev BETWEEN 0.5 * ws_item_rev AND 2.0 * ws_item_rev
ORDER BY ss_items.item_id
LIMIT 100
"""

QUERIES["q59"] = """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                  ELSE 0 END) sun_sales,
         sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                  ELSE 0 END) mon_sales,
         sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                  ELSE 0 END) fri_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk)
SELECT s_store_name, y.d_week_seq AS week1,
       y.sun_sales / x.sun_sales AS r_sun,
       y.mon_sales / x.mon_sales AS r_mon,
       y.fri_sales / x.fri_sales AS r_fri
FROM wss y, wss x, store
WHERE y.ss_store_sk = x.ss_store_sk
  AND y.ss_store_sk = s_store_sk
  AND y.d_week_seq = x.d_week_seq - 52
  AND y.d_week_seq BETWEEN 52 AND 103
  AND x.sun_sales > 0 AND x.mon_sales > 0 AND x.fri_sales > 0
ORDER BY s_store_name, week1
LIMIT 200
"""

QUERIES["q60"] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Children')
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 1999 AND d_moy = 9
    AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Children')
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 1999 AND d_moy = 9
    AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Children')
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 1999 AND d_moy = 9
    AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
"""

QUERIES["q63"] = """
SELECT mgr, sum_sales, avg_monthly
FROM (SELECT i_manager_id AS mgr, sum(ss_sales_price) AS sum_sales,
             avg(sum(ss_sales_price)) OVER (PARTITION BY i_manager_id)
               AS avg_monthly
      FROM item, store_sales, date_dim, store
      WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND d_year = 1999
        AND ((i_category IN ('Books', 'Children', 'Electronics')
              AND i_class IN ('class01', 'class02', 'class03', 'class04'))
             OR (i_category IN ('Women', 'Music', 'Men')
                 AND i_class IN ('class05', 'class06', 'class07',
                                 'class08')))
      GROUP BY i_manager_id, d_moy) tmp1
WHERE CASE WHEN avg_monthly > 0
           THEN abs(sum_sales - avg_monthly) / avg_monthly
           ELSE NULL END > 0.0001
ORDER BY mgr, sum_sales
LIMIT 100
"""

QUERIES["q66"] = """
SELECT w_warehouse_name, w_warehouse_sq_ft, ship_carriers, d_year,
       sum(jan_sales) AS jan_sales, sum(feb_sales) AS feb_sales,
       sum(mar_sales) AS mar_sales
FROM (SELECT w_warehouse_name, w_warehouse_sq_ft,
             'DHL,BARIAN' AS ship_carriers, d_year,
             sum(CASE WHEN d_moy = 1 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS jan_sales,
             sum(CASE WHEN d_moy = 2 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS feb_sales,
             sum(CASE WHEN d_moy = 3 THEN ws_ext_sales_price * ws_quantity
                      ELSE 0 END) AS mar_sales
      FROM web_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE ws_warehouse_sk = w_warehouse_sk
        AND ws_sold_date_sk = d_date_sk AND d_year = 1999
        AND ws_sold_time_sk = t_time_sk
        AND t_hour BETWEEN 8 AND 17
        AND ws_ship_mode_sk = sm_ship_mode_sk
        AND sm_carrier IN ('DHL', 'BARIAN', 'UPS', 'FEDEX', 'AIRBORNE',
                           'USPS', 'TBS', 'ZOUROS', 'MSC', 'LATVIAN')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, d_year
      UNION ALL
      SELECT w_warehouse_name, w_warehouse_sq_ft,
             'DHL,BARIAN' AS ship_carriers, d_year,
             sum(CASE WHEN d_moy = 1 THEN cs_ext_sales_price * cs_quantity
                      ELSE 0 END) AS jan_sales,
             sum(CASE WHEN d_moy = 2 THEN cs_ext_sales_price * cs_quantity
                      ELSE 0 END) AS feb_sales,
             sum(CASE WHEN d_moy = 3 THEN cs_ext_sales_price * cs_quantity
                      ELSE 0 END) AS mar_sales
      FROM catalog_sales, warehouse, date_dim, ship_mode
      WHERE cs_warehouse_sk = w_warehouse_sk
        AND cs_sold_date_sk = d_date_sk AND d_year = 1999
        AND cs_ship_mode_sk = sm_ship_mode_sk
        AND sm_carrier IN ('DHL', 'BARIAN', 'UPS', 'FEDEX', 'AIRBORNE',
                           'USPS', 'TBS', 'ZOUROS', 'MSC', 'LATVIAN')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, d_year) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, ship_carriers, d_year
ORDER BY w_warehouse_name
LIMIT 100
"""

QUERIES["q71"] = """
SELECT i_brand_id AS brand_id, i_brand AS brand, t_hour, t_minute,
       sum(ext_price) AS ext_price
FROM item,
     (SELECT ws_ext_sales_price AS ext_price,
             ws_sold_date_sk AS sold_date_sk, ws_item_sk AS sold_item_sk,
             ws_sold_time_sk AS time_sk
      FROM web_sales, date_dim
      WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 1999
      UNION ALL
      SELECT ss_ext_sales_price AS ext_price,
             ss_sold_date_sk AS sold_date_sk, ss_item_sk AS sold_item_sk,
             ss_sold_time_sk AS time_sk
      FROM store_sales, date_dim
      WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11 AND d_year = 1999
     ) tmp, time_dim
WHERE sold_item_sk = i_item_sk AND i_manager_id = 1
  AND time_sk = t_time_sk
  AND (t_hour BETWEEN 7 AND 9 OR t_hour BETWEEN 19 AND 21)
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id, t_hour, t_minute
LIMIT 200
"""

QUERIES["q73"] = """
SELECT c_last_name, c_first_name, c_customer_id, cnt
FROM (SELECT ss_customer_sk, count(*) AS cnt
      FROM store_sales, store, household_demographics
      WHERE ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND hd_buy_potential IN ('501-1000', '5001-10000')
        AND hd_vehicle_count > 0
        AND CASE WHEN hd_vehicle_count > 0
                 THEN hd_dep_count / hd_vehicle_count ELSE NULL END > 0
      GROUP BY ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 3 AND 8
ORDER BY c_last_name, c_first_name, c_customer_id
LIMIT 1000
"""

QUERIES["q76"] = """
SELECT channel, col_name, d_year, d_qoy, i_category, count(*) AS sales_cnt,
       sum(ext_sales_price) AS sales_amt
FROM (SELECT 'store' AS channel, 'ss_promo_sk' AS col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price AS ext_sales_price
      FROM store_sales, item, date_dim
      WHERE ss_promo_sk IS NULL AND ss_sold_date_sk = d_date_sk
        AND ss_item_sk = i_item_sk
      UNION ALL
      SELECT 'web' AS channel, 'ws_promo_sk' AS col_name, d_year, d_qoy,
             i_category, ws_ext_sales_price AS ext_sales_price
      FROM web_sales, item, date_dim
      WHERE ws_promo_sk IS NULL AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk = i_item_sk
      UNION ALL
      SELECT 'catalog' AS channel, 'cs_promo_sk' AS col_name, d_year, d_qoy,
             i_category, cs_ext_sales_price AS ext_sales_price
      FROM catalog_sales, item, date_dim
      WHERE cs_promo_sk IS NULL AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 500
"""

QUERIES["q84"] = """
SELECT c_customer_id AS customer_id, c_last_name AS customername
FROM customer, customer_address, customer_demographics,
     household_demographics, income_band
WHERE ca_city = 'Riverside'
  AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 10000
  AND ib_upper_bound <= 200000
  AND ib_income_band_sk = hd_income_band_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND cd_demo_sk = c_current_cdemo_sk
ORDER BY c_customer_id
LIMIT 100
"""

QUERIES["q85"] = """
SELECT r_reason_desc, avg(ws_quantity) AS a1, avg(wr_return_amt) AS a2,
       avg(wr_fee) AS a3
FROM web_sales, web_returns, web_page, customer_demographics cd1, reason
WHERE ws_web_page_sk = wp_web_page_sk
  AND ws_item_sk = wr_item_sk AND ws_order_number = wr_order_number
  AND wr_refunded_cdemo_sk = cd1.cd_demo_sk
  AND wr_reason_sk = r_reason_sk
  AND ((cd1.cd_marital_status = 'M'
        AND cd1.cd_education_status = 'Advanced Degree'
        AND ws_sales_price BETWEEN 50.00 AND 150.00)
       OR (cd1.cd_marital_status = 'S'
           AND cd1.cd_education_status = 'College'
           AND ws_sales_price BETWEEN 10.00 AND 100.00)
       OR (cd1.cd_marital_status = 'W'
           AND cd1.cd_education_status = '2 yr Degree'
           AND ws_sales_price BETWEEN 50.00 AND 200.00))
GROUP BY r_reason_desc
ORDER BY r_reason_desc
LIMIT 100
"""

QUERIES["q90"] = """
SELECT CAST(amc AS DOUBLE) / CAST(pmc AS DOUBLE) AS am_pm_ratio
FROM (SELECT count(*) AS amc FROM web_sales, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 8 AND 9
        AND wp_char_count BETWEEN 2500 AND 5200) at1,
     (SELECT count(*) AS pmc FROM web_sales, time_dim, web_page
      WHERE ws_sold_time_sk = t_time_sk
        AND ws_web_page_sk = wp_web_page_sk
        AND t_hour BETWEEN 19 AND 20
        AND wp_char_count BETWEEN 2500 AND 5200) pt
"""

QUERIES["q91"] = """
SELECT cc_call_center_id AS call_center, cc_name, sum(cr_net_loss) AS returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND d_year = 1999
  AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
       OR (cd_marital_status = 'W'
           AND cd_education_status = 'Advanced Degree'))
  AND hd_buy_potential LIKE 'Unknown%'
GROUP BY cc_call_center_id, cc_name
ORDER BY cc_call_center_id
LIMIT 100
"""

QUERIES["q93"] = """
SELECT ss_customer_sk, sum(act_sales) AS sumsales
FROM (SELECT ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END AS act_sales
      FROM store_sales
      LEFT OUTER JOIN store_returns
        ON sr_item_sk = ss_item_sk AND sr_ticket_number = ss_ticket_number,
      reason
      WHERE sr_reason_sk = r_reason_sk AND r_reason_sk = 5) t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
"""

QUERIES["q81"] = """
WITH customer_total_return AS (
  SELECT cr_returning_customer_sk AS ctr_customer_sk, ca_state AS ctr_state,
         sum(cr_return_amount) AS ctr_total_return
  FROM catalog_returns, date_dim, customer_address, customer
  WHERE cr_returned_date_sk = d_date_sk AND d_year = 2000
    AND cr_returning_customer_sk = c_customer_sk
    AND c_current_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_first_name, c_last_name, ctr_total_return
FROM customer_total_return ctr1, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
"""

QUERIES["q86"] = """
SELECT sum(ws_net_paid) AS total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) AS lochierarchy,
       rank() OVER (PARTITION BY grouping(i_category) + grouping(i_class),
                    CASE WHEN grouping(i_class) = 0 THEN i_category END
                    ORDER BY sum(ws_net_paid) DESC) AS rank_within_parent
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN 12 AND 23
  AND d1.d_date_sk = ws_sold_date_sk AND i_item_sk = ws_item_sk
GROUP BY ROLLUP (i_category, i_class)
ORDER BY lochierarchy DESC, i_category, i_class
LIMIT 100
"""

QUERIES["q2"] = """
WITH wscs AS (
  SELECT ws_sold_date_sk AS sold_date_sk, ws_ext_sales_price AS sales_price
  FROM web_sales
  UNION ALL
  SELECT cs_sold_date_sk, cs_ext_sales_price FROM catalog_sales),
wswscs AS (
  SELECT d_week_seq,
    sum(CASE WHEN d_day_name = 'Sunday' THEN sales_price ELSE NULL END)
      AS sun_sales,
    sum(CASE WHEN d_day_name = 'Monday' THEN sales_price ELSE NULL END)
      AS mon_sales,
    sum(CASE WHEN d_day_name = 'Tuesday' THEN sales_price ELSE NULL END)
      AS tue_sales,
    sum(CASE WHEN d_day_name = 'Wednesday' THEN sales_price ELSE NULL END)
      AS wed_sales,
    sum(CASE WHEN d_day_name = 'Thursday' THEN sales_price ELSE NULL END)
      AS thu_sales,
    sum(CASE WHEN d_day_name = 'Friday' THEN sales_price ELSE NULL END)
      AS fri_sales,
    sum(CASE WHEN d_day_name = 'Saturday' THEN sales_price ELSE NULL END)
      AS sat_sales
  FROM wscs, date_dim WHERE d_date_sk = sold_date_sk GROUP BY d_week_seq),
wk AS (SELECT DISTINCT d_week_seq, d_year FROM date_dim)
SELECT y.d_week_seq AS week1,
       y.sun_sales / z.sun_sales AS r_sun, y.mon_sales / z.mon_sales AS r_mon,
       y.tue_sales / z.tue_sales AS r_tue, y.wed_sales / z.wed_sales AS r_wed,
       y.thu_sales / z.thu_sales AS r_thu, y.fri_sales / z.fri_sales AS r_fri,
       y.sat_sales / z.sat_sales AS r_sat
FROM wswscs y, wk wky, wswscs z, wk wkz
WHERE y.d_week_seq = wky.d_week_seq AND wky.d_year = 1999
  AND z.d_week_seq = wkz.d_week_seq AND wkz.d_year = 2000
  AND y.d_week_seq = z.d_week_seq - 53
ORDER BY y.d_week_seq
"""

# q4/q11/q74: the year_total family (3/2/2-channel year-over-year customer
# growth, 6/4/4-way CTE self joins). catalog_sales has no cs_ext_wholesale_cost
# in the generated subset; cs_wholesale_cost substitutes (same type).
QUERIES["q4"] = """
WITH year_total AS (
  SELECT c_customer_id AS customer_id, c_first_name, c_last_name, d_year,
         sum(((ss_ext_list_price - ss_ext_wholesale_cost
               - ss_ext_discount_amt) + ss_ext_sales_price) / 2)
           AS year_total,
         's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(((cs_ext_list_price - cs_wholesale_cost - cs_ext_discount_amt)
              + cs_ext_sales_price) / 2),
         'c'
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk AND cs_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(((ws_ext_list_price - ws_ext_wholesale_cost
               - ws_ext_discount_amt) + ws_ext_sales_price) / 2),
         'w'
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.c_first_name,
       t_s_secyear.c_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w' AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.d_year = 1999 AND t_s_secyear.d_year = 2000
  AND t_c_firstyear.d_year = 1999 AND t_c_secyear.d_year = 2000
  AND t_w_firstyear.d_year = 1999 AND t_w_secyear.d_year = 2000
  AND t_s_firstyear.year_total > 0 AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE NULL END
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_w_firstyear.year_total > 0
             THEN t_w_secyear.year_total / t_w_firstyear.year_total
             ELSE NULL END
ORDER BY t_s_secyear.customer_id, t_s_secyear.c_first_name,
         t_s_secyear.c_last_name
LIMIT 100
"""

QUERIES["q11"] = """
WITH year_total AS (
  SELECT c_customer_id AS customer_id, c_first_name, c_last_name, d_year,
         sum(ss_ext_list_price - ss_ext_discount_amt) AS year_total,
         's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(ws_ext_list_price - ws_ext_discount_amt), 'w'
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.c_first_name,
       t_s_secyear.c_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.d_year = 1999 AND t_s_secyear.d_year = 2000
  AND t_w_firstyear.d_year = 1999 AND t_w_secyear.d_year = 2000
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE 0.0 END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE 0.0 END
ORDER BY t_s_secyear.customer_id, t_s_secyear.c_first_name,
         t_s_secyear.c_last_name
LIMIT 100
"""

QUERIES["q74"] = """
WITH year_total AS (
  SELECT c_customer_id AS customer_id, c_first_name, c_last_name, d_year,
         sum(ss_net_paid) AS year_total, 's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
    AND d_year IN (1999, 2000)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(ws_net_paid), 'w'
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk AND ws_sold_date_sk = d_date_sk
    AND d_year IN (1999, 2000)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.c_first_name,
       t_s_secyear.c_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.d_year = 1999 AND t_s_secyear.d_year = 2000
  AND t_w_firstyear.d_year = 1999 AND t_w_secyear.d_year = 2000
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND t_w_secyear.year_total / t_w_firstyear.year_total
      > t_s_secyear.year_total / t_s_firstyear.year_total
ORDER BY t_s_secyear.c_first_name, t_s_secyear.c_last_name,
         t_s_secyear.customer_id
LIMIT 100
"""

QUERIES["q97"] = """
WITH ssci AS (
  SELECT ss_customer_sk AS customer_sk, ss_item_sk AS item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 24 AND 35
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk AS customer_sk, cs_item_sk AS item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 24 AND 35
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL THEN 1 ELSE 0 END)
         AS store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
         AS catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
         AS store_and_catalog
FROM ssci FULL OUTER JOIN csci
  ON ssci.customer_sk = csci.customer_sk AND ssci.item_sk = csci.item_sk
"""

# q5/q77/q80: per-channel sales+returns rollups. The generated subset has no
# cp_catalog_page_sk on catalog_returns, so the catalog channel ids are call
# centers; web returns reach their site/page through the sales-side join
# (wr_order_number+wr_item_sk), as in the official wsr definition.
QUERIES["q5"] = """
WITH ssr AS (
  SELECT s_store_id AS id, sum(sales_price) AS sales,
         sum(return_amt) AS returns_amt, sum(profit) AS profit,
         sum(net_loss) AS profit_loss
  FROM (SELECT ss_store_sk AS store_sk, ss_sold_date_sk AS date_sk,
               ss_ext_sales_price AS sales_price, ss_net_profit AS profit,
               0.0 AS return_amt, 0.0 AS net_loss
        FROM store_sales
        UNION ALL
        SELECT sr_store_sk, sr_returned_date_sk, 0.0, 0.0,
               sr_return_amt, sr_net_loss
        FROM store_returns) salesreturns, date_dim, store
  WHERE date_sk = d_date_sk AND d_date_sk BETWEEN 2451100 AND 2451114
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
csr AS (
  SELECT cc_call_center_id AS id, sum(sales_price) AS sales,
         sum(return_amt) AS returns_amt, sum(profit) AS profit,
         sum(net_loss) AS profit_loss
  FROM (SELECT cs_call_center_sk AS center_sk, cs_sold_date_sk AS date_sk,
               cs_ext_sales_price AS sales_price, cs_net_profit AS profit,
               0.0 AS return_amt, 0.0 AS net_loss
        FROM catalog_sales
        UNION ALL
        SELECT cr_call_center_sk, cr_returned_date_sk, 0.0, 0.0,
               cr_return_amount, cr_net_loss
        FROM catalog_returns) salesreturns, date_dim, call_center
  WHERE date_sk = d_date_sk AND d_date_sk BETWEEN 2451100 AND 2451114
    AND center_sk = cc_call_center_sk
  GROUP BY cc_call_center_id),
wsr AS (
  SELECT web_site_id AS id, sum(sales_price) AS sales,
         sum(return_amt) AS returns_amt, sum(profit) AS profit,
         sum(net_loss) AS profit_loss
  FROM (SELECT ws_web_site_sk AS site_sk, ws_sold_date_sk AS date_sk,
               ws_ext_sales_price AS sales_price, ws_net_profit AS profit,
               0.0 AS return_amt, 0.0 AS net_loss
        FROM web_sales
        UNION ALL
        SELECT ws_web_site_sk, wr_returned_date_sk, 0.0, 0.0,
               wr_return_amt, wr_net_loss
        FROM web_returns, web_sales
        WHERE wr_item_sk = ws_item_sk AND wr_order_number = ws_order_number
       ) salesreturns, date_dim, web_site
  WHERE date_sk = d_date_sk AND d_date_sk BETWEEN 2451100 AND 2451114
    AND site_sk = web_site_sk
  GROUP BY web_site_id)
SELECT channel, id, sum(sales) AS sales, sum(returns_amt) AS returns_amt,
       sum(profit) AS profit
FROM (SELECT 'store channel' AS channel, id, sales, returns_amt,
             profit - profit_loss AS profit FROM ssr
      UNION ALL
      SELECT 'catalog channel', id, sales, returns_amt,
             profit - profit_loss FROM csr
      UNION ALL
      SELECT 'web channel', id, sales, returns_amt,
             profit - profit_loss FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
"""

QUERIES["q77"] = """
WITH ss AS (
  SELECT s_store_sk, sum(ss_ext_sales_price) AS sales,
         sum(ss_net_profit) AS profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date_sk BETWEEN 2451100 AND 2451129
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
sr AS (
  SELECT s_store_sk, sum(sr_return_amt) AS returns_amt,
         sum(sr_net_loss) AS profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date_sk BETWEEN 2451100 AND 2451129
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
cs AS (
  SELECT cs_call_center_sk, sum(cs_ext_sales_price) AS sales,
         sum(cs_net_profit) AS profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date_sk BETWEEN 2451100 AND 2451129
  GROUP BY cs_call_center_sk),
cr AS (
  SELECT cr_call_center_sk, sum(cr_return_amount) AS returns_amt,
         sum(cr_net_loss) AS profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date_sk BETWEEN 2451100 AND 2451129
  GROUP BY cr_call_center_sk),
ws AS (
  SELECT wp_web_page_sk, sum(ws_ext_sales_price) AS sales,
         sum(ws_net_profit) AS profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date_sk BETWEEN 2451100 AND 2451129
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
wr AS (
  SELECT wp_web_page_sk, sum(wr_return_amt) AS returns_amt,
         sum(wr_net_loss) AS profit_loss
  FROM web_returns, web_sales, date_dim, web_page
  WHERE wr_item_sk = ws_item_sk AND wr_order_number = ws_order_number
    AND wr_returned_date_sk = d_date_sk
    AND d_date_sk BETWEEN 2451100 AND 2451129
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk)
SELECT channel, id, sum(sales) AS sales, sum(returns_amt) AS returns_amt,
       sum(profit) AS profit
FROM (SELECT 'store channel' AS channel, ss.s_store_sk AS id, sales,
             coalesce(returns_amt, 0.0) AS returns_amt,
             profit - coalesce(profit_loss, 0.0) AS profit
      FROM ss LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
      UNION ALL
      SELECT 'catalog channel', cs.cs_call_center_sk, sales,
             coalesce(returns_amt, 0.0),
             profit - coalesce(profit_loss, 0.0)
      FROM cs LEFT JOIN cr ON cs.cs_call_center_sk = cr.cr_call_center_sk
      UNION ALL
      SELECT 'web channel', ws.wp_web_page_sk, sales,
             coalesce(returns_amt, 0.0),
             profit - coalesce(profit_loss, 0.0)
      FROM ws LEFT JOIN wr ON ws.wp_web_page_sk = wr.wp_web_page_sk) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
"""

QUERIES["q80"] = """
WITH ssr AS (
  SELECT s_store_id AS id,
         sum(ss_ext_sales_price) AS sales,
         sum(coalesce(sr_return_amt, 0.0)) AS returns_amt,
         sum(ss_net_profit - coalesce(sr_net_loss, 0.0)) AS profit
  FROM store_sales
  LEFT JOIN store_returns ON ss_item_sk = sr_item_sk
                          AND ss_ticket_number = sr_ticket_number
  JOIN date_dim ON ss_sold_date_sk = d_date_sk
  JOIN store ON ss_store_sk = s_store_sk
  JOIN item ON ss_item_sk = i_item_sk
  JOIN promotion ON ss_promo_sk = p_promo_sk
  WHERE d_date_sk BETWEEN 2451100 AND 2451129
    AND i_current_price > 50 AND p_channel_tv = 'N'
  GROUP BY s_store_id),
csr AS (
  SELECT cc_call_center_id AS id,
         sum(cs_ext_sales_price) AS sales,
         sum(coalesce(cr_return_amount, 0.0)) AS returns_amt,
         sum(cs_net_profit - coalesce(cr_net_loss, 0.0)) AS profit
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cs_item_sk = cr_item_sk
                            AND cs_order_number = cr_order_number
  JOIN date_dim ON cs_sold_date_sk = d_date_sk
  JOIN call_center ON cs_call_center_sk = cc_call_center_sk
  JOIN item ON cs_item_sk = i_item_sk
  JOIN promotion ON cs_promo_sk = p_promo_sk
  WHERE d_date_sk BETWEEN 2451100 AND 2451129
    AND i_current_price > 50 AND p_channel_tv = 'N'
  GROUP BY cc_call_center_id),
wsr AS (
  SELECT web_site_id AS id,
         sum(ws_ext_sales_price) AS sales,
         sum(coalesce(wr_return_amt, 0.0)) AS returns_amt,
         sum(ws_net_profit - coalesce(wr_net_loss, 0.0)) AS profit
  FROM web_sales
  LEFT JOIN web_returns ON ws_item_sk = wr_item_sk
                        AND ws_order_number = wr_order_number
  JOIN date_dim ON ws_sold_date_sk = d_date_sk
  JOIN web_site ON ws_web_site_sk = web_site_sk
  JOIN item ON ws_item_sk = i_item_sk
  JOIN promotion ON ws_promo_sk = p_promo_sk
  WHERE d_date_sk BETWEEN 2451100 AND 2451129
    AND i_current_price > 50 AND p_channel_tv = 'N'
  GROUP BY web_site_id)
SELECT channel, id, sum(sales) AS sales, sum(returns_amt) AS returns_amt,
       sum(profit) AS profit
FROM (SELECT 'store channel' AS channel, id, sales, returns_amt, profit
      FROM ssr
      UNION ALL
      SELECT 'catalog channel', id, sales, returns_amt, profit FROM csr
      UNION ALL
      SELECT 'web channel', id, sales, returns_amt, profit FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
"""

QUERIES["q75"] = """
WITH all_sales AS (
  SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
         sum(sales_cnt) AS sales_cnt, sum(sales_amt) AS sales_amt
  FROM (SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0) AS sales_cnt,
               cs_ext_sales_price - coalesce(cr_return_amount, 0.0)
                 AS sales_amt
        FROM catalog_sales
        JOIN item ON i_item_sk = cs_item_sk
        JOIN date_dim ON d_date_sk = cs_sold_date_sk
        LEFT JOIN catalog_returns ON cs_order_number = cr_order_number
                                  AND cs_item_sk = cr_item_sk
        WHERE i_category = 'Electronics'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0),
               ss_ext_sales_price - coalesce(sr_return_amt, 0.0)
        FROM store_sales
        JOIN item ON i_item_sk = ss_item_sk
        JOIN date_dim ON d_date_sk = ss_sold_date_sk
        LEFT JOIN store_returns ON ss_ticket_number = sr_ticket_number
                                AND ss_item_sk = sr_item_sk
        WHERE i_category = 'Electronics'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0),
               ws_ext_sales_price - coalesce(wr_return_amt, 0.0)
        FROM web_sales
        JOIN item ON i_item_sk = ws_item_sk
        JOIN date_dim ON d_date_sk = ws_sold_date_sk
        LEFT JOIN web_returns ON ws_order_number = wr_order_number
                              AND ws_item_sk = wr_item_sk
        WHERE i_category = 'Electronics') sales_detail
  GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
SELECT prev_yr.d_year AS prev_year, curr_yr.d_year AS year,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id, prev_yr.sales_cnt AS prev_yr_cnt,
       curr_yr.sales_cnt AS curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt AS sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt AS sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2000 AND prev_yr.d_year = 1999
  AND cast(curr_yr.sales_cnt AS double) / cast(prev_yr.sales_cnt AS double)
      < 0.9
ORDER BY sales_cnt_diff, sales_amt_diff
LIMIT 100
"""

QUERIES["q78"] = """
WITH ws AS (
  SELECT d_year AS ws_sold_year, ws_item_sk,
         ws_bill_customer_sk AS ws_customer_sk,
         sum(ws_quantity) AS ws_qty, sum(ws_wholesale_cost) AS ws_wc,
         sum(ws_sales_price) AS ws_sp
  FROM web_sales
  LEFT JOIN web_returns ON wr_order_number = ws_order_number
                        AND ws_item_sk = wr_item_sk
  JOIN date_dim ON ws_sold_date_sk = d_date_sk
  WHERE wr_order_number IS NULL
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk),
cs AS (
  SELECT d_year AS cs_sold_year, cs_item_sk,
         cs_bill_customer_sk AS cs_customer_sk,
         sum(cs_quantity) AS cs_qty, sum(cs_wholesale_cost) AS cs_wc,
         sum(cs_sales_price) AS cs_sp
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
                            AND cs_item_sk = cr_item_sk
  JOIN date_dim ON cs_sold_date_sk = d_date_sk
  WHERE cr_order_number IS NULL
  GROUP BY d_year, cs_item_sk, cs_bill_customer_sk),
ss AS (
  SELECT d_year AS ss_sold_year, ss_item_sk,
         ss_customer_sk,
         sum(ss_quantity) AS ss_qty, sum(ss_wholesale_cost) AS ss_wc,
         sum(ss_sales_price) AS ss_sp
  FROM store_sales
  LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number
                          AND ss_item_sk = sr_item_sk
  JOIN date_dim ON ss_sold_date_sk = d_date_sk
  WHERE sr_ticket_number IS NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk)
SELECT ss.ss_customer_sk, ss.ss_item_sk, ss_qty,
       ss_qty / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0)) AS ratio,
       coalesce(ws_qty, 0) + coalesce(cs_qty, 0) AS other_chan_qty,
       coalesce(ws_wc, 0.0) + coalesce(cs_wc, 0.0) AS other_chan_wholesale,
       coalesce(ws_sp, 0.0) + coalesce(cs_sp, 0.0) AS other_chan_sales_price
FROM ss
LEFT JOIN ws ON ws.ws_sold_year = ss.ss_sold_year
             AND ws.ws_item_sk = ss.ss_item_sk
             AND ws.ws_customer_sk = ss.ss_customer_sk
LEFT JOIN cs ON cs.cs_sold_year = ss.ss_sold_year
             AND cs.cs_item_sk = ss.ss_item_sk
             AND cs.cs_customer_sk = ss.ss_customer_sk
WHERE (coalesce(ws_qty, 0) > 0 OR coalesce(cs_qty, 0) > 0)
  AND ss.ss_sold_year = 2000
ORDER BY ss.ss_customer_sk, ss.ss_item_sk
LIMIT 100
"""

# q8: store has no s_zip in the generated subset — the zip-prefix
# neighborhood match becomes a state match (same shape: literal list
# INTERSECT states with enough preferred customers, joined to stores).
QUERIES["q8"] = """
WITH qualified_states AS (
  SELECT ca_state FROM customer_address
  WHERE ca_state IN ('AL', 'IL', 'MI', 'TN', 'CA', 'NY')
  INTERSECT
  SELECT ca_state FROM
   (SELECT ca_state, count(*) AS cnt
    FROM customer_address, customer
    WHERE ca_address_sk = c_current_addr_sk
      AND c_preferred_cust_flag = 'Y'
    GROUP BY ca_state HAVING count(*) > 40) a)
SELECT s_store_name, sum(ss_net_profit) AS profit
FROM store_sales, date_dim, store, qualified_states
WHERE ss_sold_date_sk = d_date_sk AND d_qoy = 2 AND d_year = 1999
  AND ss_store_sk = s_store_sk AND s_state = ca_state
GROUP BY s_store_name
ORDER BY s_store_name
"""

QUERIES["q49"] = """
SELECT channel, item, return_ratio, return_rank, currency_rank FROM
 (SELECT 'web' AS channel, web.item, web.return_ratio, web.return_rank,
         web.currency_rank
  FROM (SELECT item, return_ratio, currency_ratio,
               rank() OVER (ORDER BY return_ratio) AS return_rank,
               rank() OVER (ORDER BY currency_ratio) AS currency_rank
        FROM (SELECT ws_item_sk AS item,
                     cast(sum(coalesce(wr_return_quantity, 0)) AS double)
                     / cast(sum(coalesce(ws_quantity, 0)) AS double)
                       AS return_ratio,
                     cast(sum(coalesce(wr_return_amt, 0.0)) AS double)
                     / cast(sum(coalesce(ws_net_paid, 0.0)) AS double)
                       AS currency_ratio
              FROM web_sales
              LEFT JOIN web_returns ON ws_order_number = wr_order_number
                                    AND ws_item_sk = wr_item_sk, date_dim
              WHERE wr_return_amt > 100 AND ws_net_profit > 1
                AND ws_net_paid > 0 AND ws_quantity > 0
                AND ws_sold_date_sk = d_date_sk AND d_year = 2000
              GROUP BY ws_item_sk) in_web) web
  WHERE web.return_rank <= 10 OR web.currency_rank <= 10
  UNION
  SELECT 'catalog', c.item, c.return_ratio, c.return_rank, c.currency_rank
  FROM (SELECT item, return_ratio, currency_ratio,
               rank() OVER (ORDER BY return_ratio) AS return_rank,
               rank() OVER (ORDER BY currency_ratio) AS currency_rank
        FROM (SELECT cs_item_sk AS item,
                     cast(sum(coalesce(cr_return_quantity, 0)) AS double)
                     / cast(sum(coalesce(cs_quantity, 0)) AS double)
                       AS return_ratio,
                     cast(sum(coalesce(cr_return_amount, 0.0)) AS double)
                     / cast(sum(coalesce(cs_ext_sales_price, 0.0)) AS double)
                       AS currency_ratio
              FROM catalog_sales
              LEFT JOIN catalog_returns ON cs_order_number = cr_order_number
                                        AND cs_item_sk = cr_item_sk, date_dim
              WHERE cr_return_amount > 100 AND cs_net_profit > 1
                AND cs_ext_sales_price > 0 AND cs_quantity > 0
                AND cs_sold_date_sk = d_date_sk AND d_year = 2000
              GROUP BY cs_item_sk) in_cat) c
  WHERE c.return_rank <= 10 OR c.currency_rank <= 10
  UNION
  SELECT 'store', s.item, s.return_ratio, s.return_rank, s.currency_rank
  FROM (SELECT item, return_ratio, currency_ratio,
               rank() OVER (ORDER BY return_ratio) AS return_rank,
               rank() OVER (ORDER BY currency_ratio) AS currency_rank
        FROM (SELECT ss_item_sk AS item,
                     cast(sum(coalesce(sr_return_quantity, 0)) AS double)
                     / cast(sum(coalesce(ss_quantity, 0)) AS double)
                       AS return_ratio,
                     cast(sum(coalesce(sr_return_amt, 0.0)) AS double)
                     / cast(sum(coalesce(ss_net_paid, 0.0)) AS double)
                       AS currency_ratio
              FROM store_sales
              LEFT JOIN store_returns ON ss_ticket_number = sr_ticket_number
                                      AND ss_item_sk = sr_item_sk, date_dim
              WHERE sr_return_amt > 100 AND ss_net_profit > 1
                AND ss_net_paid > 0 AND ss_quantity > 0
                AND ss_sold_date_sk = d_date_sk AND d_year = 2000
              GROUP BY ss_item_sk) in_store) s
  WHERE s.return_rank <= 10 OR s.currency_rank <= 10) x
ORDER BY channel, return_rank, currency_rank, item
"""

QUERIES["q54"] = """
WITH my_customers AS (
  SELECT DISTINCT c_customer_sk, c_current_addr_sk
  FROM (SELECT cs_sold_date_sk AS sold_date_sk,
               cs_bill_customer_sk AS customer_sk, cs_item_sk AS item_sk
        FROM catalog_sales
        UNION ALL
        SELECT ws_sold_date_sk, ws_bill_customer_sk, ws_item_sk
        FROM web_sales) cs_or_ws_sales, item, date_dim, customer
  WHERE sold_date_sk = d_date_sk AND item_sk = i_item_sk
    AND i_category = 'Music' AND i_class = 'class01'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_moy = 3 AND d_year = 2000),
my_revenue AS (
  SELECT c_customer_sk, sum(ss_ext_sales_price) AS revenue
  FROM my_customers, store_sales, customer_address, store, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND c_current_addr_sk = ca_address_sk
    AND ca_county = s_county AND ca_state = s_state
    AND ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN
        (SELECT DISTINCT d_month_seq + 1 FROM date_dim
         WHERE d_year = 2000 AND d_moy = 3)
        AND
        (SELECT DISTINCT d_month_seq + 3 FROM date_dim
         WHERE d_year = 2000 AND d_moy = 3)
  GROUP BY c_customer_sk),
segments AS (
  SELECT cast((revenue / 50) AS int) AS segment FROM my_revenue)
SELECT segment, count(*) AS num_customers, segment * 50 AS segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers
"""

QUERIES["q56"] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('blue', 'khaki', 'plum'))
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 2
    AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('blue', 'khaki', 'plum'))
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 2
    AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('blue', 'khaki', 'plum'))
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 2000 AND d_moy = 2
    AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) AS total_sales
FROM (SELECT i_item_id, total_sales FROM ss
      UNION ALL
      SELECT i_item_id, total_sales FROM cs
      UNION ALL
      SELECT i_item_id, total_sales FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
"""

QUERIES["q57"] = """
WITH v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy,
         sum(cs_sales_price) AS sum_sales,
         avg(sum(cs_sales_price)) OVER (PARTITION BY i_category, i_brand,
                                        cc_name, d_year)
           AS avg_monthly_sales,
         rank() OVER (PARTITION BY i_category, i_brand, cc_name
                      ORDER BY d_year, d_moy) AS rn
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND cc_call_center_sk = cs_call_center_sk
    AND (d_year = 1999 OR (d_year = 1998 AND d_moy = 12)
         OR (d_year = 2000 AND d_moy = 1))
  GROUP BY i_category, i_brand, cc_name, d_year, d_moy)
SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
       v1.avg_monthly_sales, v1.sum_sales,
       v1_lag.sum_sales AS psum, v1_lead.sum_sales AS nsum
FROM v1, v1 v1_lag, v1 v1_lead
WHERE v1.i_category = v1_lag.i_category
  AND v1.i_category = v1_lead.i_category
  AND v1.i_brand = v1_lag.i_brand AND v1.i_brand = v1_lead.i_brand
  AND v1.cc_name = v1_lag.cc_name AND v1.cc_name = v1_lead.cc_name
  AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1
  AND v1.d_year = 1999
  AND v1.avg_monthly_sales > 0
  AND abs(v1.sum_sales - v1.avg_monthly_sales) / v1.avg_monthly_sales > 0.1
ORDER BY v1.i_category, v1.i_brand, v1.cc_name, v1.d_moy
"""

QUERIES["q14"] = """
WITH cross_items AS (
  SELECT i_item_sk AS ss_item_sk
  FROM item,
   (SELECT iss.i_brand_id AS brand_id, iss.i_class_id AS class_id,
           iss.i_category_id AS category_id
    FROM store_sales, item iss, date_dim d1
    WHERE ss_item_sk = iss.i_item_sk AND ss_sold_date_sk = d1.d_date_sk
      AND d1.d_year BETWEEN 1999 AND 2001
    INTERSECT
    SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
    FROM catalog_sales, item ics, date_dim d2
    WHERE cs_item_sk = ics.i_item_sk AND cs_sold_date_sk = d2.d_date_sk
      AND d2.d_year BETWEEN 1999 AND 2001
    INTERSECT
    SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
    FROM web_sales, item iws, date_dim d3
    WHERE ws_item_sk = iws.i_item_sk AND ws_sold_date_sk = d3.d_date_sk
      AND d3.d_year BETWEEN 1999 AND 2001) x
  WHERE i_brand_id = brand_id AND i_class_id = class_id
    AND i_category_id = category_id),
avg_sales AS (
  SELECT avg(quantity * list_price) AS average_sales
  FROM (SELECT ss_quantity AS quantity, ss_list_price AS list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT cs_quantity, cs_list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT ws_quantity, ws_list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 2001) x)
SELECT channel, i_brand_id, i_class_id, i_category_id, sum(sales) AS sales,
       sum(number_sales) AS number_sales
FROM (SELECT 'store' AS channel, i_brand_id, i_class_id, i_category_id,
             sum(ss_quantity * ss_list_price) AS sales,
             count(*) AS number_sales
      FROM store_sales, item, date_dim
      WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND d_year = 2001 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(ss_quantity * ss_list_price)
             > (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'catalog', i_brand_id, i_class_id, i_category_id,
             sum(cs_quantity * cs_list_price), count(*)
      FROM catalog_sales, item, date_dim
      WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
        AND d_year = 2001 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(cs_quantity * cs_list_price)
             > (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'web', i_brand_id, i_class_id, i_category_id,
             sum(ws_quantity * ws_list_price), count(*)
      FROM web_sales, item, date_dim
      WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
        AND d_year = 2001 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(ws_quantity * ws_list_price)
             > (SELECT average_sales FROM avg_sales)) y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id
"""

# q23: thresholds adapted to the synthetic sf=0.01 domains (items bought
# >4 times over the window; customers above 50% of the max store spend).
QUERIES["q23"] = """
WITH frequent_ss_items AS (
  SELECT i_item_sk AS item_sk, count(*) AS cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
    AND d_year IN (1999, 2000)
  GROUP BY i_item_sk
  HAVING count(*) > 4),
max_store_sales AS (
  SELECT max(csales) AS tpcds_cmax
  FROM (SELECT c_customer_sk,
               sum(ss_quantity * ss_sales_price) AS csales
        FROM store_sales, customer, date_dim
        WHERE ss_customer_sk = c_customer_sk AND ss_sold_date_sk = d_date_sk
          AND d_year IN (1999, 2000)
        GROUP BY c_customer_sk) a),
best_ss_customer AS (
  SELECT c_customer_sk
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING sum(ss_quantity * ss_sales_price)
         > 0.5 * (SELECT tpcds_cmax FROM max_store_sales))
SELECT sum(sales) AS total_sales
FROM (SELECT cs_quantity * cs_list_price AS sales
      FROM catalog_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 3 AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND cs_bill_customer_sk IN
            (SELECT c_customer_sk FROM best_ss_customer)
      UNION ALL
      SELECT ws_quantity * ws_list_price
      FROM web_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 3 AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND ws_bill_customer_sk IN
            (SELECT c_customer_sk FROM best_ss_customer)) x
"""

# q24: store has no s_zip/s_market_id and customer no c_birth_country in
# the generated subset — the same-neighborhood match rides s_state=ca_state
# and the market filter becomes s_number_employees; shape (returns-joined
# store sales, CTE reused in a scalar HAVING threshold) is preserved.
QUERIES["q24"] = """
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, i_color,
         sum(ss_net_paid) AS netpaid
  FROM store_sales, store_returns, store, item, customer, customer_address
  WHERE ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk AND c_current_addr_sk = ca_address_sk
    AND s_state = ca_state AND s_number_employees BETWEEN 200 AND 290
  GROUP BY c_last_name, c_first_name, s_store_name, i_color)
SELECT c_last_name, c_first_name, s_store_name, sum(netpaid) AS paid
FROM ssales
WHERE i_color = 'pink'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING sum(netpaid) > (SELECT 0.05 * avg(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
"""

# q64: customer first-sales/first-shipto dates and demographics joins are
# absent from the generated subset; the core shape — returns-qualified
# catalog items (cs_ui), the per-(item, store, year) cross_sales rollup,
# and the year-over-year self join — is preserved.
QUERIES["q64"] = """
WITH cs_ui AS (
  SELECT cs_item_sk,
         sum(cs_ext_list_price) AS sale,
         sum(cr_refunded_cash + cr_net_loss) AS refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price)
         > 2 * sum(cr_refunded_cash + cr_net_loss)),
cross_sales AS (
  SELECT i_product_name AS product_name, i_item_sk AS item_sk,
         s_store_name AS store_name, d1.d_year AS syear,
         count(*) AS cnt, sum(ss_wholesale_cost) AS s1,
         sum(ss_list_price) AS s2, sum(ss_coupon_amt) AS s3
  FROM store_sales, store_returns, cs_ui, date_dim d1, store, item
  WHERE ss_store_sk = s_store_sk AND ss_sold_date_sk = d1.d_date_sk
    AND ss_item_sk = i_item_sk AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number AND ss_item_sk = cs_ui.cs_item_sk
    AND i_color IN ('green', 'red', 'blue', 'pink', 'white', 'black')
    AND i_current_price BETWEEN 1 AND 100
  GROUP BY i_product_name, i_item_sk, s_store_name, d1.d_year)
SELECT cs1.product_name, cs1.store_name, cs1.syear AS year1,
       cs2.syear AS year2, cs1.cnt AS cnt1, cs2.cnt AS cnt2,
       cs1.s1 AS s11, cs1.s2 AS s21, cs1.s3 AS s31,
       cs2.s1 AS s12, cs2.s2 AS s22, cs2.s3 AS s32
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk AND cs1.syear = 1999
  AND cs2.syear = 2000 AND cs2.cnt <= cs1.cnt
  AND cs1.store_name = cs2.store_name
ORDER BY cs1.product_name, cs1.store_name, cs2.cnt
"""

QUERIES["q70"] = """
SELECT sum(ss_net_profit) AS total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) AS lochierarchy
FROM store_sales, date_dim d1, store
WHERE d1.d_month_seq BETWEEN 24 AND 35
  AND d1.d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
  AND s_state IN
      (SELECT s_state
       FROM (SELECT s_state, rank() OVER (PARTITION BY s_state
                                          ORDER BY sum(ss_net_profit) DESC)
                      AS ranking
             FROM store_sales, store, date_dim
             WHERE d_month_seq BETWEEN 24 AND 35
               AND d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
             GROUP BY s_state) tmp1
       WHERE ranking <= 5)
GROUP BY ROLLUP (s_state, s_county)
ORDER BY lochierarchy DESC, s_state, s_county
"""

# q72: d3.d_date > d1.d_date + 5 rides the day-indexed date_sk arithmetic
# (d_date_sk IS the day number in the generated calendar).
QUERIES["q72"] = """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) AS no_promo,
       sum(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) AS promo,
       count(*) AS total_cnt
FROM catalog_sales
JOIN inventory ON cs_item_sk = inv_item_sk
JOIN warehouse ON w_warehouse_sk = inv_warehouse_sk
JOIN item ON i_item_sk = cs_item_sk
JOIN customer_demographics ON cs_bill_cdemo_sk = cd_demo_sk
JOIN household_demographics ON cs_bill_hdemo_sk = hd_demo_sk
JOIN date_dim d1 ON cs_sold_date_sk = d1.d_date_sk
JOIN date_dim d2 ON inv_date_sk = d2.d_date_sk
JOIN date_dim d3 ON cs_ship_date_sk = d3.d_date_sk
LEFT JOIN promotion ON cs_promo_sk = p_promo_sk
LEFT JOIN catalog_returns ON cr_item_sk = cs_item_sk
                          AND cr_order_number = cs_order_number
WHERE d1.d_week_seq = d2.d_week_seq AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date_sk > d1.d_date_sk + 5 AND hd_buy_potential = '>10000'
  AND d1.d_year = 1999 AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100
"""

QUERIES["q83"] = """
WITH date_set AS (
  SELECT d_date_sk FROM date_dim
  WHERE d_week_seq IN (SELECT d_week_seq FROM date_dim
                       WHERE d_date IN (date '2000-06-30',
                                        date '2000-09-27',
                                        date '2000-11-17'))),
sr_items AS (
  SELECT i_item_id AS item_id, sum(sr_return_quantity) AS sr_item_qty
  FROM store_returns, item, date_set
  WHERE sr_item_sk = i_item_sk AND sr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
cr_items AS (
  SELECT i_item_id AS item_id, sum(cr_return_quantity) AS cr_item_qty
  FROM catalog_returns, item, date_set
  WHERE cr_item_sk = i_item_sk AND cr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
wr_items AS (
  SELECT i_item_id AS item_id, sum(wr_return_quantity) AS wr_item_qty
  FROM web_returns, item, date_set
  WHERE wr_item_sk = i_item_sk AND wr_returned_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT sr_items.item_id, sr_item_qty,
       sr_item_qty * 1.0 / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
         * 100 AS sr_dev,
       cr_item_qty,
       cr_item_qty * 1.0 / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
         * 100 AS cr_dev,
       wr_item_qty,
       wr_item_qty * 1.0 / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
         * 100 AS wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 AS average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id, sr_item_qty
"""

# q95: web_sales has no ws_ship_addr_sk / ws_ext_ship_cost in the generated
# subset — ws_bill_addr_sk and ws_ext_list_price substitute (same types).
QUERIES["q95"] = """
WITH ws_wh AS (
  SELECT ws1.ws_order_number
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT count(DISTINCT ws1.ws_order_number) AS order_count,
       sum(ws_ext_list_price) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE ws1.ws_ship_date_sk = d_date_sk
  AND d_date BETWEEN date '2000-02-01' AND date '2000-04-01'
  AND ws1.ws_bill_addr_sk = ca_address_sk AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk AND web_company_name = 'pri0'
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number = ws_wh.ws_order_number)
"""
