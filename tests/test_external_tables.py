"""Read-only external parquet tables (connector framework, first axis;
reference: be/src/connector/ + file external tables)."""

import numpy as np
import pytest

from starrocks_tpu.runtime.session import Session


@pytest.fixture()
def ext_dir(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    d = tmp_path / "lake"
    d.mkdir()
    for i in range(3):
        t = pa.table({
            "k": pa.array([i * 10 + j for j in range(10)], pa.int64()),
            "cat": pa.array([f"c{(i * 10 + j) % 4}" for j in range(10)]),
            "x": pa.array([float(j) + i for j in range(10)], pa.float64()),
        })
        pq.write_table(t, d / f"part-{i}.parquet")
    return str(d)


def test_external_scan_and_joins(ext_dir):
    s = Session()
    s.sql(f"create external table lake from '{ext_dir}'")
    assert s.sql("select count(*), min(k), max(k) from lake").rows() == \
        [(30, 0, 29)]
    r = s.sql("select cat, count(*), sum(x) from lake group by cat "
              "order by cat").rows()
    assert len(r) == 4 and sum(row[1] for row in r) == 30
    # joins with native tables work unchanged
    s.sql("create table dim (cat varchar, label varchar)")
    s.sql("insert into dim values ('c0', 'zero'), ('c1', 'one')")
    r = s.sql("select d.label, count(*) from lake l join dim d "
              "on l.cat = d.cat group by d.label order by 1").rows()
    assert [x[0] for x in r] == ["one", "zero"]


def test_external_metadata_only_row_count(ext_dir):
    from starrocks_tpu.storage.external import ExternalTableHandle

    h = ExternalTableHandle("lake", ext_dir)
    assert h.row_count == 30        # footers only
    assert h._table is None         # no data loaded yet
    assert len(h.schema.names) == 3


def test_external_rejects_writes(ext_dir):
    s = Session()
    s.sql(f"create external table lake from '{ext_dir}'")
    for stmt in ("insert into lake values (1, 'c0', 1.0)",
                 "delete from lake where k = 1",
                 "update lake set x = 0 where k = 1"):
        with pytest.raises(ValueError, match="EXTERNAL"):
            s.sql(stmt)
    # DROP unregisters without touching the files
    s.sql("drop table lake")
    import os

    assert len(os.listdir(ext_dir)) == 3


def test_external_glob_and_info_schema(ext_dir):
    s = Session()
    s.sql(f"create external table l2 from '{ext_dir}/part-*.parquet'")
    assert s.sql("select count(*) from l2").rows() == [(30,)]
    r = dict(s.sql("select table_name, table_type from "
                   "information_schema.tables").rows())
    assert "l2" in r


def test_external_defs_survive_restart(ext_dir, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    store = tmp_path / "store"
    s = Session(data_dir=str(store))
    s.sql(f"create external table lake from '{ext_dir}'")
    assert s.sql("select count(*) from lake").rows() == [(30,)]
    s2 = Session(data_dir=str(store))
    assert s2.sql("select count(*) from lake").rows() == [(30,)]
    # a new file appears after CREATE: refresh sees it
    pq.write_table(pa.table({"k": pa.array([99], pa.int64()),
                             "cat": pa.array(["c9"]),
                             "x": pa.array([1.0], pa.float64())}),
                   ext_dir + "/part-9.parquet")
    s2.catalog.get_table("lake").invalidate()
    s2.cache.invalidate("lake")
    assert s2.sql("select count(*) from lake").rows() == [(31,)]
    s2.sql("drop table lake")
    s3 = Session(data_dir=str(store))
    assert s3.catalog.get_table("lake") is None


def test_external_rejects_load_csv_and_alter(ext_dir, tmp_path):
    s = Session()
    s.sql(f"create external table lake from '{ext_dir}'")
    csv = tmp_path / "x.csv"
    csv.write_text("1,c0,1.0\n")
    import pytest as _pt

    with _pt.raises(ValueError, match="EXTERNAL"):
        s.load_csv("lake", str(csv))
    with _pt.raises(ValueError, match="EXTERNAL"):
        s.sql("alter table lake add column extra int")


def test_external_orc_table(tmp_path):
    """ORC external tables (reference: be/src/formats/orc/) read through
    the same lazy host-table path as parquet; mixed directories merge."""
    import pyarrow as pa
    import pyarrow.orc as po

    d = tmp_path / "orcdir"
    d.mkdir()
    t1 = pa.table({"k": [1, 2, 3], "v": ["a", "b", "a"]})
    t2 = pa.table({"k": [4, 5], "v": ["c", "a"]})
    po.write_table(t1, str(d / "part1.orc"))
    po.write_table(t2, str(d / "part2.orc"))

    s = Session()
    s.sql(f"create external table eorc from '{d}'")
    # schema/rowcount from footers only
    assert s.sql("describe eorc") == [
        ("k", "BIGINT", "YES"), ("v", "VARCHAR", "YES")]
    assert s.sql("select count(*) from eorc").rows() == [(5,)]
    assert s.sql(
        "select v, count(*) c from eorc group by v order by v").rows() == [
        ("a", 3), ("b", 1), ("c", 1)]
    assert s.sql(
        "select sum(k) from eorc where v = 'a'").rows() == [(9,)]
    with pytest.raises(ValueError, match="EXTERNAL"):
        s.sql("insert into eorc values (9, 'z')")
