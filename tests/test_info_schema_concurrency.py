"""information_schema breadth + concurrent multi-client sessions
(VERDICT r4 item 10; reference: be/src/schema_scanner/ + the FE audit log)."""

import threading

import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.mysql_service import MySQLServer
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog

from tests.test_mysql_protocol import FullClient


@pytest.fixture()
def sess(tmp_path):
    s = Session(data_dir=str(tmp_path))
    s.sql("create table facts (k int, v double)")
    s.sql("insert into facts values (1, 1.5), (2, 2.5), (3, null)")
    s.sql("create view v_facts as select k from facts where v is not null")
    s.sql("create materialized view mv_sum as "
          "select k, sum(v) as s from facts group by k")
    return s


def test_schemata_views_tables(sess):
    r = sess.sql("select schema_name from information_schema.schemata "
                 "order by 1").rows()
    assert r == [("default",), ("information_schema",)]
    r = dict(sess.sql("select table_name, table_type "
                      "from information_schema.tables").rows())
    assert r["facts"] == "BASE TABLE"
    assert r["v_facts"] == "VIEW"
    assert r["mv_sum"] == "MATERIALIZED VIEW"
    r = dict((a, (b, c)) for a, b, c in sess.sql(
        "select table_name, view_definition, view_type "
        "from information_schema.views").rows())
    assert "select k from facts" in r["v_facts"][0]
    assert r["mv_sum"][1] == "MATERIALIZED VIEW"


def test_statistics_and_storage_views(sess):
    stats = {(t, c): (n, mn, mx, az) for t, c, n, mn, mx, az in sess.sql(
        "select * from information_schema.statistics").rows()}
    assert stats[("facts", "k")][:3] == (3, "1", "3")  # exact NDV + bounds
    tablets = sess.sql("select table_name, rows from "
                       "information_schema.tablets where table_name = "
                       "'facts'").rows()
    assert sum(r[1] for r in tablets) == 3
    parts = sess.sql("select table_name, partition_name, rows from "
                     "information_schema.partitions "
                     "where table_name = 'facts'").rows()
    assert sum(p[2] for p in parts) == 3


def test_query_log(sess):
    sess.sql("select count(*) from facts")
    log = sess.sql("select user, statement, state, rows from "
                   "information_schema.query_log").rows()
    assert any("count(*)" in r[1] and r[0] == "root" and r[2] == "OK"
               for r in log)
    with pytest.raises(Exception):
        sess.sql("select nope from facts")
    log = sess.sql("select statement, state from "
                   "information_schema.query_log").rows()
    assert any(r[1] == "ERR" and "nope" in r[0] for r in log)


def test_show_full_tables_over_the_wire(sess):
    srv = MySQLServer(sess, port=0).start()
    try:
        c = FullClient("127.0.0.1", srv.port)
        cols, rows = c.query("show full tables")
        assert cols == ["table_name", "table_type"]
        d = dict(rows)
        assert d["facts"] == "BASE TABLE" and d["v_facts"] == "VIEW"
        c.quit()
    finally:
        srv.shutdown()


def test_concurrent_sessions_ddl_query_insert(sess):
    """Two byte-level MySQL clients + direct session traffic running DDL,
    INSERT, and SELECT concurrently must serialize correctly (no torn
    state, every client sees its own writes)."""
    srv = MySQLServer(sess, port=0).start()
    errors = []

    def worker(wid: int):
        try:
            c = FullClient("127.0.0.1", srv.port)
            c.query(f"create table w{wid} (a int, b varchar)")
            total = 0
            for i in range(10):
                c.query(f"insert into w{wid} values ({i}, 'x{wid}_{i}')")
                total += 1
                _, rows = c.query(f"select count(*) from w{wid}")
                assert rows == [(str(total),)], (wid, i, rows)
                # interleave reads of the shared table + info schema
                _, rows = c.query("select count(*) from facts")
                assert rows[0][0] >= "3"
                c.query("select table_name from information_schema.tables")
            _, rows = c.query(
                f"select b from w{wid} where a = 7")
            assert rows == [(f"x{wid}_7",)]
            c.query(f"drop table w{wid}")
            c.quit()
        except Exception as e:  # noqa: BLE001
            errors.append((wid, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    srv.shutdown()
    assert not errors, errors
    assert all(f"w{i}" not in sess.catalog.tables for i in range(3))


def test_info_schema_long_tail(tmp_path):
    """The round-5 view breadth (reference: be/src/schema_scanner/ ~60
    views): every view answers through plain SELECT with typed columns."""
    from starrocks_tpu.runtime.session import Session

    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table base (k int, v varchar) "
          "distributed by hash(k) buckets 2")
    s.sql("insert into base values (1, 'x'), (2, 'y'), (2, 'z')")
    s.sql("create materialized view mvx as "
          "select v, count(*) c from base group by v")
    s.sql("create user io_user identified by 'p'")
    s.sql("grant select on base to io_user")
    s.sql("""create function io_twice(a bigint) returns bigint as '
def io_twice(a):
    return a * 2
'""")
    s.sql("create resource group io_rg with (concurrency_limit = 2)")

    q = lambda v: s.sql(f"select * from information_schema.{v}").rows()  # noqa: E731
    assert ("mvx", ) == tuple(r[0] for r in q("materialized_views"))
    mv = q("materialized_views")[0]
    assert mv[3] == 1 and mv[2] == 3  # fresh, 3 groups... rows
    assert [r[0] for r in q("routines")] == ["io_twice"]
    assert any(r[0] == "max_recompiles" for r in q("session_variables"))
    assert any(r[0] == "max_recompiles" for r in q("global_variables"))
    assert ("'io_user'@'%'", "base", "SELECT") in q("table_privileges")
    assert any(g == "'root'@'%'" for g, *_ in q("user_privileges"))
    assert q("referential_constraints") == []
    assert q("engines")[0][0] == "OLAP_TPU"
    assert q("character_sets")[0][0] == "utf8mb4"
    assert q("collations")[0][0] == "utf8mb4_bin"
    rowsets = q("rowsets")
    assert {r[0] for r in rowsets} >= {"base"}
    assert sum(r[3] for r in rowsets if r[0] == "base") == 3
    loads = q("loads")
    assert any(r[1] == "base" and r[2] == 3 for r in loads)
    assert q("compactions") == []  # nothing compacted yet
    stats = q("column_statistics")
    assert ("base", "k", 2) in stats
    # unique-key views populate for PRIMARY KEY tables
    s.sql("create table pkt (id int, x int, primary key (id))")
    assert ("pkt", "id", "UNIQUE") in q("key_column_usage")
    assert ("pkt", "UNIQUE") in q("table_constraints")
    d = tmp_path / "ext"
    d.mkdir()
    import pyarrow as pa
    import pyarrow.parquet as pq
    pq.write_table(pa.table({"a": [1]}), str(d / "f.parquet"))
    s.sql(f"create external table io_ext from '{d}'")
    assert ("io_ext", str(d)) in q("external_tables")
