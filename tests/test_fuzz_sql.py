"""Randomized differential testing: generated query specs run both through
the engine (as SQL) and through pandas (as a direct evaluation of the same
spec). Reference analog: the builtin-function fuzz tier
(be/test/fuzzy/builtin_functions_fuzzy_test.cpp) lifted to whole queries."""

import math

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session

N_CASES = 30
ROWS = 500


def make_tables(rng):
    t1 = pd.DataFrame({
        "g": rng.choice(["a", "b", "c", None], ROWS, p=[0.4, 0.3, 0.2, 0.1]),
        "h": rng.integers(0, 4, ROWS),
        "x": np.round(rng.normal(10, 5, ROWS), 3),
        "y": rng.integers(-50, 50, ROWS),
        "k": rng.integers(1, 40, ROWS),
    })
    t1.loc[rng.random(ROWS) < 0.08, "x"] = None
    t2 = pd.DataFrame({
        "k": np.arange(1, 41),
        "w": np.round(rng.normal(0, 3, 40), 3),
        "c": rng.choice(["u", "v"], 40),
    })
    return t1, t2


def load_session(t1, t2):
    s = Session()
    s.sql("create table t1 (g varchar, h int, x double, y int, k int)")
    s.sql("create table t2 (k int, w double, c varchar)")
    for df, name in ((t1, "t1"), (t2, "t2")):
        rows = []
        for r in df.itertuples(index=False):
            vals = []
            for v in r:
                if v is None or (isinstance(v, float) and math.isnan(v)):
                    vals.append("null")
                elif isinstance(v, str):
                    vals.append(f"'{v}'")
                else:
                    vals.append(repr(v))
            rows.append("(" + ", ".join(vals) + ")")
        s.sql(f"insert into {name} values " + ", ".join(rows))
    return s


def gen_spec(rng):
    """A random query spec over t1 (optionally joined to t2)."""
    spec = {
        "join": bool(rng.random() < 0.4),
        "filters": [],
        "group": list(rng.choice(["g", "h"], size=rng.integers(1, 3), replace=False)),
        "aggs": [],
        "str_filter": (rng.random() < 0.3),
        "having_min_cnt": int(rng.integers(0, 4)) if rng.random() < 0.4 else None,
        "order_limit": int(rng.integers(1, 6)) if rng.random() < 0.4 else None,
    }
    for _ in range(rng.integers(0, 3)):
        col, lo, hi = rng.choice([("y", -50, 50), ("k", 1, 40), ("h", 0, 4)])
        op = rng.choice(["<", ">=", "="])
        spec["filters"].append((col, op, int(rng.integers(int(lo), int(hi)))))
    pool = ["x", "y"] + (["w"] if spec["join"] else [])
    for _ in range(rng.integers(1, 4)):
        fn = rng.choice(["sum", "count", "min", "max", "avg"])
        spec["aggs"].append((fn, rng.choice(pool)))
    return spec


def spec_to_sql(spec):
    aggs = ", ".join(
        f"{fn}({col}) a{i}" for i, (fn, col) in enumerate(spec["aggs"])
    )
    keys = ", ".join(spec["group"])
    sql = f"select {keys}, {aggs}, count(*) cnt from t1"
    if spec["join"]:
        sql += ", t2 where t1.k = t2.k"
        glue = " and "
    else:
        glue = " where "
    for col, op, v in spec["filters"]:
        q = f"t1.{col}" if spec["join"] else col
        sql += f"{glue}{q} {op} {v}"
        glue = " and "
    if spec["str_filter"]:
        g = "t1.g" if spec["join"] else "g"
        sql += f"{glue}{g} in ('a', 'c')"
    sql += f" group by {keys}"
    if spec["having_min_cnt"] is not None:
        sql += f" having count(*) >= {spec['having_min_cnt']}"
    if spec["order_limit"] is not None:
        sql += f" order by cnt desc, {keys} limit {spec['order_limit']}"
    return sql


def spec_to_pandas(spec, t1, t2):
    df = t1.merge(t2, on="k") if spec["join"] else t1
    for col, op, v in spec["filters"]:
        if op == "<":
            df = df[df[col] < v]
        elif op == ">=":
            df = df[df[col] >= v]
        else:
            df = df[df[col] == v]
    if spec["str_filter"]:
        df = df[df["g"].isin(["a", "c"])]
    if df.empty:
        return []
    g = df.groupby(spec["group"], dropna=False)
    out = {}
    for i, (fn, col) in enumerate(spec["aggs"]):
        if fn == "count":
            out[f"a{i}"] = g[col].count()
        else:
            out[f"a{i}"] = getattr(g[col], fn if fn != "avg" else "mean")()
    out["cnt"] = g.size()
    res = pd.DataFrame(out).reset_index()
    if spec["having_min_cnt"] is not None:
        res = res[res["cnt"] >= spec["having_min_cnt"]]
    if spec["order_limit"] is not None:
        res = res.sort_values(
            ["cnt"] + spec["group"], ascending=[False] + [True] * len(spec["group"])
        ).head(spec["order_limit"])
    return [tuple(r) for r in res.itertuples(index=False)]


def _norm_cell(v):
    if v is None:
        return None
    if isinstance(v, float) and math.isnan(v):
        return None
    if isinstance(v, (np.floating, np.integer)):
        v = v.item()
    if isinstance(v, float):
        return round(v, 6)
    return v


def _norm(rows):
    return sorted(
        [tuple(_norm_cell(c) for c in r) for r in rows],
        key=lambda t: tuple((x is None, x) for x in t),
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(20260729)
    t1, t2 = make_tables(rng)
    return load_session(t1, t2), t1, t2, rng


def test_fuzz_specs(env):
    s, t1, t2, rng = env
    failures = []
    for case in range(N_CASES):
        spec = gen_spec(rng)
        sql = spec_to_sql(spec)
        try:
            got = _norm(s.sql(sql).rows())
            exp = _norm(spec_to_pandas(spec, t1, t2))
            if len(got) != len(exp):
                failures.append((case, sql, f"rows {len(got)} vs {len(exp)}"))
                continue
            for gr, er in zip(got, exp):
                for gv, ev in zip(gr, er):
                    if isinstance(gv, float) and isinstance(ev, float):
                        if not math.isclose(gv, ev, rel_tol=1e-6, abs_tol=1e-6):
                            failures.append((case, sql, f"{gv} vs {ev}"))
                            break
                    elif gv != ev:
                        failures.append((case, sql, f"{gv!r} vs {ev!r}"))
                        break
                else:
                    continue
                break
        except Exception as e:
            failures.append((case, sql, f"{type(e).__name__}: {e}"))
    assert not failures, failures[:3]
