"""EXPLAIN ANALYZE + query-profile service (reference analogs:
be/src/common/runtime_profile.h per-operator counters, FE ProfileManager
+ audit log, /api/query profile endpoints).

Covers: per-operator est-vs-observed annotation against the feedback
observation channel on a join+agg (monolithic single-chip AND the
distributed fragment path, byte-identical result rows), ProfileManager
retention/LRU/slow-ring bounds, histogram bucket math + Prometheus
exposition golden, Chrome trace-event export schema, killed-query
profiles reporting the failed stage, and a chaos scenario asserting the
profile store leaks nothing across mid-execute failures."""

import json
import re

import pytest

import starrocks_tpu.sql.distributed as D
from starrocks_tpu.runtime import failpoint
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.metrics import Histogram
from starrocks_tpu.runtime.profile import (
    PROFILE_MANAGER, ProfileManager, trace_json)
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import tpch_catalog

from tpch_queries import QUERIES

JOIN_AGG = ("select t.a, sum(t.b) sb from t join u on t.a = u.a "
            "group by t.a order by t.a")


def _small_sess():
    s = Session()
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1,2),(1,3),(2,4),(3,5),(2,6)")
    s.sql("create table u (a int, c int)")
    s.sql("insert into u values (1,10),(1,11),(2,20)")
    return s


def _ann(out: str, node: str) -> dict:
    """Parse the [#o est=.. rows=.. cap=..] annotation off a node line."""
    m = re.search(rf"{node}\[[^\n]*\[#(\d+)([^\]]*)\]", out)
    assert m, f"no annotation on {node} in:\n{out}"
    d = {"ord": int(m.group(1))}
    for k, v in re.findall(r"(est|rows|cap)=(\d+)", m.group(2)):
        d[k] = int(v)
    return d


# --- per-operator attribution -------------------------------------------------


def test_explain_analyze_monolithic_observed_rows():
    s = _small_sess()
    base = s.sql(JOIN_AGG).rows()
    out = s.sql("explain analyze " + JOIN_AGG)
    # join true cardinality: a=1 (2x2) + a=2 (2x1) = 6; agg groups = 2
    j = _ann(out, "Join")
    assert j["rows"] == 6 and j["cap"] >= 6
    a = _ann(out, "Agg")
    assert a["rows"] == len(base) == 2
    assert "est=" in out
    # the annotation's observed rows are the same channel the plan-feedback
    # store learns from: the recorded capacity for the join key covers the
    # observed count
    fb = list(s.cache.feedback._entries.values())
    caps = [c for e in fb for c in e["caps"].get("local", {}).items()]
    jc = {k: v for k, v in caps if k.startswith("join_")}
    assert jc and all(v >= 6 for v in jc.values())
    # EXPLAIN ANALYZE executed the real query; result rows unperturbed
    assert s.sql(JOIN_AGG).rows() == base


def test_explain_analyze_counter_groups():
    s = _small_sess()
    out = s.sql("explain analyze " + JOIN_AGG)
    # per-operator counter group renders on the annotated tree and the
    # profile's op# lines agree with the flattened legacy counters
    assert re.search(r"op#\d+ join rows=6", out)


@pytest.fixture(scope="module")
def dist_sess(eight_devices):
    old = D.SHARD_THRESHOLD_ROWS
    old_sh = D.SHUFFLE_AGG_MIN_GROUPS
    D.SHARD_THRESHOLD_ROWS = 10_000
    D.SHUFFLE_AGG_MIN_GROUPS = 4_000
    yield Session(tpch_catalog(sf=0.01), dist_shards=8)
    D.SHARD_THRESHOLD_ROWS = old
    D.SHUFFLE_AGG_MIN_GROUPS = old_sh


def test_explain_analyze_fragment_path_q5(dist_sess):
    """TPC-H q5 (join+agg) annotated on BOTH dist paths: the monolithic
    SPMD program and the fragment IR path produce byte-identical result
    rows and both attribute observed rows per operator."""
    s = dist_sess
    q5 = QUERIES[5]
    outs, rows = {}, {}
    for frag in (False, True):
        config.set("dist_fragments", frag)
        try:
            rows[frag] = s.sql(q5).rows()
            outs[frag] = s.sql("explain analyze " + q5)
        finally:
            config.set("dist_fragments", True)
    assert rows[False] == rows[True]  # byte-identity across paths
    for frag, out in outs.items():
        a = _ann(out, "Agg")
        assert a["rows"] == len(rows[frag]), f"frag={frag}:\n{out}"
        assert re.search(r"Join\[[^\n]*rows=\d+", out), f"frag={frag}"
        assert "ctrs{" in out, f"frag={frag}: no counter groups"
    # fragment run carries per-fragment timings in the profile tail
    assert re.search(r"fragment_\d+_(compile|execute)", outs[True])


# --- ProfileManager retention -------------------------------------------------


def _entry(qid, ms=1, sql="select 1", state="done"):
    return dict(qid=qid, user="root", sql=sql, state=state, ms=ms,
                rows=0, queue_wait_ms=0, stage="executor::fetch_results",
                profile=None)


def test_profile_manager_retention_and_lru():
    pm = ProfileManager()
    config.set("profile_history_size", 4)
    try:
        for q in range(1, 8):
            pm.register(**_entry(q))
        assert pm.stats()["entries"] == 4
        assert [e["query_id"] for e in pm.snapshot()] == [4, 5, 6, 7]
        # get() is an LRU touch: qid 4 survives the next eviction, 5 goes
        assert pm.get(4)["query_id"] == 4
        pm.register(**_entry(8))
        got = [e["query_id"] for e in pm.snapshot()]
        assert 4 in got and 5 not in got
        assert pm.get(5) is None
    finally:
        config.set("profile_history_size", 64)


def test_profile_manager_bytes_budget():
    pm = ProfileManager()
    config.set("profile_history_bytes", 4096)
    try:
        big = "select '" + "x" * 2000 + "'"
        for q in range(1, 6):
            pm.register(**_entry(q, sql=big))
        st = pm.stats()
        assert st["bytes"] <= 4096 and st["entries"] >= 1
    finally:
        config.set("profile_history_bytes", 8 << 20)


def test_profile_manager_slow_ring():
    pm = ProfileManager()
    config.set("slow_query_ms", 100)
    config.set("profile_history_size", 2)
    try:
        pm.register(**_entry(1, ms=500))   # slow
        pm.register(**_entry(2, ms=1))
        pm.register(**_entry(3, ms=1))
        pm.register(**_entry(4, ms=1))     # 1 evicted from history
        e = pm.get(1)                      # ...but the slow ring kept it
        assert e is not None and e["slow"] is True
        assert pm.get(2) is None           # fast + evicted = gone
        # ring itself is bounded
        for q in range(10, 10 + 2 * ProfileManager.SLOW_RING):
            pm.register(**_entry(q, ms=500))
        assert pm.stats()["slow"] <= ProfileManager.SLOW_RING
    finally:
        config.set("slow_query_ms", 0)
        config.set("profile_history_size", 64)


def test_slow_query_flag_in_query_log():
    s = _small_sess()
    config.set("slow_query_ms", 1)  # everything counts as slow
    try:
        s.sql("select a from t")
        r = s.sql("select query_id, slow from information_schema.query_log "
                  "where statement like '%from t%' and slow = 1")
        assert r.rows(), "slow flag never set in query_log"
        qid = r.rows()[-1][0]
        assert qid > 0
        assert PROFILE_MANAGER.get(qid)["slow"] is True
    finally:
        config.set("slow_query_ms", 0)


# --- histogram math + exposition ----------------------------------------------


def test_histogram_bucket_math_and_exposition_golden():
    h = Histogram("sr_tpu_unit_test_ms", "unit test", buckets=(1, 10, 100))
    for v in (0.5, 1.0, 5, 50, 500):
        h.observe(v)
    counts, s, n = h.snapshot()
    # 0.5 and 1.0 land in le=1 (inclusive upper bound), 5 in le=10,
    # 50 in le=100, 500 in +Inf
    assert counts == [2, 1, 1, 1] and n == 5 and s == 556.5
    golden = [
        "# HELP sr_tpu_unit_test_ms unit test",
        "# TYPE sr_tpu_unit_test_ms histogram",
        'sr_tpu_unit_test_ms_bucket{le="1"} 2',
        'sr_tpu_unit_test_ms_bucket{le="10"} 3',
        'sr_tpu_unit_test_ms_bucket{le="100"} 4',
        'sr_tpu_unit_test_ms_bucket{le="+Inf"} 5',
        "sr_tpu_unit_test_ms_sum 556.5",
        "sr_tpu_unit_test_ms_count 5",
    ]
    assert h.render() == golden
    # percentile interpolates within the owning bucket; +Inf clamps
    assert 0 < h.percentile(0.5) <= 10
    assert h.percentile(0.99) == 100  # clamped to largest finite bound
    assert Histogram("sr_tpu_unit_empty").percentile(0.5) == 0.0


def test_latency_histograms_observe_by_statement_class():
    from starrocks_tpu.runtime.lifecycle import (
        LATENCY_DML_MS, LATENCY_READ_MS)

    r0, d0 = LATENCY_READ_MS.value, LATENCY_DML_MS.value
    s = _small_sess()  # DDL + DML
    s.sql("select a from t")
    assert LATENCY_READ_MS.value > r0
    assert LATENCY_DML_MS.value > d0
    from starrocks_tpu.runtime.metrics import metrics

    text = metrics.render_prometheus()
    for fam in ("sr_tpu_query_latency_ms_read", "sr_tpu_compile_ms"):
        assert f"# TYPE {fam} histogram" in text
        assert f'{fam}_bucket{{le="+Inf"}}' in text
        assert f"{fam}_sum" in text and f"{fam}_count" in text


# --- trace export -------------------------------------------------------------


def test_trace_export_schema():
    s = _small_sess()
    s.sql("select a, sum(b) sb from t group by a")
    qid = s.sql("select max(query_id) from information_schema.query_log"
                ).rows()[0][0]
    e = PROFILE_MANAGER.get(qid)
    assert e is not None
    tr = trace_json(e)
    assert set(tr) == {"traceEvents", "displayTimeUnit", "otherData"}
    assert tr["displayTimeUnit"] == "ms"
    evs = tr["traceEvents"]
    assert evs, "no trace events for an executed query"
    names = {ev["name"] for ev in evs}
    # the full lifecycle is visible: parse -> analyze -> optimize ->
    # compile -> fetch
    for stage in ("parse", "analyze", "optimize", "compile_and_run",
                  "fetch_results"):
        assert stage in names, f"{stage} missing from {names}"
    last = 0.0
    for ev in evs:
        assert ev["ph"] == "X" and ev["dur"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert ev["ts"] >= last  # sorted for the viewer
        last = ev["ts"]


def test_trace_synthesizes_admission_wait():
    e = {"query_id": 7, "sql": "select 1", "state": "done", "ms": 12,
         "queue_wait_ms": 5, "user": "root",
         "profile": {"name": "query", "spans": [["parse", 1000.0, 0.001]],
                     "counters": {}, "infos": {}, "children": []}}
    tr = trace_json(e)
    names = [ev["name"] for ev in tr["traceEvents"]]
    assert names[0] == "admission_wait"
    assert tr["traceEvents"][0]["dur"] == pytest.approx(5000)  # us


# --- failure paths ------------------------------------------------------------


def test_killed_query_profile_reports_failed_stage():
    s = _small_sess()
    with failpoint.scoped("executor::before_run"):
        with pytest.raises(failpoint.FailPointError):
            s.sql("select a, sum(b) q from t group by a")
    qid = s.sql("select max(query_id) from information_schema.query_log"
                ).rows()[0][0]
    e = PROFILE_MANAGER.get(qid)
    # wire rows for the SQL above succeed (the SELECT on query_log bumps
    # qid by one — the failed query is the one before it)
    if e is None or e["state"] != "error":
        e = PROFILE_MANAGER.get(qid - 1)
    assert e is not None and e["state"] == "error"
    assert e["stage"], "failed query retained no stage"


def test_chaos_profile_store_zero_leak():
    """Mid-execute failures must not grow the profile store past its
    bounds or corrupt its byte accounting — the chaos invariant."""
    s = _small_sess()
    config.set("profile_history_size", 8)
    try:
        for i in range(12):
            with failpoint.scoped("executor::before_run"):
                with pytest.raises(failpoint.FailPointError):
                    s.sql(f"select a + {i} from t")
        st = PROFILE_MANAGER.stats()
        assert st["entries"] <= 8
        assert st["slow"] <= ProfileManager.SLOW_RING
        # byte accounting stays consistent with the retained entries
        with PROFILE_MANAGER._lock:
            real = sum(e["_bytes"] for e in PROFILE_MANAGER._entries.values())
            assert real == PROFILE_MANAGER._bytes
    finally:
        config.set("profile_history_size", 64)


# --- SQL surfaces -------------------------------------------------------------


def test_show_profile_for_query_and_info_schema():
    s = _small_sess()
    s.sql("select a, sum(b) sp from t group by a")
    qid = s.sql("select max(query_id) from information_schema.query_profiles"
                ).rows()[0][0]
    out = s.sql(f"show profile for query {qid - 1}")
    assert f"query {qid - 1} " in out
    r = s.sql("select query_id, state, ms from "
              "information_schema.query_profiles")
    assert any(row[0] == qid for row in r.rows())
    assert s.sql("show profile for query 999999").startswith("no profile")
