"""Low-cardinality (sort-free) aggregation fast path + pallas kernel tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import tpch_catalog


QUERIES = [
    # dict keys, no NULLs
    """select l_returnflag, l_linestatus, sum(l_quantity) q,
       sum(l_extendedprice) p, avg(l_discount) a, count(*) c,
       min(l_extendedprice) mn, max(l_extendedprice) mx
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus order by 1, 2""",
    # boolean-derived key mixes with dict key via CASE? (bool col via expr)
    """select l_returnflag, count(*) c from lineitem
       group by l_returnflag order by 1""",
]


@pytest.fixture(scope="module")
def cat():
    return tpch_catalog(sf=0.01)


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_lowcard_matches_sort_path(cat, qi):
    q = QUERIES[qi]
    fast = Session(cat).sql(q).rows()
    config.set("enable_lowcard_agg", False)
    try:
        slow = Session(cat).sql(q).rows()
    finally:
        config.set("enable_lowcard_agg", True)
    assert len(fast) == len(slow)
    for fr, sr in zip(fast, slow):
        for fv, sv in zip(fr, sr):
            if isinstance(fv, float):
                assert sv == pytest.approx(fv, rel=1e-12, abs=1e-12)
            else:
                assert fv == sv


def test_lowcard_with_nulls_and_two_phase():
    s = Session()
    s.sql("create table t (g varchar, v double)")
    s.sql("insert into t values ('a', 1.0), (null, 2.0), ('a', null), ('b', 4.0), (null, 6.0)")
    q = "select g, count(*) c, count(v) cv, sum(v) s, avg(v) a from t group by g order by g nulls last"
    fast = s.sql(q).rows()
    config.set("enable_lowcard_agg", False)
    try:
        slow = Session(s.catalog).sql(q).rows()
    finally:
        config.set("enable_lowcard_agg", True)
    assert len(fast) == len(slow)
    for fr, sr in zip(fast, slow):
        for fv, sv in zip(fr, sr):
            if isinstance(fv, float):
                # the two paths reduce in different row orders; float sums
                # may differ in the last ulp (esp. on TPU)
                assert sv == pytest.approx(fv, rel=1e-12, abs=1e-12)
            else:
                assert fv == sv
    assert fast[-1][0] is None and fast[-1][1] == 2  # NULL group


def test_lowcard_distributed_two_phase(eight_devices, cat):
    import starrocks_tpu.sql.distributed as D

    old = D.SHARD_THRESHOLD_ROWS
    D.SHARD_THRESHOLD_ROWS = 10_000
    try:
        q = QUERIES[0]
        single = Session(cat).sql(q).rows()
        dist = Session(cat, dist_shards=8).sql(q).rows()
        assert single == dist
    finally:
        D.SHARD_THRESHOLD_ROWS = old


def test_pallas_segment_sum_matches_oracle():
    from starrocks_tpu.ops.pallas_kernels import (
        segment_sum_onehot, segment_sum_pallas,
    )

    rng = np.random.default_rng(0)
    N, G, M = 8192, 8, 4
    gid = jnp.asarray(rng.integers(0, G + 1, N).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(N, M)).astype(np.float32))
    ref = segment_sum_onehot(gid, vals, G)
    pal = segment_sum_pallas(gid, vals, G, block=2048, interpret=True)
    assert jnp.allclose(ref, pal, rtol=1e-4, atol=1e-3)
    exp = np.stack([
        np.asarray(vals)[np.asarray(gid) == g].sum(axis=0) for g in range(G)
    ])
    np.testing.assert_allclose(np.asarray(ref), exp, rtol=1e-3, atol=1e-2)


def test_pallas_strategy_end_to_end(cat):
    """segment_strategy=pallas routes float segment sums through the Pallas
    kernel (interpret mode on CPU) and the query still matches the default
    strategy — the flag-flip correctness gate for real hardware."""
    q = ("select l_returnflag, avg(l_discount) a, var_samp(l_discount) v "
         "from lineitem group by l_returnflag order by 1")
    base = Session(cat).sql(q).rows()
    config.set("segment_strategy", "pallas")
    try:
        pal = Session(cat).sql(q).rows()
    finally:
        config.set("segment_strategy", "auto")
    assert len(base) == len(pal)
    for br, pr in zip(base, pal):
        assert br[0] == pr[0]
        for bv, pv in zip(br[1:], pr[1:]):
            assert pv == pytest.approx(bv, rel=1e-5)


def test_pallas_join_probe_parity():
    """The second Pallas kernel (probe_searchsorted_pallas) matches
    jnp.searchsorted in interpret mode, standalone and through a full
    SQL join flipped on via SET join_probe_strategy='pallas'."""
    import numpy as np
    import jax.numpy as jnp

    from starrocks_tpu.ops.pallas_kernels import probe_searchsorted_pallas

    rng = np.random.RandomState(3)
    build = np.sort(rng.randint(0, 10_000, 512).astype(np.int64))
    probe = rng.randint(-100, 10_100, 4096).astype(np.int64)
    got = np.asarray(probe_searchsorted_pallas(
        jnp.asarray(build), jnp.asarray(probe), block=1024, interpret=True))
    exp = np.searchsorted(build, probe, side="left")
    assert (got == exp).all()

    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session

    s = Session()
    s.sql("create table dimp (k int, name varchar, primary key (k))")
    s.sql("insert into dimp values (1, 'a'), (2, 'b'), (3, 'c')")
    s.sql("create table facts (k int, v int)")
    s.sql("insert into facts values (1, 10), (3, 30), (3, 31), (9, 90)")
    q = ("select name, sum(v) sv from facts, dimp "
         "where facts.k = dimp.k group by name order by name")
    base = s.sql(q).rows()
    s.sql("set join_probe_strategy = 'pallas'")
    try:
        assert s.sql(q).rows() == base == [("a", 10), ("c", 61)]
    finally:
        config.set("join_probe_strategy", "auto")
