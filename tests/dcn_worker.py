"""Worker process for the cross-process (DCN-analog) mesh test.

Launched twice by tests/test_cluster.py. Each process joins the global
mesh via jax.distributed (2 processes x 4 virtual CPU devices = 8 global
shards; on TPU pods the same code spans hosts over DCN), contributes its
process-local rows, and runs ONE jitted shuffle-aggregate step:

    row-sharded values -> all_to_all-style hash repartition by key
    -> per-shard partial sums -> global psum

which is the compiled equivalent of the reference's cross-BE shuffle
exchange (gensrc/proto/internal_service.proto:802-851): the collectives
carry the shuffle, gloo/DCN carries the collectives. Process 0 prints the
per-key totals for the driver test to assert; both processes also run a
heartbeat against the test's ClusterMonitor so the liveness plane is
exercised across REAL process boundaries.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def main():
    pid = int(sys.argv[1])
    coord = sys.argv[2]          # jax.distributed coordinator addr
    mon_port = int(sys.argv[3])  # ClusterMonitor port

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")
    from starrocks_tpu.runtime.cluster import Heartbeater, init_multihost

    devices = init_multihost(coord, num_processes=2, process_id=pid,
                             local_device_count=4)
    hb = Heartbeater("127.0.0.1", mon_port, f"worker-{pid}",
                     interval_s=0.1)

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_shards = len(devices)
    assert n_shards == 8, devices
    mesh = Mesh(np.array(devices), ("dp",))

    # deterministic global data; each process materializes ITS rows only
    rows_per_shard = 16
    total = n_shards * rows_per_shard
    keys = (np.arange(total, dtype=np.int32) * 7) % 5
    vals = np.arange(total, dtype=np.float64)

    sh = NamedSharding(mesh, P("dp"))
    # each process materializes only the shards it hosts (the callback is
    # invoked per LOCAL device with that shard's index range)
    gkeys = jax.make_array_from_callback((total,), sh,
                                         lambda idx: keys[idx])
    gvals = jax.make_array_from_callback((total,), sh,
                                         lambda idx: vals[idx])

    def step(k, v):
        # hash-repartition + partial agg + global merge, all collectives:
        # one-hot per-key partial sums per shard, then psum across shards
        oh = (k[:, None] == jnp.arange(5)[None, :])
        part = jnp.sum(jnp.where(oh, v[:, None], 0.0), axis=0)
        return jax.lax.psum(part, "dp")

    from jax.experimental.shard_map import shard_map

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P("dp"), P("dp")),
                           out_specs=P()))
    out = np.asarray(fn(gkeys, gvals))
    expected = np.array([
        vals[keys == g].sum() for g in range(5)])
    ok = np.allclose(out, expected)
    print(f"proc {pid}: shuffle-agg ok={ok} totals={out.tolist()}",
          flush=True)
    # stay alive briefly so the monitor sees both workers beating
    import time

    time.sleep(1.0)
    hb.stop()
    if not ok:
        sys.exit(3)


if __name__ == "__main__":
    main()
