"""TPC-DS differential suite: each query vs an independent pandas oracle.

Reference analog: the SQL-tester T/R suites + the 99-query benchmark
(docs/en/benchmarking/TPC_DS_Benchmark.md). Comparison is order-insensitive
(rows keyed by their non-float cells; floats compared approximately) because
ties at LIMIT boundaries are resolved arbitrarily; limits are asserted to be
non-binding at the test scale except where a total order makes truncation
deterministic.
"""

import math

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.datagen.tpcds import tpcds_catalog

from tests.tpcds_queries import QUERIES

SF = 0.01


@pytest.fixture(scope="module")
def env():
    cat = tpcds_catalog(sf=SF)
    s = Session(cat)
    F = {name: cat.get_table(name).table.to_pandas()
         for name in cat.tables}
    return s, F


def _is_float(v):
    return isinstance(v, (float, np.floating))


def _norm(v):
    if v is None:
        return None
    if _is_float(v):
        return None if math.isnan(v) else float(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, pd.Timestamp):
        return v.to_pydatetime().date()
    return v


def _nf_key(row):
    """Bucket key from the non-float cells only (floats masked): rows are
    aligned exactly on discrete cells, then floats matched by sorted order
    within the bucket — no rounding-boundary misalignment."""
    return tuple("\0f" if _is_float(v) else (v if v is not None else "\0n")
                 for v in row)


def _floats_of(row):
    return tuple(float(v) for v in row if _is_float(v))


def compare(got_rows, exp_df, limit=None):
    exp_rows = [tuple(_norm(v) for v in r)
                for r in exp_df.itertuples(index=False)]
    if limit is not None:
        assert len(exp_rows) <= limit, (
            f"oracle returned {len(exp_rows)} rows; LIMIT {limit} binds — "
            "tighten the query's filters so truncation can't be ambiguous")
    got_rows = [tuple(_norm(v) for v in r) for r in got_rows]
    assert len(got_rows) == len(exp_rows), (len(got_rows), len(exp_rows))
    from collections import defaultdict

    gb, eb = defaultdict(list), defaultdict(list)
    for r in got_rows:
        gb[_nf_key(r)].append(r)
    for r in exp_rows:
        eb[_nf_key(r)].append(r)
    assert set(gb) == set(eb), (
        f"row-key mismatch: only-got={list(set(gb) - set(eb))[:3]} "
        f"only-exp={list(set(eb) - set(gb))[:3]}")
    for k, grows in gb.items():
        erows = eb[k]
        assert len(grows) == len(erows), (k, len(grows), len(erows))
        for g, e in zip(sorted(grows, key=_floats_of),
                        sorted(erows, key=_floats_of)):
            for gv, ev in zip(g, e):
                if gv is None or ev is None:
                    assert gv is None and ev is None, (g, e)
                elif _is_float(gv) or _is_float(ev):
                    assert np.isclose(float(gv), float(ev),
                                      rtol=1e-6, atol=1e-2), (g, e)
                else:
                    assert gv == ev, (g, e)


def run(env, qid, oracle, limit=100):
    s, F = env
    got = s.sql(QUERIES[qid]).rows()
    compare(got, oracle(F), limit)


def rollup_levels(df, keys, agg_fn, grouping_cols=()):
    """Pandas ROLLUP: one aggregate per prefix level; dropped keys -> NaN.
    agg_fn(sub_df) -> dict of aggregate values. grouping_cols adds
    __grouping_i indicator columns."""
    frames = []
    for k in range(len(keys), -1, -1):
        keep = list(keys[:k])
        if keep:
            g = df.groupby(keep, dropna=False, sort=False)
            rows = []
            for vals, sub in g:
                if not isinstance(vals, tuple):
                    vals = (vals,)
                r = dict(zip(keep, vals))
                r.update(agg_fn(sub))
                rows.append(r)
        else:
            rows = [agg_fn(df)]
        lvl = pd.DataFrame(rows)
        for kk in keys[k:]:
            lvl[kk] = None
        for i, _ in enumerate(keys):
            if f"__g{i}" in grouping_cols or grouping_cols == "all":
                lvl[f"__g{i}"] = 0 if i < k else 1
        frames.append(lvl)
    return pd.concat(frames, ignore_index=True)


# --- the star-join family --------------------------------------------------

def test_q3(env):
    def oracle(F):
        x = F["store_sales"].merge(
            F["date_dim"][F["date_dim"].d_moy == 11],
            left_on="ss_sold_date_sk", right_on="d_date_sk",
        ).merge(F["item"][F["item"].i_manufact_id == 7],
                left_on="ss_item_sk", right_on="i_item_sk")
        g = x.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)[
            "ss_ext_sales_price"].sum()
        return g
    run(env, "q3", oracle)


def test_q7(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                & (cd.cd_education_status == "College")]
        p = F["promotion"]
        p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
        x = (F["store_sales"]
             .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
             .merge(p, left_on="ss_promo_sk", right_on="p_promo_sk"))
        return x.groupby("i_item_id", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
            agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
    run(env, "q7", oracle)


def test_q26(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "F") & (cd.cd_marital_status == "W")
                & (cd.cd_education_status == "Primary")]
        p = F["promotion"]
        p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
        x = (F["catalog_sales"]
             .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="cs_item_sk", right_on="i_item_sk")
             .merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
             .merge(p, left_on="cs_promo_sk", right_on="p_promo_sk"))
        return x.groupby("i_item_id", as_index=False).agg(
            agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
            agg3=("cs_coupon_amt", "mean"), agg4=("cs_sales_price", "mean"))
    run(env, "q26", oracle)


def test_q15(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["catalog_sales"]
             .merge(F["customer"], left_on="cs_bill_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
             .merge(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)],
                    left_on="cs_sold_date_sk", right_on="d_date_sk"))
        m = (x.ca_zip.str[:2].isin(["10", "22", "34", "85"])
             | x.ca_state.isin(["CA", "GA"]) | (x.cs_sales_price > 90))
        g = x[m].groupby("ca_zip", as_index=False)["cs_sales_price"].sum()
        # LIMIT 100 ordered by the unique group key: truncation is
        # deterministic, apply it on the oracle side too
        return g.sort_values("ca_zip").head(100)
    run(env, "q15", oracle, limit=None)


def test_q19(env):
    def oracle(F):
        dd = F["date_dim"]
        it = F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 8], left_on="ss_item_sk",
                    right_on="i_item_sk")
             .merge(F["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk"))
        x = x[x.ca_city != x.s_city]
        return x.groupby(
            ["i_brand_id", "i_brand", "i_manufact_id", "i_manufact"],
            as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q19", oracle)


def test_q42(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 1],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        return x.groupby(["d_year", "i_category_id", "i_category"],
                         as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q42", oracle)


def test_q52(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 1],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        return x.groupby(["d_year", "i_brand_id", "i_brand"],
                         as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q52", oracle)


def test_q55(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 28],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        return x.groupby(["i_brand_id", "i_brand"],
                         as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q55", oracle)


def test_q43(env):
    def oracle(F):
        dd = F["date_dim"]
        st = F["store"]
        x = (F["store_sales"]
             .merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(st[st.s_gmt_offset == -5.0],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        out = []
        for (nm, sid), sub in x.groupby(["s_store_name", "s_store_id"]):
            r = {"s_store_name": nm, "s_store_id": sid}
            for day, col in [("Sunday", "sun"), ("Monday", "mon"),
                             ("Tuesday", "tue"), ("Wednesday", "wed"),
                             ("Thursday", "thu"), ("Friday", "fri"),
                             ("Saturday", "sat")]:
                v = sub.ss_sales_price.where(sub.d_day_name == day)
                r[f"{col}_sales"] = v.sum(min_count=1)
            out.append(r)
        return pd.DataFrame(out)
    run(env, "q43", oracle)


def test_q96(env):
    def oracle(F):
        td = F["time_dim"]
        hd = F["household_demographics"]
        st = F["store"]
        x = (F["store_sales"]
             .merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                    left_on="ss_sold_time_sk", right_on="t_time_sk")
             .merge(hd[hd.hd_dep_count == 7],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
             .merge(st[st.s_store_name == "store a"],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        return pd.DataFrame([{"cnt": len(x)}])
    run(env, "q96", oracle)


def test_q62(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["web_sales"]
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="ws_ship_date_sk", right_on="d_date_sk")
             .merge(F["warehouse"], left_on="ws_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(F["ship_mode"], left_on="ws_ship_mode_sk",
                    right_on="sm_ship_mode_sk")
             .merge(F["web_site"], left_on="ws_web_site_sk",
                    right_on="web_site_sk"))
        d = x.ws_ship_date_sk - x.ws_sold_date_sk
        x = x.assign(
            d30=(d <= 30).astype(int),
            d60=((d > 30) & (d <= 60)).astype(int),
            d90=((d > 60) & (d <= 90)).astype(int),
            d120=(d > 90).astype(int))
        return x.groupby(["w_warehouse_name", "sm_type", "web_name"],
                         as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    run(env, "q62", oracle)


def test_q21(env):
    def oracle(F):
        dd = F["date_dim"]
        it = F["item"]
        cut = pd.Timestamp("2000-03-11")
        x = (F["inventory"]
             .merge(F["warehouse"], left_on="inv_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(it[it.i_current_price.between(10, 60)],
                    left_on="inv_item_sk", right_on="i_item_sk")
             .merge(dd[(dd.d_date >= pd.Timestamp("2000-02-10"))
                       & (dd.d_date <= pd.Timestamp("2000-04-10"))],
                    left_on="inv_date_sk", right_on="d_date_sk"))
        x = x.assign(
            inv_before=x.inv_quantity_on_hand.where(x.d_date < cut, 0),
            inv_after=x.inv_quantity_on_hand.where(x.d_date >= cut, 0))
        g = x.groupby(["w_warehouse_name", "i_item_id"], as_index=False)[
            ["inv_before", "inv_after"]].sum()
        g = g[(g.inv_before > 0) & (g.inv_after * 3 >= g.inv_before * 2)
              & (g.inv_before * 3 >= g.inv_after * 2)]
        return g
    run(env, "q21", oracle)


# --- window-over-aggregate family ------------------------------------------

def _ratio_oracle(F, fact, prefix, date_col, item_col, ext_col):
    dd = F["date_dim"]
    it = F["item"]
    x = (F[fact]
         .merge(it[it.i_category.isin(["Sports", "Books", "Home"])],
                left_on=item_col, right_on="i_item_sk")
         .merge(dd[(dd.d_year == 1999) & dd.d_moy.isin([2, 3])],
                left_on=date_col, right_on="d_date_sk"))
    g = x.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                   "i_current_price"], as_index=False)[ext_col].sum()
    g = g.rename(columns={ext_col: "itemrevenue"})
    g["revenueratio"] = (g.itemrevenue * 100
                         / g.groupby("i_class").itemrevenue.transform("sum"))
    return g


def test_q12(env):
    run(env, "q12",
        lambda F: _ratio_oracle(F, "web_sales", "ws", "ws_sold_date_sk",
                                "ws_item_sk", "ws_ext_sales_price"))


def test_q98(env):
    run(env, "q98",
        lambda F: _ratio_oracle(F, "store_sales", "ss", "ss_sold_date_sk",
                                "ss_item_sk", "ss_ext_sales_price"))


def test_q53(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(it[it.i_category.isin(
                 ["Books", "Children", "Electronics"])],
                 left_on="ss_item_sk", right_on="i_item_sk")
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        g = x.groupby(["i_manufact_id", "d_qoy"], as_index=False)[
            "ss_sales_price"].sum().rename(
                columns={"ss_sales_price": "sum_sales"})
        g["avg_quarterly_sales"] = g.groupby(
            "i_manufact_id").sum_sales.transform("mean")
        g = g[np.where(
            g.avg_quarterly_sales > 0,
            (g.sum_sales - g.avg_quarterly_sales).abs()
            / g.avg_quarterly_sales, np.nan) > 0.1]
        return g[["i_manufact_id", "sum_sales", "avg_quarterly_sales"]]
    run(env, "q53", oracle)


def test_q89(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        m = ((it.i_category.isin(["Books", "Electronics", "Sports"])
              & it.i_class.isin(["class01", "class03", "class05"]))
             | (it.i_category.isin(["Men", "Jewelry", "Women"])
                & it.i_class.isin(["class02", "class04", "class06"])))
        x = (F["store_sales"]
             .merge(it[m], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(dd[dd.d_year == 1999], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        g = x.groupby(["i_category", "i_class", "i_brand", "s_store_name",
                       "s_city", "d_moy"], as_index=False)[
            "ss_sales_price"].sum().rename(
                columns={"ss_sales_price": "sum_sales"})
        g["avg_monthly_sales"] = g.groupby(
            ["i_category", "i_brand", "s_store_name", "s_city"]
        ).sum_sales.transform("mean")
        g = g[np.where(
            g.avg_monthly_sales != 0,
            (g.sum_sales - g.avg_monthly_sales).abs() / g.avg_monthly_sales,
            np.nan) > 0.1]
        return g
    run(env, "q89", oracle, limit=10000)


# --- ROLLUP / GROUPING family ----------------------------------------------

def test_q22(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["inventory"]
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="inv_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="inv_item_sk", right_on="i_item_sk"))
        return rollup_levels(
            x, ["i_product_name", "i_brand", "i_class", "i_category"],
            lambda sub: {"qoh": sub.inv_quantity_on_hand.mean()})
    run(env, "q22", oracle, limit=10000)


def test_q27(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                & (cd.cd_education_status == "College")]
        dd = F["date_dim"]
        x = (F["store_sales"]
             .merge(dd[dd.d_year == 2002], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk"))
        g = rollup_levels(
            x, ["i_item_id", "s_state"],
            lambda sub: {"agg1": sub.ss_quantity.mean(),
                         "agg2": sub.ss_list_price.mean(),
                         "agg3": sub.ss_coupon_amt.mean(),
                         "agg4": sub.ss_sales_price.mean()},
            grouping_cols="all")
        g["g_state"] = g["__g1"]
        return g[["i_item_id", "s_state", "g_state",
                  "agg1", "agg2", "agg3", "agg4"]]
    run(env, "q27", oracle, limit=10000)


def test_q36(env):
    def oracle(F):
        dd = F["date_dim"]
        st = F["store"]
        x = (F["store_sales"]
             .merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(st[st.s_state.isin(["TN", "CA", "NY", "TX"])],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        g = rollup_levels(
            x, ["i_category", "i_class"],
            lambda sub: {"gross_margin": sub.ss_net_profit.sum()
                         / sub.ss_ext_sales_price.sum()},
            grouping_cols="all")
        g["lochierarchy"] = g["__g0"] + g["__g1"]
        part_key = np.where(g["__g1"] == 1,
                            g["i_category"].fillna("<null>").astype(str), "")
        g["rank_within_parent"] = g.groupby(
            [g.lochierarchy, pd.Series(part_key)], dropna=False
        ).gross_margin.rank(method="min", ascending=True).astype(int)
        return g[["gross_margin", "i_category", "i_class", "lochierarchy",
                  "rank_within_parent"]]
    run(env, "q36", oracle, limit=10000)


# --- EXISTS / set-ops / correlated-scalar family ----------------------------

def test_q16(env):
    def oracle(F):
        cs, cr, dd = F["catalog_sales"], F["catalog_returns"], F["date_dim"]
        multi_wh = cs.groupby("cs_order_number").cs_warehouse_sk.nunique()
        multi_wh = set(multi_wh[multi_wh > 1].index)
        returned = set(cr.cr_order_number)
        x = (cs.merge(dd[(dd.d_date >= pd.Timestamp("2002-02-01"))
                         & (dd.d_date <= pd.Timestamp("2002-04-02"))],
                      left_on="cs_ship_date_sk", right_on="d_date_sk")
             .merge(F["customer_address"][
                 F["customer_address"].ca_state == "GA"],
                 left_on="cs_bill_addr_sk", right_on="ca_address_sk")
             .merge(F["call_center"], left_on="cs_call_center_sk",
                    right_on="cc_call_center_sk"))
        x = x[x.cs_order_number.isin(multi_wh)
              & ~x.cs_order_number.isin(returned)]
        return pd.DataFrame([{
            "order_count": x.cs_order_number.nunique(),
            "total_shipping_cost": x.cs_ext_list_price.sum(min_count=1),
            "total_net_profit": x.cs_net_profit.sum(min_count=1)}])
    run(env, "q16", oracle)


def test_q94(env):
    def oracle(F):
        ws, wr, dd = F["web_sales"], F["web_returns"], F["date_dim"]
        multi_wh = ws.groupby("ws_order_number").ws_warehouse_sk.nunique()
        multi_wh = set(multi_wh[multi_wh > 1].index)
        returned = set(wr.wr_order_number)
        web = F["web_site"]
        x = (ws.merge(dd[(dd.d_date >= pd.Timestamp("1999-02-01"))
                         & (dd.d_date <= pd.Timestamp("1999-04-02"))],
                      left_on="ws_ship_date_sk", right_on="d_date_sk")
             .merge(F["customer_address"][
                 F["customer_address"].ca_state == "IL"],
                 left_on="ws_bill_addr_sk", right_on="ca_address_sk")
             .merge(web[web.web_company_name == "pri0"],
                    left_on="ws_web_site_sk", right_on="web_site_sk"))
        x = x[x.ws_order_number.isin(multi_wh)
              & ~x.ws_order_number.isin(returned)]
        return pd.DataFrame([{
            "order_count": x.ws_order_number.nunique(),
            "total_shipping_cost": x.ws_ext_list_price.sum(min_count=1),
            "total_net_profit": x.ws_net_profit.sum(min_count=1)}])
    run(env, "q94", oracle)


def test_q20(env):
    run(env, "q20",
        lambda F: _ratio_oracle(F, "catalog_sales", "cs", "cs_sold_date_sk",
                                "cs_item_sk", "cs_ext_sales_price"))


def test_q25(env):
    def oracle(F):
        dd = F["date_dim"]
        d1 = dd[(dd.d_moy == 4) & (dd.d_year == 2000)]
        d23 = dd[dd.d_moy.between(4, 10) & (dd.d_year == 2000)]
        x = (F["store_sales"]
             .merge(d1[["d_date_sk"]], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["store_returns"],
                    left_on=["ss_customer_sk", "ss_item_sk",
                             "ss_ticket_number"],
                    right_on=["sr_customer_sk", "sr_item_sk",
                              "sr_ticket_number"])
             .merge(d23[["d_date_sk"]].rename(
                 columns={"d_date_sk": "d2sk"}),
                 left_on="sr_returned_date_sk", right_on="d2sk")
             .merge(F["catalog_sales"],
                    left_on=["sr_customer_sk", "sr_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"])
             .merge(d23[["d_date_sk"]].rename(
                 columns={"d_date_sk": "d3sk"}),
                 left_on="cs_sold_date_sk", right_on="d3sk"))
        return x.groupby(
            ["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
            as_index=False).agg(
                store_sales_profit=("ss_net_profit", "sum"),
                store_returns_loss=("sr_net_loss", "sum"),
                catalog_sales_profit=("cs_net_profit", "sum"))
    run(env, "q25", oracle)


def _discount_oracle(F, fact, item_col, date_col, amt_col):
    dd = F["date_dim"]
    win = dd[(dd.d_date >= pd.Timestamp("2000-01-27"))
             & (dd.d_date <= pd.Timestamp("2000-04-26"))]
    s = F[fact].merge(win[["d_date_sk"]], left_on=date_col,
                      right_on="d_date_sk")
    thresh = 1.3 * s.groupby(item_col)[amt_col].transform("mean")
    it = F["item"]
    picked = s[(s[amt_col] > thresh)
               & s[item_col].isin(it[it.i_manufact_id == 7].i_item_sk)]
    return pd.DataFrame([{
        "excess_discount_amount": picked[amt_col].sum(min_count=1)}])


def test_q32(env):
    run(env, "q32", lambda F: _discount_oracle(
        F, "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
        "cs_ext_discount_amt"))


def test_q92(env):
    run(env, "q92", lambda F: _discount_oracle(
        F, "web_sales", "ws_item_sk", "ws_sold_date_sk",
        "ws_ext_discount_amt"))


def _inv_item_oracle(F, fact, item_col, lo, hi, d_lo, d_hi):
    dd, it = F["date_dim"], F["item"]
    cand = it[(it.i_current_price.between(lo, hi))
              & it.i_manufact_id.isin(range(1, 9))]
    x = (F["inventory"]
         .merge(cand, left_on="inv_item_sk", right_on="i_item_sk")
         .merge(dd[(dd.d_date >= pd.Timestamp(d_lo))
                   & (dd.d_date <= pd.Timestamp(d_hi))],
                left_on="inv_date_sk", right_on="d_date_sk"))
    x = x[x.inv_quantity_on_hand.between(100, 500)]
    sold = set(F[fact][item_col])
    x = x[x.i_item_sk.isin(sold)]
    return x[["i_item_id", "i_item_desc", "i_current_price"]
             ].drop_duplicates()


def test_q37(env):
    run(env, "q37", lambda F: _inv_item_oracle(
        F, "catalog_sales", "cs_item_sk", 20, 50,
        "2000-02-01", "2000-04-01"))


def test_q82(env):
    run(env, "q82", lambda F: _inv_item_oracle(
        F, "store_sales", "ss_item_sk", 30, 60,
        "2000-05-25", "2000-07-24"))


def _channel_cust_dates(F, fact, date_col, cust_col):
    dd = F["date_dim"]
    x = (F[fact]
         .merge(dd[dd.d_month_seq.between(24, 35)],
                left_on=date_col, right_on="d_date_sk")
         .merge(F["customer"], left_on=cust_col, right_on="c_customer_sk"))
    return set(map(tuple, x[["c_last_name", "c_first_name", "d_date"]
                            ].itertuples(index=False)))


def test_q38(env):
    def oracle(F):
        a = _channel_cust_dates(F, "store_sales", "ss_sold_date_sk",
                                "ss_customer_sk")
        b = _channel_cust_dates(F, "catalog_sales", "cs_sold_date_sk",
                                "cs_bill_customer_sk")
        c = _channel_cust_dates(F, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk")
        return pd.DataFrame([{"cnt": len(a & b & c)}])
    run(env, "q38", oracle)


def test_q87(env):
    def oracle(F):
        a = _channel_cust_dates(F, "store_sales", "ss_sold_date_sk",
                                "ss_customer_sk")
        b = _channel_cust_dates(F, "catalog_sales", "cs_sold_date_sk",
                                "cs_bill_customer_sk")
        c = _channel_cust_dates(F, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk")
        return pd.DataFrame([{"cnt": len(a - b - c)}])
    run(env, "q87", oracle)


def test_q45(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        zips = {"85669", "86197", "88274", "83405", "86475",
                "85392", "85460", "80348", "81792"}
        ids = set(it[it.i_item_sk.isin(
            [2, 3, 5, 7, 11, 13, 17, 19, 23])].i_item_id)
        x = (F["web_sales"]
             .merge(F["customer"], left_on="ws_bill_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
             .merge(it, left_on="ws_item_sk", right_on="i_item_sk")
             .merge(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)],
                    left_on="ws_sold_date_sk", right_on="d_date_sk"))
        x = x[x.ca_zip.str[:5].isin(zips) | x.i_item_id.isin(ids)]
        return x.groupby(["ca_zip", "ca_city"], as_index=False)[
            "ws_sales_price"].sum()
    run(env, "q45", oracle)


def test_q50(env):
    def oracle(F):
        dd = F["date_dim"]
        d2 = dd[(dd.d_year == 2001) & (dd.d_moy == 8)]
        x = (F["store_sales"]
             .merge(F["store_returns"],
                    left_on=["ss_ticket_number", "ss_item_sk",
                             "ss_customer_sk"],
                    right_on=["sr_ticket_number", "sr_item_sk",
                              "sr_customer_sk"])
             .merge(d2[["d_date_sk"]], left_on="sr_returned_date_sk",
                    right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        d = x.sr_returned_date_sk - x.ss_sold_date_sk
        x = x.assign(d30=(d <= 30).astype(int),
                     d60=((d > 30) & (d <= 60)).astype(int),
                     d90=((d > 60) & (d <= 90)).astype(int),
                     d120=(d > 90).astype(int))
        return x.groupby(["s_store_name", "s_store_id", "s_state"],
                         as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    run(env, "q50", oracle)


def test_q61(env):
    def oracle(F):
        dd, st, it = F["date_dim"], F["store"], F["item"]
        base = (F["store_sales"]
                .merge(dd[(dd.d_year == 1998) & (dd.d_moy == 11)],
                       left_on="ss_sold_date_sk", right_on="d_date_sk")
                .merge(st[st.s_gmt_offset == -5.0],
                       left_on="ss_store_sk", right_on="s_store_sk")
                .merge(F["customer"], left_on="ss_customer_sk",
                       right_on="c_customer_sk")
                .merge(F["customer_address"][
                    F["customer_address"].ca_gmt_offset == -5.0],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
                .merge(it[it.i_category == "Jewelry"],
                       left_on="ss_item_sk", right_on="i_item_sk"))
        p = F["promotion"]
        promo = p[(p.p_channel_dmail == "Y") | (p.p_channel_email == "Y")
                  | (p.p_channel_tv == "Y")]
        promos = base.merge(promo, left_on="ss_promo_sk",
                            right_on="p_promo_sk").ss_ext_sales_price.sum()
        total = base.ss_ext_sales_price.sum()
        return pd.DataFrame([{
            "promotions": promos, "total": total,
            "ratio": promos / total * 100}])
    run(env, "q61", oracle)


def test_q65(env):
    def oracle(F):
        dd = F["date_dim"]
        x = F["store_sales"].merge(
            dd[dd.d_month_seq.between(24, 35)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        sa = x.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)[
            "ss_sales_price"].sum().rename(
                columns={"ss_sales_price": "revenue"})
        sb = sa.groupby("ss_store_sk", as_index=False).revenue.mean(
            ).rename(columns={"revenue": "ave"})
        sc = sa.merge(sb, on="ss_store_sk")
        sc = sc[sc.revenue <= 0.1 * sc.ave]
        out = (sc.merge(F["store"], left_on="ss_store_sk",
                        right_on="s_store_sk")
               .merge(F["item"], left_on="ss_item_sk",
                      right_on="i_item_sk"))
        return out[["s_store_name", "i_item_desc", "revenue",
                    "i_current_price", "i_brand"]]
    run(env, "q65", oracle)


def test_q68(env):
    def oracle(F):
        dd, st, hd = F["date_dim"], F["store"], F["household_demographics"]
        x = (F["store_sales"]
             .merge(dd[dd.d_dom.between(1, 2)
                       & dd.d_year.isin([1999, 2000, 2001])],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(st[st.s_city.isin(["Midway", "Fairview"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
             .merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
             .merge(F["customer_address"], left_on="ss_addr_sk",
                    right_on="ca_address_sk"))
        dn = x.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                        "ca_city"], as_index=False).agg(
            extended_price=("ss_ext_sales_price", "sum"),
            list_price=("ss_ext_list_price", "sum"),
            extended_tax=("ss_ext_tax", "sum")).rename(
                columns={"ca_city": "bought_city"})
        out = (dn.merge(F["customer"], left_on="ss_customer_sk",
                        right_on="c_customer_sk")
               .merge(F["customer_address"], left_on="c_current_addr_sk",
                      right_on="ca_address_sk"))
        out = out[out.ca_city != out.bought_city]
        out = out.sort_values(["c_last_name", "ss_ticket_number"]).head(100)
        return out[["c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "extended_price", "extended_tax",
                    "list_price"]]
    run(env, "q68", oracle, limit=None)


def test_q69(env):
    def oracle(F):
        dd = F["date_dim"]
        win = dd[(dd.d_year == 2001) & dd.d_moy.between(4, 6)]
        ss_c = set(F["store_sales"].merge(
            win[["d_date_sk"]], left_on="ss_sold_date_sk",
            right_on="d_date_sk").ss_customer_sk)
        ws_c = set(F["web_sales"].merge(
            win[["d_date_sk"]], left_on="ws_sold_date_sk",
            right_on="d_date_sk").ws_bill_customer_sk)
        cs_c = set(F["catalog_sales"].merge(
            win[["d_date_sk"]], left_on="cs_sold_date_sk",
            right_on="d_date_sk").cs_bill_customer_sk)
        c = (F["customer"]
             .merge(F["customer_address"][
                 F["customer_address"].ca_state.isin(["KS", "GA", "NY"])],
                 left_on="c_current_addr_sk", right_on="ca_address_sk")
             .merge(F["customer_demographics"], left_on="c_current_cdemo_sk",
                    right_on="cd_demo_sk"))
        c = c[c.c_customer_sk.isin(ss_c)
              & ~c.c_customer_sk.isin(ws_c)
              & ~c.c_customer_sk.isin(cs_c)]
        g = c.groupby(["cd_gender", "cd_marital_status",
                       "cd_education_status", "cd_purchase_estimate",
                       "cd_credit_rating"], as_index=False).size()
        g["cnt1"] = g["size"]
        return g[["cd_gender", "cd_marital_status", "cd_education_status",
                  "cnt1", "cd_purchase_estimate", "size",
                  "cd_credit_rating"]].assign(cnt3=g["size"])[
            ["cd_gender", "cd_marital_status", "cd_education_status",
             "cnt1", "cd_purchase_estimate", "size", "cd_credit_rating",
             "cnt3"]]
    run(env, "q69", oracle)


def test_q79(env):
    def oracle(F):
        dd, st, hd = F["date_dim"], F["store"], F["household_demographics"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_dow == 1) & dd.d_year.isin([1999, 2000, 2001])],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(st[st.s_number_employees.between(200, 295)],
                    left_on="ss_store_sk", right_on="s_store_sk")
             .merge(hd[(hd.hd_dep_count == 6) | (hd.hd_vehicle_count > 2)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
        ms = x.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                        "s_city"], as_index=False).agg(
            amt=("ss_coupon_amt", "sum"), profit=("ss_net_profit", "sum"))
        out = ms.merge(F["customer"], left_on="ss_customer_sk",
                       right_on="c_customer_sk")
        out["city30"] = out.s_city.str[:30]
        out = out.sort_values(
            ["c_last_name", "c_first_name", "city30", "profit",
             "ss_ticket_number"]).head(100)
        return out[["c_last_name", "c_first_name", "city30",
                    "ss_ticket_number", "amt", "profit"]]
    run(env, "q79", oracle, limit=None)


def test_q88(env):
    def oracle(F):
        td, hd, st = (F["time_dim"], F["household_demographics"], F["store"])
        hdm = hd[((hd.hd_dep_count == 4) & (hd.hd_vehicle_count <= 6))
                 | ((hd.hd_dep_count == 2) & (hd.hd_vehicle_count <= 4))
                 | ((hd.hd_dep_count == 0) & (hd.hd_vehicle_count <= 2))]
        base = (F["store_sales"]
                .merge(hdm, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
                .merge(st[st.s_store_name == "store a"],
                       left_on="ss_store_sk", right_on="s_store_sk")
                .merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk"))

        def cnt(h, half):
            if half == 0:
                return len(base[(base.t_hour == h) & (base.t_minute < 30)])
            return len(base[(base.t_hour == h) & (base.t_minute >= 30)])
        return pd.DataFrame([{
            "h8_30_to_9": cnt(8, 1), "h9_to_9_30": cnt(9, 0),
            "h9_30_to_10": cnt(9, 1), "h10_to_10_30": cnt(10, 0)}])
    run(env, "q88", oracle)


def test_q99(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["catalog_sales"]
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="cs_ship_date_sk", right_on="d_date_sk")
             .merge(F["warehouse"], left_on="cs_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(F["ship_mode"], left_on="cs_ship_mode_sk",
                    right_on="sm_ship_mode_sk")
             .merge(F["call_center"], left_on="cs_call_center_sk",
                    right_on="cc_call_center_sk"))
        d = x.cs_ship_date_sk - x.cs_sold_date_sk
        x = x.assign(wname=x.w_warehouse_name.str[:20],
                     d30=(d <= 30).astype(int),
                     d60=((d > 30) & (d <= 60)).astype(int),
                     d90=((d > 60) & (d <= 90)).astype(int),
                     d120=(d > 90).astype(int))
        return x.groupby(["wname", "sm_type", "cc_name"], as_index=False)[
            ["d30", "d60", "d90", "d120"]].sum()
    run(env, "q99", oracle)
