"""TPC-DS differential suite: each query vs an independent pandas oracle.

Reference analog: the SQL-tester T/R suites + the 99-query benchmark
(docs/en/benchmarking/TPC_DS_Benchmark.md). Comparison is order-insensitive
(rows keyed by their non-float cells; floats compared approximately) because
ties at LIMIT boundaries are resolved arbitrarily; limits are asserted to be
non-binding at the test scale except where a total order makes truncation
deterministic.
"""

import math

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.datagen.tpcds import tpcds_catalog

from tests.tpcds_queries import QUERIES

SF = 0.01


@pytest.fixture(scope="module")
def env():
    cat = tpcds_catalog(sf=SF)
    s = Session(cat)
    F = {name: cat.get_table(name).table.to_pandas()
         for name in cat.tables}
    return s, F


def _is_float(v):
    return isinstance(v, (float, np.floating))


def _norm(v):
    if v is None:
        return None
    if _is_float(v):
        return None if math.isnan(v) else float(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, pd.Timestamp):
        return v.to_pydatetime().date()
    return v


def _nf_key(row):
    """Bucket key from the non-float cells only (floats masked): rows are
    aligned exactly on discrete cells, then floats matched by sorted order
    within the bucket — no rounding-boundary misalignment."""
    return tuple("\0f" if _is_float(v) else (v if v is not None else "\0n")
                 for v in row)


def _floats_of(row):
    return tuple(float(v) for v in row if _is_float(v))


def compare(got_rows, exp_df, limit=None):
    exp_rows = [tuple(_norm(v) for v in r)
                for r in exp_df.itertuples(index=False)]
    if limit is not None:
        assert len(exp_rows) <= limit, (
            f"oracle returned {len(exp_rows)} rows; LIMIT {limit} binds — "
            "tighten the query's filters so truncation can't be ambiguous")
    got_rows = [tuple(_norm(v) for v in r) for r in got_rows]
    assert len(got_rows) == len(exp_rows), (len(got_rows), len(exp_rows))
    from collections import defaultdict

    gb, eb = defaultdict(list), defaultdict(list)
    for r in got_rows:
        gb[_nf_key(r)].append(r)
    for r in exp_rows:
        eb[_nf_key(r)].append(r)
    assert set(gb) == set(eb), (
        f"row-key mismatch: only-got={list(set(gb) - set(eb))[:3]} "
        f"only-exp={list(set(eb) - set(gb))[:3]}")
    for k, grows in gb.items():
        erows = eb[k]
        assert len(grows) == len(erows), (k, len(grows), len(erows))
        for g, e in zip(sorted(grows, key=_floats_of),
                        sorted(erows, key=_floats_of)):
            for gv, ev in zip(g, e):
                if gv is None or ev is None:
                    assert gv is None and ev is None, (g, e)
                elif _is_float(gv) or _is_float(ev):
                    assert np.isclose(float(gv), float(ev),
                                      rtol=1e-6, atol=1e-2), (g, e)
                else:
                    assert gv == ev, (g, e)


def run(env, qid, oracle, limit=100):
    s, F = env
    got = s.sql(QUERIES[qid]).rows()
    compare(got, oracle(F), limit)


def rollup_levels(df, keys, agg_fn, grouping_cols=()):
    """Pandas ROLLUP: one aggregate per prefix level; dropped keys -> NaN.
    agg_fn(sub_df) -> dict of aggregate values. grouping_cols adds
    __grouping_i indicator columns."""
    frames = []
    for k in range(len(keys), -1, -1):
        keep = list(keys[:k])
        if keep:
            g = df.groupby(keep, dropna=False, sort=False)
            rows = []
            for vals, sub in g:
                if not isinstance(vals, tuple):
                    vals = (vals,)
                r = dict(zip(keep, vals))
                r.update(agg_fn(sub))
                rows.append(r)
        else:
            rows = [agg_fn(df)]
        lvl = pd.DataFrame(rows)
        for kk in keys[k:]:
            lvl[kk] = None
        for i, _ in enumerate(keys):
            if f"__g{i}" in grouping_cols or grouping_cols == "all":
                lvl[f"__g{i}"] = 0 if i < k else 1
        frames.append(lvl)
    return pd.concat(frames, ignore_index=True)


# --- the star-join family --------------------------------------------------

def test_q3(env):
    def oracle(F):
        x = F["store_sales"].merge(
            F["date_dim"][F["date_dim"].d_moy == 11],
            left_on="ss_sold_date_sk", right_on="d_date_sk",
        ).merge(F["item"][F["item"].i_manufact_id == 7],
                left_on="ss_item_sk", right_on="i_item_sk")
        g = x.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)[
            "ss_ext_sales_price"].sum()
        return g
    run(env, "q3", oracle)


def test_q7(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                & (cd.cd_education_status == "College")]
        p = F["promotion"]
        p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
        x = (F["store_sales"]
             .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
             .merge(p, left_on="ss_promo_sk", right_on="p_promo_sk"))
        return x.groupby("i_item_id", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
            agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
    run(env, "q7", oracle)


def test_q26(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "F") & (cd.cd_marital_status == "W")
                & (cd.cd_education_status == "Primary")]
        p = F["promotion"]
        p = p[(p.p_channel_email == "N") | (p.p_channel_event == "N")]
        x = (F["catalog_sales"]
             .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="cs_item_sk", right_on="i_item_sk")
             .merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
             .merge(p, left_on="cs_promo_sk", right_on="p_promo_sk"))
        return x.groupby("i_item_id", as_index=False).agg(
            agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
            agg3=("cs_coupon_amt", "mean"), agg4=("cs_sales_price", "mean"))
    run(env, "q26", oracle)


def test_q15(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["catalog_sales"]
             .merge(F["customer"], left_on="cs_bill_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
             .merge(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)],
                    left_on="cs_sold_date_sk", right_on="d_date_sk"))
        m = (x.ca_zip.str[:2].isin(["10", "22", "34", "85"])
             | x.ca_state.isin(["CA", "GA"]) | (x.cs_sales_price > 90))
        g = x[m].groupby("ca_zip", as_index=False)["cs_sales_price"].sum()
        # LIMIT 100 ordered by the unique group key: truncation is
        # deterministic, apply it on the oracle side too
        return g.sort_values("ca_zip").head(100)
    run(env, "q15", oracle, limit=None)


def test_q19(env):
    def oracle(F):
        dd = F["date_dim"]
        it = F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 8], left_on="ss_item_sk",
                    right_on="i_item_sk")
             .merge(F["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk"))
        x = x[x.ca_city != x.s_city]
        return x.groupby(
            ["i_brand_id", "i_brand", "i_manufact_id", "i_manufact"],
            as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q19", oracle)


def test_q42(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 1],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        return x.groupby(["d_year", "i_category_id", "i_category"],
                         as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q42", oracle)


def test_q52(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 1],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        return x.groupby(["d_year", "i_brand_id", "i_brand"],
                         as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q52", oracle)


def test_q55(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[it.i_manager_id == 28],
                    left_on="ss_item_sk", right_on="i_item_sk"))
        return x.groupby(["i_brand_id", "i_brand"],
                         as_index=False)["ss_ext_sales_price"].sum()
    run(env, "q55", oracle)


def test_q43(env):
    def oracle(F):
        dd = F["date_dim"]
        st = F["store"]
        x = (F["store_sales"]
             .merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(st[st.s_gmt_offset == -5.0],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        out = []
        for (nm, sid), sub in x.groupby(["s_store_name", "s_store_id"]):
            r = {"s_store_name": nm, "s_store_id": sid}
            for day, col in [("Sunday", "sun"), ("Monday", "mon"),
                             ("Tuesday", "tue"), ("Wednesday", "wed"),
                             ("Thursday", "thu"), ("Friday", "fri"),
                             ("Saturday", "sat")]:
                v = sub.ss_sales_price.where(sub.d_day_name == day)
                r[f"{col}_sales"] = v.sum(min_count=1)
            out.append(r)
        return pd.DataFrame(out)
    run(env, "q43", oracle)


def test_q96(env):
    def oracle(F):
        td = F["time_dim"]
        hd = F["household_demographics"]
        st = F["store"]
        x = (F["store_sales"]
             .merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                    left_on="ss_sold_time_sk", right_on="t_time_sk")
             .merge(hd[hd.hd_dep_count == 7],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
             .merge(st[st.s_store_name == "store a"],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        return pd.DataFrame([{"cnt": len(x)}])
    run(env, "q96", oracle)


def test_q62(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["web_sales"]
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="ws_ship_date_sk", right_on="d_date_sk")
             .merge(F["warehouse"], left_on="ws_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(F["ship_mode"], left_on="ws_ship_mode_sk",
                    right_on="sm_ship_mode_sk")
             .merge(F["web_site"], left_on="ws_web_site_sk",
                    right_on="web_site_sk"))
        d = x.ws_ship_date_sk - x.ws_sold_date_sk
        x = x.assign(
            d30=(d <= 30).astype(int),
            d60=((d > 30) & (d <= 60)).astype(int),
            d90=((d > 60) & (d <= 90)).astype(int),
            d120=(d > 90).astype(int))
        return x.groupby(["w_warehouse_name", "sm_type", "web_name"],
                         as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    run(env, "q62", oracle)


def test_q21(env):
    def oracle(F):
        dd = F["date_dim"]
        it = F["item"]
        cut = pd.Timestamp("2000-03-11")
        x = (F["inventory"]
             .merge(F["warehouse"], left_on="inv_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(it[it.i_current_price.between(10, 60)],
                    left_on="inv_item_sk", right_on="i_item_sk")
             .merge(dd[(dd.d_date >= pd.Timestamp("2000-02-10"))
                       & (dd.d_date <= pd.Timestamp("2000-04-10"))],
                    left_on="inv_date_sk", right_on="d_date_sk"))
        x = x.assign(
            inv_before=x.inv_quantity_on_hand.where(x.d_date < cut, 0),
            inv_after=x.inv_quantity_on_hand.where(x.d_date >= cut, 0))
        g = x.groupby(["w_warehouse_name", "i_item_id"], as_index=False)[
            ["inv_before", "inv_after"]].sum()
        g = g[(g.inv_before > 0) & (g.inv_after * 3 >= g.inv_before * 2)
              & (g.inv_before * 3 >= g.inv_after * 2)]
        return g
    run(env, "q21", oracle)


# --- window-over-aggregate family ------------------------------------------

def _ratio_oracle(F, fact, prefix, date_col, item_col, ext_col):
    dd = F["date_dim"]
    it = F["item"]
    x = (F[fact]
         .merge(it[it.i_category.isin(["Sports", "Books", "Home"])],
                left_on=item_col, right_on="i_item_sk")
         .merge(dd[(dd.d_year == 1999) & dd.d_moy.isin([2, 3])],
                left_on=date_col, right_on="d_date_sk"))
    g = x.groupby(["i_item_id", "i_item_desc", "i_category", "i_class",
                   "i_current_price"], as_index=False)[ext_col].sum()
    g = g.rename(columns={ext_col: "itemrevenue"})
    g["revenueratio"] = (g.itemrevenue * 100
                         / g.groupby("i_class").itemrevenue.transform("sum"))
    return g


def test_q12(env):
    run(env, "q12",
        lambda F: _ratio_oracle(F, "web_sales", "ws", "ws_sold_date_sk",
                                "ws_item_sk", "ws_ext_sales_price"))


def test_q98(env):
    run(env, "q98",
        lambda F: _ratio_oracle(F, "store_sales", "ss", "ss_sold_date_sk",
                                "ss_item_sk", "ss_ext_sales_price"))


def test_q53(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        x = (F["store_sales"]
             .merge(it[it.i_category.isin(
                 ["Books", "Children", "Electronics"])],
                 left_on="ss_item_sk", right_on="i_item_sk")
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        g = x.groupby(["i_manufact_id", "d_qoy"], as_index=False)[
            "ss_sales_price"].sum().rename(
                columns={"ss_sales_price": "sum_sales"})
        g["avg_quarterly_sales"] = g.groupby(
            "i_manufact_id").sum_sales.transform("mean")
        g = g[np.where(
            g.avg_quarterly_sales > 0,
            (g.sum_sales - g.avg_quarterly_sales).abs()
            / g.avg_quarterly_sales, np.nan) > 0.1]
        return g[["i_manufact_id", "sum_sales", "avg_quarterly_sales"]]
    run(env, "q53", oracle)


def test_q89(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        m = ((it.i_category.isin(["Books", "Electronics", "Sports"])
              & it.i_class.isin(["class01", "class03", "class05"]))
             | (it.i_category.isin(["Men", "Jewelry", "Women"])
                & it.i_class.isin(["class02", "class04", "class06"])))
        x = (F["store_sales"]
             .merge(it[m], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(dd[dd.d_year == 1999], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        g = x.groupby(["i_category", "i_class", "i_brand", "s_store_name",
                       "s_city", "d_moy"], as_index=False)[
            "ss_sales_price"].sum().rename(
                columns={"ss_sales_price": "sum_sales"})
        g["avg_monthly_sales"] = g.groupby(
            ["i_category", "i_brand", "s_store_name", "s_city"]
        ).sum_sales.transform("mean")
        g = g[np.where(
            g.avg_monthly_sales != 0,
            (g.sum_sales - g.avg_monthly_sales).abs() / g.avg_monthly_sales,
            np.nan) > 0.1]
        return g
    run(env, "q89", oracle, limit=10000)


# --- ROLLUP / GROUPING family ----------------------------------------------

def test_q22(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["inventory"]
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="inv_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="inv_item_sk", right_on="i_item_sk"))
        return rollup_levels(
            x, ["i_product_name", "i_brand", "i_class", "i_category"],
            lambda sub: {"qoh": sub.inv_quantity_on_hand.mean()})
    run(env, "q22", oracle, limit=10000)


def test_q27(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                & (cd.cd_education_status == "College")]
        dd = F["date_dim"]
        x = (F["store_sales"]
             .merge(dd[dd.d_year == 2002], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk"))
        g = rollup_levels(
            x, ["i_item_id", "s_state"],
            lambda sub: {"agg1": sub.ss_quantity.mean(),
                         "agg2": sub.ss_list_price.mean(),
                         "agg3": sub.ss_coupon_amt.mean(),
                         "agg4": sub.ss_sales_price.mean()},
            grouping_cols="all")
        g["g_state"] = g["__g1"]
        return g[["i_item_id", "s_state", "g_state",
                  "agg1", "agg2", "agg3", "agg4"]]
    run(env, "q27", oracle, limit=10000)


def test_q36(env):
    def oracle(F):
        dd = F["date_dim"]
        st = F["store"]
        x = (F["store_sales"]
             .merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(st[st.s_state.isin(["TN", "CA", "NY", "TX"])],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        g = rollup_levels(
            x, ["i_category", "i_class"],
            lambda sub: {"gross_margin": sub.ss_net_profit.sum()
                         / sub.ss_ext_sales_price.sum()},
            grouping_cols="all")
        g["lochierarchy"] = g["__g0"] + g["__g1"]
        part_key = np.where(g["__g1"] == 1,
                            g["i_category"].fillna("<null>").astype(str), "")
        g["rank_within_parent"] = g.groupby(
            [g.lochierarchy, pd.Series(part_key)], dropna=False
        ).gross_margin.rank(method="min", ascending=True).astype(int)
        return g[["gross_margin", "i_category", "i_class", "lochierarchy",
                  "rank_within_parent"]]
    run(env, "q36", oracle, limit=10000)


# --- EXISTS / set-ops / correlated-scalar family ----------------------------

def test_q16(env):
    def oracle(F):
        cs, cr, dd = F["catalog_sales"], F["catalog_returns"], F["date_dim"]
        multi_wh = cs.groupby("cs_order_number").cs_warehouse_sk.nunique()
        multi_wh = set(multi_wh[multi_wh > 1].index)
        returned = set(cr.cr_order_number)
        x = (cs.merge(dd[(dd.d_date >= pd.Timestamp("2002-02-01"))
                         & (dd.d_date <= pd.Timestamp("2002-04-02"))],
                      left_on="cs_ship_date_sk", right_on="d_date_sk")
             .merge(F["customer_address"][
                 F["customer_address"].ca_state == "GA"],
                 left_on="cs_bill_addr_sk", right_on="ca_address_sk")
             .merge(F["call_center"], left_on="cs_call_center_sk",
                    right_on="cc_call_center_sk"))
        x = x[x.cs_order_number.isin(multi_wh)
              & ~x.cs_order_number.isin(returned)]
        return pd.DataFrame([{
            "order_count": x.cs_order_number.nunique(),
            "total_shipping_cost": x.cs_ext_list_price.sum(min_count=1),
            "total_net_profit": x.cs_net_profit.sum(min_count=1)}])
    run(env, "q16", oracle)


def test_q94(env):
    def oracle(F):
        ws, wr, dd = F["web_sales"], F["web_returns"], F["date_dim"]
        multi_wh = ws.groupby("ws_order_number").ws_warehouse_sk.nunique()
        multi_wh = set(multi_wh[multi_wh > 1].index)
        returned = set(wr.wr_order_number)
        web = F["web_site"]
        x = (ws.merge(dd[(dd.d_date >= pd.Timestamp("1999-02-01"))
                         & (dd.d_date <= pd.Timestamp("1999-04-02"))],
                      left_on="ws_ship_date_sk", right_on="d_date_sk")
             .merge(F["customer_address"][
                 F["customer_address"].ca_state == "IL"],
                 left_on="ws_bill_addr_sk", right_on="ca_address_sk")
             .merge(web[web.web_company_name == "pri0"],
                    left_on="ws_web_site_sk", right_on="web_site_sk"))
        x = x[x.ws_order_number.isin(multi_wh)
              & ~x.ws_order_number.isin(returned)]
        return pd.DataFrame([{
            "order_count": x.ws_order_number.nunique(),
            "total_shipping_cost": x.ws_ext_list_price.sum(min_count=1),
            "total_net_profit": x.ws_net_profit.sum(min_count=1)}])
    run(env, "q94", oracle)


def test_q20(env):
    run(env, "q20",
        lambda F: _ratio_oracle(F, "catalog_sales", "cs", "cs_sold_date_sk",
                                "cs_item_sk", "cs_ext_sales_price"))


def test_q25(env):
    def oracle(F):
        dd = F["date_dim"]
        d1 = dd[(dd.d_moy == 4) & (dd.d_year == 2000)]
        d23 = dd[dd.d_moy.between(4, 10) & (dd.d_year == 2000)]
        x = (F["store_sales"]
             .merge(d1[["d_date_sk"]], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["store_returns"],
                    left_on=["ss_customer_sk", "ss_item_sk",
                             "ss_ticket_number"],
                    right_on=["sr_customer_sk", "sr_item_sk",
                              "sr_ticket_number"])
             .merge(d23[["d_date_sk"]].rename(
                 columns={"d_date_sk": "d2sk"}),
                 left_on="sr_returned_date_sk", right_on="d2sk")
             .merge(F["catalog_sales"],
                    left_on=["sr_customer_sk", "sr_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"])
             .merge(d23[["d_date_sk"]].rename(
                 columns={"d_date_sk": "d3sk"}),
                 left_on="cs_sold_date_sk", right_on="d3sk"))
        return x.groupby(
            ["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
            as_index=False).agg(
                store_sales_profit=("ss_net_profit", "sum"),
                store_returns_loss=("sr_net_loss", "sum"),
                catalog_sales_profit=("cs_net_profit", "sum"))
    run(env, "q25", oracle)


def _discount_oracle(F, fact, item_col, date_col, amt_col):
    dd = F["date_dim"]
    win = dd[(dd.d_date >= pd.Timestamp("2000-01-27"))
             & (dd.d_date <= pd.Timestamp("2000-04-26"))]
    s = F[fact].merge(win[["d_date_sk"]], left_on=date_col,
                      right_on="d_date_sk")
    thresh = 1.3 * s.groupby(item_col)[amt_col].transform("mean")
    it = F["item"]
    picked = s[(s[amt_col] > thresh)
               & s[item_col].isin(it[it.i_manufact_id == 7].i_item_sk)]
    return pd.DataFrame([{
        "excess_discount_amount": picked[amt_col].sum(min_count=1)}])


def test_q32(env):
    run(env, "q32", lambda F: _discount_oracle(
        F, "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
        "cs_ext_discount_amt"))


def test_q92(env):
    run(env, "q92", lambda F: _discount_oracle(
        F, "web_sales", "ws_item_sk", "ws_sold_date_sk",
        "ws_ext_discount_amt"))


def _inv_item_oracle(F, fact, item_col, lo, hi, d_lo, d_hi):
    dd, it = F["date_dim"], F["item"]
    cand = it[(it.i_current_price.between(lo, hi))
              & it.i_manufact_id.isin(range(1, 9))]
    x = (F["inventory"]
         .merge(cand, left_on="inv_item_sk", right_on="i_item_sk")
         .merge(dd[(dd.d_date >= pd.Timestamp(d_lo))
                   & (dd.d_date <= pd.Timestamp(d_hi))],
                left_on="inv_date_sk", right_on="d_date_sk"))
    x = x[x.inv_quantity_on_hand.between(100, 500)]
    sold = set(F[fact][item_col])
    x = x[x.i_item_sk.isin(sold)]
    return x[["i_item_id", "i_item_desc", "i_current_price"]
             ].drop_duplicates()


def test_q37(env):
    run(env, "q37", lambda F: _inv_item_oracle(
        F, "catalog_sales", "cs_item_sk", 20, 50,
        "2000-02-01", "2000-04-01"))


def test_q82(env):
    run(env, "q82", lambda F: _inv_item_oracle(
        F, "store_sales", "ss_item_sk", 30, 60,
        "2000-05-25", "2000-07-24"))


def _channel_cust_dates(F, fact, date_col, cust_col):
    dd = F["date_dim"]
    x = (F[fact]
         .merge(dd[dd.d_month_seq.between(24, 35)],
                left_on=date_col, right_on="d_date_sk")
         .merge(F["customer"], left_on=cust_col, right_on="c_customer_sk"))
    return set(map(tuple, x[["c_last_name", "c_first_name", "d_date"]
                            ].itertuples(index=False)))


def test_q38(env):
    def oracle(F):
        a = _channel_cust_dates(F, "store_sales", "ss_sold_date_sk",
                                "ss_customer_sk")
        b = _channel_cust_dates(F, "catalog_sales", "cs_sold_date_sk",
                                "cs_bill_customer_sk")
        c = _channel_cust_dates(F, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk")
        return pd.DataFrame([{"cnt": len(a & b & c)}])
    run(env, "q38", oracle)


def test_q87(env):
    def oracle(F):
        a = _channel_cust_dates(F, "store_sales", "ss_sold_date_sk",
                                "ss_customer_sk")
        b = _channel_cust_dates(F, "catalog_sales", "cs_sold_date_sk",
                                "cs_bill_customer_sk")
        c = _channel_cust_dates(F, "web_sales", "ws_sold_date_sk",
                                "ws_bill_customer_sk")
        return pd.DataFrame([{"cnt": len(a - b - c)}])
    run(env, "q87", oracle)


def test_q45(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]
        zips = {"85669", "86197", "88274", "83405", "86475",
                "85392", "85460", "80348", "81792"}
        ids = set(it[it.i_item_sk.isin(
            [2, 3, 5, 7, 11, 13, 17, 19, 23])].i_item_id)
        x = (F["web_sales"]
             .merge(F["customer"], left_on="ws_bill_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
             .merge(it, left_on="ws_item_sk", right_on="i_item_sk")
             .merge(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)],
                    left_on="ws_sold_date_sk", right_on="d_date_sk"))
        x = x[x.ca_zip.str[:5].isin(zips) | x.i_item_id.isin(ids)]
        return x.groupby(["ca_zip", "ca_city"], as_index=False)[
            "ws_sales_price"].sum()
    run(env, "q45", oracle)


def test_q50(env):
    def oracle(F):
        dd = F["date_dim"]
        d2 = dd[(dd.d_year == 2001) & (dd.d_moy == 8)]
        x = (F["store_sales"]
             .merge(F["store_returns"],
                    left_on=["ss_ticket_number", "ss_item_sk",
                             "ss_customer_sk"],
                    right_on=["sr_ticket_number", "sr_item_sk",
                              "sr_customer_sk"])
             .merge(d2[["d_date_sk"]], left_on="sr_returned_date_sk",
                    right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk",
                    right_on="s_store_sk"))
        d = x.sr_returned_date_sk - x.ss_sold_date_sk
        x = x.assign(d30=(d <= 30).astype(int),
                     d60=((d > 30) & (d <= 60)).astype(int),
                     d90=((d > 60) & (d <= 90)).astype(int),
                     d120=(d > 90).astype(int))
        return x.groupby(["s_store_name", "s_store_id", "s_state"],
                         as_index=False)[["d30", "d60", "d90", "d120"]].sum()
    run(env, "q50", oracle)


def test_q61(env):
    def oracle(F):
        dd, st, it = F["date_dim"], F["store"], F["item"]
        base = (F["store_sales"]
                .merge(dd[(dd.d_year == 1998) & (dd.d_moy == 11)],
                       left_on="ss_sold_date_sk", right_on="d_date_sk")
                .merge(st[st.s_gmt_offset == -5.0],
                       left_on="ss_store_sk", right_on="s_store_sk")
                .merge(F["customer"], left_on="ss_customer_sk",
                       right_on="c_customer_sk")
                .merge(F["customer_address"][
                    F["customer_address"].ca_gmt_offset == -5.0],
                    left_on="c_current_addr_sk", right_on="ca_address_sk")
                .merge(it[it.i_category == "Jewelry"],
                       left_on="ss_item_sk", right_on="i_item_sk"))
        p = F["promotion"]
        promo = p[(p.p_channel_dmail == "Y") | (p.p_channel_email == "Y")
                  | (p.p_channel_tv == "Y")]
        promos = base.merge(promo, left_on="ss_promo_sk",
                            right_on="p_promo_sk").ss_ext_sales_price.sum()
        total = base.ss_ext_sales_price.sum()
        return pd.DataFrame([{
            "promotions": promos, "total": total,
            "ratio": promos / total * 100}])
    run(env, "q61", oracle)


def test_q65(env):
    def oracle(F):
        dd = F["date_dim"]
        x = F["store_sales"].merge(
            dd[dd.d_month_seq.between(24, 35)],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        sa = x.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)[
            "ss_sales_price"].sum().rename(
                columns={"ss_sales_price": "revenue"})
        sb = sa.groupby("ss_store_sk", as_index=False).revenue.mean(
            ).rename(columns={"revenue": "ave"})
        sc = sa.merge(sb, on="ss_store_sk")
        sc = sc[sc.revenue <= 0.1 * sc.ave]
        out = (sc.merge(F["store"], left_on="ss_store_sk",
                        right_on="s_store_sk")
               .merge(F["item"], left_on="ss_item_sk",
                      right_on="i_item_sk"))
        return out[["s_store_name", "i_item_desc", "revenue",
                    "i_current_price", "i_brand"]]
    run(env, "q65", oracle)


def test_q68(env):
    def oracle(F):
        dd, st, hd = F["date_dim"], F["store"], F["household_demographics"]
        x = (F["store_sales"]
             .merge(dd[dd.d_dom.between(1, 2)
                       & dd.d_year.isin([1999, 2000, 2001])],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(st[st.s_city.isin(["Midway", "Fairview"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
             .merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
             .merge(F["customer_address"], left_on="ss_addr_sk",
                    right_on="ca_address_sk"))
        dn = x.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                        "ca_city"], as_index=False).agg(
            extended_price=("ss_ext_sales_price", "sum"),
            list_price=("ss_ext_list_price", "sum"),
            extended_tax=("ss_ext_tax", "sum")).rename(
                columns={"ca_city": "bought_city"})
        out = (dn.merge(F["customer"], left_on="ss_customer_sk",
                        right_on="c_customer_sk")
               .merge(F["customer_address"], left_on="c_current_addr_sk",
                      right_on="ca_address_sk"))
        out = out[out.ca_city != out.bought_city]
        out = out.sort_values(["c_last_name", "ss_ticket_number"]).head(100)
        return out[["c_last_name", "c_first_name", "ca_city", "bought_city",
                    "ss_ticket_number", "extended_price", "extended_tax",
                    "list_price"]]
    run(env, "q68", oracle, limit=None)


def test_q69(env):
    def oracle(F):
        dd = F["date_dim"]
        win = dd[(dd.d_year == 2001) & dd.d_moy.between(4, 6)]
        ss_c = set(F["store_sales"].merge(
            win[["d_date_sk"]], left_on="ss_sold_date_sk",
            right_on="d_date_sk").ss_customer_sk)
        ws_c = set(F["web_sales"].merge(
            win[["d_date_sk"]], left_on="ws_sold_date_sk",
            right_on="d_date_sk").ws_bill_customer_sk)
        cs_c = set(F["catalog_sales"].merge(
            win[["d_date_sk"]], left_on="cs_sold_date_sk",
            right_on="d_date_sk").cs_bill_customer_sk)
        c = (F["customer"]
             .merge(F["customer_address"][
                 F["customer_address"].ca_state.isin(["KS", "GA", "NY"])],
                 left_on="c_current_addr_sk", right_on="ca_address_sk")
             .merge(F["customer_demographics"], left_on="c_current_cdemo_sk",
                    right_on="cd_demo_sk"))
        c = c[c.c_customer_sk.isin(ss_c)
              & ~c.c_customer_sk.isin(ws_c)
              & ~c.c_customer_sk.isin(cs_c)]
        g = c.groupby(["cd_gender", "cd_marital_status",
                       "cd_education_status", "cd_purchase_estimate",
                       "cd_credit_rating"], as_index=False).size()
        g["cnt1"] = g["size"]
        return g[["cd_gender", "cd_marital_status", "cd_education_status",
                  "cnt1", "cd_purchase_estimate", "size",
                  "cd_credit_rating"]].assign(cnt3=g["size"])[
            ["cd_gender", "cd_marital_status", "cd_education_status",
             "cnt1", "cd_purchase_estimate", "size", "cd_credit_rating",
             "cnt3"]]
    run(env, "q69", oracle)


def test_q79(env):
    def oracle(F):
        dd, st, hd = F["date_dim"], F["store"], F["household_demographics"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_dow == 1) & dd.d_year.isin([1999, 2000, 2001])],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(st[st.s_number_employees.between(200, 295)],
                    left_on="ss_store_sk", right_on="s_store_sk")
             .merge(hd[(hd.hd_dep_count == 6) | (hd.hd_vehicle_count > 2)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
        ms = x.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                        "s_city"], as_index=False).agg(
            amt=("ss_coupon_amt", "sum"), profit=("ss_net_profit", "sum"))
        out = ms.merge(F["customer"], left_on="ss_customer_sk",
                       right_on="c_customer_sk")
        out["city30"] = out.s_city.str[:30]
        out = out.sort_values(
            ["c_last_name", "c_first_name", "city30", "profit",
             "ss_ticket_number"]).head(100)
        return out[["c_last_name", "c_first_name", "city30",
                    "ss_ticket_number", "amt", "profit"]]
    run(env, "q79", oracle, limit=None)


def test_q88(env):
    def oracle(F):
        td, hd, st = (F["time_dim"], F["household_demographics"], F["store"])
        hdm = hd[((hd.hd_dep_count == 4) & (hd.hd_vehicle_count <= 6))
                 | ((hd.hd_dep_count == 2) & (hd.hd_vehicle_count <= 4))
                 | ((hd.hd_dep_count == 0) & (hd.hd_vehicle_count <= 2))]
        base = (F["store_sales"]
                .merge(hdm, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
                .merge(st[st.s_store_name == "store a"],
                       left_on="ss_store_sk", right_on="s_store_sk")
                .merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk"))

        def cnt(h, half):
            if half == 0:
                return len(base[(base.t_hour == h) & (base.t_minute < 30)])
            return len(base[(base.t_hour == h) & (base.t_minute >= 30)])
        return pd.DataFrame([{
            "h8_30_to_9": cnt(8, 1), "h9_to_9_30": cnt(9, 0),
            "h9_30_to_10": cnt(9, 1), "h10_to_10_30": cnt(10, 0)}])
    run(env, "q88", oracle)


def test_q99(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["catalog_sales"]
             .merge(dd[dd.d_month_seq.between(24, 35)],
                    left_on="cs_ship_date_sk", right_on="d_date_sk")
             .merge(F["warehouse"], left_on="cs_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(F["ship_mode"], left_on="cs_ship_mode_sk",
                    right_on="sm_ship_mode_sk")
             .merge(F["call_center"], left_on="cs_call_center_sk",
                    right_on="cc_call_center_sk"))
        d = x.cs_ship_date_sk - x.cs_sold_date_sk
        x = x.assign(wname=x.w_warehouse_name.str[:20],
                     d30=(d <= 30).astype(int),
                     d60=((d > 30) & (d <= 60)).astype(int),
                     d90=((d > 60) & (d <= 90)).astype(int),
                     d120=(d > 90).astype(int))
        return x.groupby(["wname", "sm_type", "cc_name"], as_index=False)[
            ["d30", "d60", "d90", "d120"]].sum()
    run(env, "q99", oracle)


# --- round-3 expansion batch 1 ----------------------------------------------


def test_q1(env):
    def oracle(F):
        dd = F["date_dim"]
        ctr = (F["store_returns"]
               .merge(dd[dd.d_year == 2000], left_on="sr_returned_date_sk",
                      right_on="d_date_sk")
               .groupby(["sr_customer_sk", "sr_store_sk"], as_index=False)
               ["sr_return_amt"].sum()
               .rename(columns={"sr_return_amt": "ctr_total_return"}))
        avg_by_store = ctr.groupby("sr_store_sk")["ctr_total_return"].mean()
        ctr["thresh"] = ctr.sr_store_sk.map(avg_by_store) * 1.2
        tn = F["store"][F["store"].s_state == "TN"].s_store_sk
        x = ctr[(ctr.ctr_total_return > ctr.thresh)
                & ctr.sr_store_sk.isin(tn)]
        out = x.merge(F["customer"], left_on="sr_customer_sk",
                      right_on="c_customer_sk")[["c_customer_id"]]
        return out.sort_values("c_customer_id").head(100)
    run(env, "q1", oracle, limit=None)


def test_q6(env):
    def oracle(F):
        dd = F["date_dim"]
        mseq = dd[(dd.d_year == 2001) & (dd.d_moy == 1)].d_month_seq.iloc[0]
        it = F["item"].copy()
        cat_avg = it.groupby("i_category")["i_current_price"].mean()
        it = it[it.i_current_price > 1.2 * it.i_category.map(cat_avg)]
        x = (F["customer_address"]
             .merge(F["customer"], left_on="ca_address_sk",
                    right_on="c_current_addr_sk")
             .merge(F["store_sales"], left_on="c_customer_sk",
                    right_on="ss_customer_sk")
             .merge(dd[dd.d_month_seq == mseq], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        g = x.groupby("ca_state", dropna=False).size().reset_index(name="cnt")
        g = g[g.cnt >= 2].rename(columns={"ca_state": "state"})
        return g[["state", "cnt"]]
    run(env, "q6", oracle)


def test_q9(env):
    def oracle(F):
        ss = F["store_sales"]
        out = {}
        for i, (lo, hi) in enumerate([(1, 20), (21, 40), (41, 60)], 1):
            b = ss[(ss.ss_quantity >= lo) & (ss.ss_quantity <= hi)]
            out[f"bucket{i}"] = (b.ss_ext_discount_amt.mean()
                                 if len(b) > 5000 else b.ss_net_paid.mean())
        return pd.DataFrame([out])
    run(env, "q9", oracle)


def test_q10(env):
    def oracle(F):
        dd = F["date_dim"]
        dsel = dd[(dd.d_year == 2002) & dd.d_moy.between(1, 4)].d_date_sk
        ss_c = set(F["store_sales"][
            F["store_sales"].ss_sold_date_sk.isin(dsel)].ss_customer_sk)
        ws_c = set(F["web_sales"][
            F["web_sales"].ws_sold_date_sk.isin(dsel)].ws_bill_customer_sk)
        cs_c = set(F["catalog_sales"][
            F["catalog_sales"].cs_sold_date_sk.isin(dsel)].cs_bill_customer_sk)
        c = F["customer"]
        c = c[c.c_customer_sk.isin(ss_c)
              & (c.c_customer_sk.isin(ws_c) | c.c_customer_sk.isin(cs_c))]
        x = (c.merge(F["customer_address"], left_on="c_current_addr_sk",
                     right_on="ca_address_sk")
             .merge(F["customer_demographics"], left_on="c_current_cdemo_sk",
                    right_on="cd_demo_sk"))
        x = x[x.ca_county.isin(["Bronx County", "Barrow County",
                                "Daviess County"])]
        g = x.groupby(["cd_gender", "cd_marital_status",
                       "cd_education_status", "cd_purchase_estimate"],
                      as_index=False).size()
        g["cnt1"] = g["size"]
        g["cnt2"] = g["size"]
        return g[["cd_gender", "cd_marital_status", "cd_education_status",
                  "cnt1", "cd_purchase_estimate", "cnt2"]]
    run(env, "q10", oracle)


def test_q13(env):
    def oracle(F):
        x = (F["store_sales"]
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["date_dim"][F["date_dim"].d_year == 2001],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["household_demographics"], left_on="ss_hdemo_sk",
                    right_on="hd_demo_sk")
             .merge(F["customer_demographics"], left_on="ss_cdemo_sk",
                    right_on="cd_demo_sk")
             .merge(F["customer_address"], left_on="ss_addr_sk",
                    right_on="ca_address_sk"))
        x = x[x.ca_country == "United States"]
        m1 = (((x.cd_marital_status == "M")
               & (x.cd_education_status == "Advanced Degree")
               & x.ss_sales_price.between(50, 100) & (x.hd_dep_count == 3))
              | ((x.cd_marital_status == "S")
                 & (x.cd_education_status == "College")
                 & x.ss_sales_price.between(10, 60) & (x.hd_dep_count == 1))
              | ((x.cd_marital_status == "W")
                 & (x.cd_education_status == "2 yr Degree")
                 & x.ss_sales_price.between(30, 80) & (x.hd_dep_count == 1)))
        m2 = ((x.ca_state.isin(["TX", "OH", "TN"])
               & x.ss_net_profit.between(0, 2000))
              | (x.ca_state.isin(["AL", "KS", "MI"])
                 & x.ss_net_profit.between(50, 3000))
              | (x.ca_state.isin(["CA", "GA", "NY"])
                 & x.ss_net_profit.between(0, 25000)))
        x = x[m1 & m2]
        assert len(x) > 0
        return pd.DataFrame([{
            "a1": x.ss_quantity.mean(), "a2": x.ss_ext_sales_price.mean(),
            "a3": x.ss_ext_wholesale_cost.mean(),
            "s1": x.ss_ext_wholesale_cost.sum()}])
    run(env, "q13", oracle)


def test_q28(env):
    def oracle(F):
        ss = F["store_sales"]
        out = {}
        bands = [(0, 5, 10, 50, 0, 200, 10, 30),
                 (6, 10, 20, 60, 0, 300, 20, 40),
                 (11, 15, 30, 70, 0, 400, 30, 50)]
        for i, (qlo, qhi, llo, lhi, clo, chi, wlo, whi) in enumerate(bands, 1):
            b = ss[ss.ss_quantity.between(qlo, qhi)
                   & (ss.ss_list_price.between(llo, lhi)
                      | ss.ss_coupon_amt.between(clo, chi)
                      | ss.ss_wholesale_cost.between(wlo, whi))]
            assert len(b) > 0
            out[f"b{i}_lp"] = b.ss_list_price.mean()
            out[f"b{i}_cnt"] = len(b)
            out[f"b{i}_cntd"] = b.ss_list_price.nunique()
        return pd.DataFrame([out])
    run(env, "q28", oracle)


def test_q29(env):
    def oracle(F):
        dd = F["date_dim"]
        d1 = dd[dd.d_year == 1999]
        d2 = dd[dd.d_year == 1999]
        d3 = dd[dd.d_year.isin([1999, 2000, 2001])]
        x = (F["store_sales"]
             .merge(d1[["d_date_sk"]], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["store_returns"],
                    left_on=["ss_customer_sk", "ss_item_sk",
                             "ss_ticket_number"],
                    right_on=["sr_customer_sk", "sr_item_sk",
                              "sr_ticket_number"])
             .merge(d2[["d_date_sk"]], left_on="sr_returned_date_sk",
                    right_on="d_date_sk")
             .merge(F["catalog_sales"],
                    left_on=["sr_customer_sk", "sr_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"])
             .merge(d3[["d_date_sk"]], left_on="cs_sold_date_sk",
                    right_on="d_date_sk"))
        assert len(x) > 0
        return x.groupby(["i_item_id", "i_item_desc", "s_store_id",
                          "s_store_name"], as_index=False).agg(
            store_sales_quantity=("ss_quantity", "sum"),
            store_returns_quantity=("sr_return_quantity", "sum"),
            catalog_sales_quantity=("cs_quantity", "sum"))
    run(env, "q29", oracle)


def test_q34(env):
    def oracle(F):
        hd = F["household_demographics"]
        x = (F["store_sales"]
             .merge(F["store"][F["store"].s_county.isin(
                 ["Richland County", "Daviess County", "Maverick County"])],
                 left_on="ss_store_sk", right_on="s_store_sk")
             .merge(hd[hd.hd_buy_potential.isin([">10000", "Unknown"])
                       & (hd.hd_vehicle_count > 0)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
        g = (x.groupby("ss_customer_sk", as_index=False).size()
             .rename(columns={"size": "cnt"}))
        g = g[g.cnt.between(5, 10)]
        out = g.merge(F["customer"], left_on="ss_customer_sk",
                      right_on="c_customer_sk")
        assert len(out) > 0
        return out[["c_last_name", "c_first_name", "c_customer_id", "cnt"]]
    run(env, "q34", oracle, limit=1000)


def test_q41(env):
    def oracle(F):
        it = F["item"]
        m = ((it.i_category == "Women") & it.i_color.isin(["plum", "pink"])) | \
            ((it.i_category == "Men") & it.i_color.isin(["black", "blue"])) | \
            ((it.i_category == "Shoes") & it.i_color.isin(["green", "ivory"]))
        manufs = set(it[m].i_manufact)
        x = it[it.i_manufact_id.between(5, 15)
               & it.i_manufact.isin(manufs)]
        assert len(x) > 0
        return (x[["i_product_name"]].drop_duplicates()
                .sort_values("i_product_name").head(100))
    run(env, "q41", oracle, limit=None)


def test_q48(env):
    def oracle(F):
        x = (F["store_sales"]
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["customer_demographics"], left_on="ss_cdemo_sk",
                    right_on="cd_demo_sk")
             .merge(F["customer_address"], left_on="ss_addr_sk",
                    right_on="ca_address_sk"))
        x = x[x.ca_country == "United States"]
        m1 = (((x.cd_marital_status == "M")
               & (x.cd_education_status == "4 yr Degree")
               & x.ss_sales_price.between(100, 150))
              | ((x.cd_marital_status == "D")
                 & (x.cd_education_status == "2 yr Degree")
                 & x.ss_sales_price.between(50, 100))
              | ((x.cd_marital_status == "S")
                 & (x.cd_education_status == "College")
                 & x.ss_sales_price.between(150, 200)))
        m2 = ((x.ca_state.isin(["CO", "OH", "TX"])
               & x.ss_net_profit.between(0, 2000))
              | (x.ca_state.isin(["OR", "MN", "KS"])
                 & x.ss_net_profit.between(150, 3000))
              | (x.ca_state.isin(["TX", "MO", "MI"])
                 & x.ss_net_profit.between(50, 25000)))
        x = x[m1 & m2]
        assert len(x) > 0
        return pd.DataFrame([{"total": x.ss_quantity.sum()}])
    run(env, "q48", oracle)


# --- round-3 expansion batch 2 ----------------------------------------------


def test_q17(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_qoy == 1) & (dd.d_year == 1999)][["d_date_sk"]],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["store_returns"],
                    left_on=["ss_customer_sk", "ss_item_sk",
                             "ss_ticket_number"],
                    right_on=["sr_customer_sk", "sr_item_sk",
                              "sr_ticket_number"])
             .merge(dd[dd.d_year == 1999][["d_date_sk"]],
                    left_on="sr_returned_date_sk", right_on="d_date_sk")
             .merge(F["catalog_sales"],
                    left_on=["sr_customer_sk", "sr_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"]))
        assert len(x) > 0
        return x.groupby(["i_item_id", "i_item_desc", "s_state"],
                         as_index=False).agg(
            store_sales_quantitycount=("ss_quantity", "count"),
            store_sales_quantityave=("ss_quantity", "mean"),
            store_sales_quantitystdev=("ss_quantity",
                                       lambda v: v.std(ddof=1)),
            store_returns_quantitycount=("sr_return_quantity", "count"),
            store_returns_quantityave=("sr_return_quantity", "mean"),
            catalog_sales_quantitycount=("cs_quantity", "count"),
            catalog_sales_quantityave=("cs_quantity", "mean"))
    run(env, "q17", oracle)


def test_q18(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[(cd.cd_gender == "F") & (cd.cd_education_status == "Unknown")]
        c = F["customer"][F["customer"].c_birth_month.isin(
            [1, 6, 8, 9, 12, 2])]
        x = (F["catalog_sales"]
             .merge(F["date_dim"][F["date_dim"].d_year == 1998],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="cs_item_sk", right_on="i_item_sk")
             .merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
             .merge(c, left_on="cs_bill_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk"))
        assert len(x) > 0

        def agg(sub):
            return {"agg1": sub.cs_quantity.mean(),
                    "agg2": sub.cs_list_price.mean(),
                    "agg3": sub.cs_coupon_amt.mean(),
                    "agg4": sub.cs_sales_price.mean()}

        return rollup_levels(x, ["i_item_id", "ca_state"], agg)[
            ["i_item_id", "ca_state", "agg1", "agg2", "agg3", "agg4"]]
    run(env, "q18", oracle, limit=1000)


def test_q30(env):
    def oracle(F):
        ctr = (F["web_returns"]
               .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                      left_on="wr_returned_date_sk", right_on="d_date_sk")
               .merge(F["customer_address"], left_on="wr_refunded_addr_sk",
                      right_on="ca_address_sk")
               .groupby(["wr_returning_cdemo_sk", "ca_state"],
                        as_index=False)["wr_return_amt"].sum()
               .rename(columns={"wr_return_amt": "ctr_total_return"}))
        avg_by_state = ctr.groupby("ca_state")["ctr_total_return"].mean()
        x = ctr[ctr.ctr_total_return
                > 1.2 * ctr.ca_state.map(avg_by_state)]
        assert len(x) > 0
        return x.rename(columns={"wr_returning_cdemo_sk": "ctr_cdemo_sk",
                                 "ca_state": "ctr_state"})
    run(env, "q30", oracle)


def test_q31(env):
    def oracle(F):
        dd = F["date_dim"]

        def chan(fact, date_col, addr_col, val_col):
            x = (F[fact]
                 .merge(dd, left_on=date_col, right_on="d_date_sk")
                 .merge(F["customer_address"], left_on=addr_col,
                        right_on="ca_address_sk"))
            return x.groupby(["ca_county", "d_qoy", "d_year"],
                             as_index=False)[val_col].sum()

        ss = chan("store_sales", "ss_sold_date_sk", "ss_addr_sk",
                  "ss_ext_sales_price")
        ws = chan("web_sales", "ws_sold_date_sk", "ws_bill_addr_sk",
                  "ws_ext_sales_price")

        def q(df, qoy, col):
            d = df[(df.d_qoy == qoy) & (df.d_year == 2000)]
            return d.set_index("ca_county")[col]

        s1, s2 = q(ss, 1, "ss_ext_sales_price"), q(ss, 2, "ss_ext_sales_price")
        w1, w2 = q(ws, 1, "ws_ext_sales_price"), q(ws, 2, "ws_ext_sales_price")
        counties = (set(s1.index) & set(s2.index) & set(w1.index)
                    & set(w2.index))
        rows = []
        for c in counties:
            wr = w2[c] / w1[c]
            sr = s2[c] / s1[c]
            if wr > sr:
                rows.append({"ca_county": c, "d_year": 2000,
                             "web_q1_q2_increase": wr,
                             "store_q1_q2_increase": sr})
        assert rows
        return pd.DataFrame(rows)
    run(env, "q31", oracle)


def test_q33(env):
    def oracle(F):
        dd, ca, it = F["date_dim"], F["customer_address"], F["item"]
        mids = set(it[it.i_category == "Electronics"].i_manufact_id)

        def chan(fact, date_col, item_col, addr_col, val_col):
            x = (F[fact]
                 .merge(dd[(dd.d_year == 1998) & (dd.d_moy == 5)],
                        left_on=date_col, right_on="d_date_sk")
                 .merge(it[it.i_manufact_id.isin(mids)], left_on=item_col,
                        right_on="i_item_sk")
                 .merge(ca[ca.ca_gmt_offset == -5], left_on=addr_col,
                        right_on="ca_address_sk"))
            return x.groupby("i_manufact_id", as_index=False)[val_col].sum()\
                .rename(columns={val_col: "total_sales"})

        u = pd.concat([
            chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
                 "ss_addr_sk", "ss_ext_sales_price"),
            chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_bill_addr_sk", "cs_ext_sales_price"),
            chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                 "ws_bill_addr_sk", "ws_ext_sales_price")])
        g = u.groupby("i_manufact_id", as_index=False)["total_sales"].sum()
        assert len(g) > 0
        return g
    run(env, "q33", oracle)


def test_q40(env):
    def oracle(F):
        dd = F["date_dim"].copy()
        dd["d_date"] = pd.to_datetime(dd.d_date)
        x = (F["catalog_sales"]
             .merge(F["catalog_returns"],
                    left_on=["cs_order_number", "cs_item_sk"],
                    right_on=["cr_order_number", "cr_item_sk"], how="left")
             .merge(F["warehouse"], left_on="cs_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(F["item"][F["item"].i_current_price.between(10, 90)],
                    left_on="cs_item_sk", right_on="i_item_sk")
             .merge(dd[dd.d_date.between("2000-02-10", "2000-04-10")],
                    left_on="cs_sold_date_sk", right_on="d_date_sk"))
        assert len(x) > 0
        cut = pd.Timestamp("2000-03-11")
        x["sales_before"] = x.cs_sales_price.where(x.d_date < cut, 0.0)
        x["sales_after"] = x.cs_sales_price.where(x.d_date >= cut, 0.0)
        g = x.groupby(["w_state", "i_item_id"], as_index=False).agg(
            sales_before=("sales_before", "sum"),
            sales_after=("sales_after", "sum"))
        # ordered by the full (unique) group key: truncation deterministic
        return g.sort_values(["w_state", "i_item_id"]).head(100)
    run(env, "q40", oracle, limit=None)


def test_q44(env):
    def oracle(F):
        ss = F["store_sales"]
        v = (ss[ss.ss_store_sk == 2]
             .groupby("ss_item_sk", as_index=False)["ss_net_profit"].mean()
             .rename(columns={"ss_net_profit": "rank_col"}))
        # rank(): ties share ranks — datagen profits are effectively unique
        v = v.copy()
        v["rnk_a"] = v.rank_col.rank(method="min", ascending=True)
        v["rnk_d"] = v.rank_col.rank(method="min", ascending=False)
        a = v[v.rnk_a < 11][["rnk_a", "ss_item_sk"]]
        d = v[v.rnk_d < 11][["rnk_d", "ss_item_sk"]]
        it = F["item"][["i_item_sk", "i_product_name"]]
        x = (a.merge(d, left_on="rnk_a", right_on="rnk_d")
             .merge(it, left_on="ss_item_sk_x", right_on="i_item_sk")
             .merge(it, left_on="ss_item_sk_y", right_on="i_item_sk"))
        out = x[["rnk_a", "i_product_name_x", "i_product_name_y"]].rename(
            columns={"rnk_a": "rnk", "i_product_name_x": "best_performing",
                     "i_product_name_y": "worst_performing"})
        out["rnk"] = out.rnk.astype(int)
        assert len(out) > 0
        return out
    run(env, "q44", oracle)


def test_q46(env):
    def oracle(F):
        dd, hd = F["date_dim"], F["household_demographics"]
        dn = (F["store_sales"]
              .merge(dd[dd.d_dow.isin([6, 0])
                        & dd.d_year.isin([1999, 2000, 2001])],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
              .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
              .merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                     left_on="ss_hdemo_sk", right_on="hd_demo_sk")
              .merge(F["customer_address"], left_on="ss_addr_sk",
                     right_on="ca_address_sk"))
        g = dn.groupby(["ss_customer_sk", "ss_addr_sk", "ca_city"],
                       as_index=False).agg(amt=("ss_coupon_amt", "sum"),
                                           profit=("ss_net_profit", "sum"))
        x = (g.merge(F["customer"], left_on="ss_customer_sk",
                     right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk", suffixes=("", "_cur")))
        x = x[x.ca_city_cur != x.ca_city]
        assert len(x) > 0
        out = x.rename(columns={"ca_city": "bought_city",
                                "ca_city_cur": "ca_city"})
        return out[["c_last_name", "c_first_name", "ca_city", "bought_city",
                    "amt", "profit"]]
    run(env, "q46", oracle, limit=1000)


def test_q47(env):
    def oracle(F):
        dd = F["date_dim"]
        sel = dd[(dd.d_year == 1999)
                 | ((dd.d_year == 1998) & (dd.d_moy == 12))
                 | ((dd.d_year == 2000) & (dd.d_moy == 1))]
        x = (F["store_sales"]
             .merge(sel, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk"))
        v1 = x.groupby(["i_category", "i_brand", "s_store_name", "d_year",
                        "d_moy"], as_index=False)["ss_sales_price"].sum()\
            .rename(columns={"ss_sales_price": "sum_sales"})
        v1["avg_monthly_sales"] = v1.groupby(
            ["i_category", "i_brand", "s_store_name", "d_year"]
        )["sum_sales"].transform("mean")
        v1 = v1.sort_values(["d_year", "d_moy"])
        v1["psum"] = v1.groupby(["i_category", "i_brand", "s_store_name"])[
            "sum_sales"].shift(1)
        v1["nsum"] = v1.groupby(["i_category", "i_brand", "s_store_name"])[
            "sum_sales"].shift(-1)
        out = v1[(v1.d_year == 1999) & (v1.avg_monthly_sales > 0)
                 & v1.psum.notna() & v1.nsum.notna()
                 & ((v1.sum_sales - v1.avg_monthly_sales).abs()
                    / v1.avg_monthly_sales > 0.1)]
        assert len(out) > 0
        out = out.sort_values(
            ["i_category", "i_brand", "s_store_name", "d_moy"]).head(100)
        return out[["i_category", "i_brand", "s_store_name", "d_year",
                    "d_moy", "avg_monthly_sales", "sum_sales", "psum",
                    "nsum"]]
    run(env, "q47", oracle, limit=None)


def test_q51(env):
    def oracle(F):
        dd = F["date_dim"][F["date_dim"].d_month_seq.between(24, 35)]

        def cume(fact, date_col, item_col, val_col):
            x = F[fact].merge(dd, left_on=date_col, right_on="d_date_sk")
            g = x.groupby([item_col, "d_date"], as_index=False)[
                val_col].sum()
            g = g.sort_values([item_col, "d_date"])
            g["cume_sales"] = g.groupby(item_col)[val_col].cumsum()
            return g.rename(columns={item_col: "item_sk"})[
                ["item_sk", "d_date", "cume_sales"]]

        web = cume("web_sales", "ws_sold_date_sk", "ws_item_sk",
                   "ws_sales_price")
        store = cume("store_sales", "ss_sold_date_sk", "ss_item_sk",
                     "ss_sales_price")
        m = web.merge(store, on=["item_sk", "d_date"], how="outer",
                      suffixes=("_w", "_s"))
        m = m[m.cume_sales_w > m.cume_sales_s]
        assert len(m) > 0
        out = m.rename(columns={"cume_sales_w": "web_sales",
                                "cume_sales_s": "store_sales"})
        out["d_date"] = out.d_date.astype(str)
        return out[["item_sk", "d_date", "web_sales", "store_sales"]]
    run(env, "q51", oracle)


# --- round-3 expansion batch 3 ----------------------------------------------


def test_q35(env):
    def oracle(F):
        dd = F["date_dim"]
        dsel = dd[(dd.d_year == 2002) & (dd.d_qoy < 4)].d_date_sk
        ss_c = set(F["store_sales"][
            F["store_sales"].ss_sold_date_sk.isin(dsel)].ss_customer_sk)
        ws_c = set(F["web_sales"][
            F["web_sales"].ws_sold_date_sk.isin(dsel)].ws_bill_customer_sk)
        cs_c = set(F["catalog_sales"][
            F["catalog_sales"].cs_sold_date_sk.isin(dsel)].cs_bill_customer_sk)
        c = F["customer"]
        c = c[c.c_customer_sk.isin(ss_c)
              & (c.c_customer_sk.isin(ws_c) | c.c_customer_sk.isin(cs_c))]
        x = (c.merge(F["customer_address"], left_on="c_current_addr_sk",
                     right_on="ca_address_sk")
             .merge(F["customer_demographics"], left_on="c_current_cdemo_sk",
                    right_on="cd_demo_sk"))
        assert len(x) > 0
        g = x.groupby(["ca_state", "cd_gender", "cd_marital_status",
                       "cd_dep_count"], as_index=False).agg(
            cnt1=("cd_dep_count", "size"), a1=("cd_dep_count", "mean"),
            m1=("cd_dep_count", "max"), s1=("cd_dep_count", "sum"))
        # ORDER BY covers the full (unique) group key: deterministic cut
        return g.sort_values(["ca_state", "cd_gender", "cd_marital_status",
                              "cd_dep_count"]).head(100)
    run(env, "q35", oracle, limit=None)


def test_q39(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["inventory"]
             .merge(dd[dd.d_year == 1999], left_on="inv_date_sk",
                    right_on="d_date_sk")
             .merge(F["item"], left_on="inv_item_sk", right_on="i_item_sk")
             .merge(F["warehouse"], left_on="inv_warehouse_sk",
                    right_on="w_warehouse_sk"))
        inv = x.groupby(["w_warehouse_sk", "i_item_sk", "d_moy"],
                        as_index=False).agg(
            stdev=("inv_quantity_on_hand", lambda v: v.std(ddof=1)),
            mean=("inv_quantity_on_hand", "mean"))
        i1 = inv[(inv.d_moy == 1) & (inv["mean"] > 0)
                 & (inv.stdev / inv["mean"] > 0.5)]
        i2 = inv[(inv.d_moy == 2) & (inv["mean"] > 0)]
        m = i1.merge(i2, on=["w_warehouse_sk", "i_item_sk"],
                     suffixes=("", "_2"))
        assert len(m) > 0
        out = pd.DataFrame({
            "w_warehouse_sk": m.w_warehouse_sk, "i_item_sk": m.i_item_sk,
            "d_moy": m.d_moy, "mean": m["mean"],
            "cov1": m.stdev / m["mean"], "d_moy_2": m.d_moy_2,
            "mean2": m.mean_2, "cov2": m.stdev_2 / m.mean_2})
        return out
    run(env, "q39", oracle, limit=200)


def test_q58(env):
    def oracle(F):
        dd = F["date_dim"]
        wk = dd[dd.d_date == "2000-03-11"].d_month_seq.iloc[0]
        dsel = dd[dd.d_month_seq == wk][["d_date_sk"]]

        def rev(fact, date_col, item_col, val_col, name):
            x = (F[fact].merge(dsel, left_on=date_col, right_on="d_date_sk")
                 .merge(F["item"], left_on=item_col, right_on="i_item_sk"))
            return x.groupby("i_item_id", as_index=False)[val_col].sum()\
                .rename(columns={val_col: name, "i_item_id": "item_id"})

        s = rev("store_sales", "ss_sold_date_sk", "ss_item_sk",
                "ss_ext_sales_price", "ss_item_rev")
        c = rev("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                "cs_ext_sales_price", "cs_item_rev")
        w = rev("web_sales", "ws_sold_date_sk", "ws_item_sk",
                "ws_ext_sales_price", "ws_item_rev")
        m = s.merge(c, on="item_id").merge(w, on="item_id")
        m = m[m.ss_item_rev.between(0.5 * m.cs_item_rev, 2.0 * m.cs_item_rev)
              & m.ss_item_rev.between(0.5 * m.ws_item_rev,
                                      2.0 * m.ws_item_rev)]
        assert len(m) > 0
        return m[["item_id", "ss_item_rev", "cs_item_rev", "ws_item_rev"]]
    run(env, "q58", oracle)


def test_q59(env):
    def oracle(F):
        x = F["store_sales"].merge(F["date_dim"], left_on="ss_sold_date_sk",
                                   right_on="d_date_sk")
        for day, col in [("Sunday", "sun"), ("Monday", "mon"),
                         ("Friday", "fri")]:
            x[col] = x.ss_sales_price.where(x.d_day_name == day, 0.0)
        wss = x.groupby(["d_week_seq", "ss_store_sk"], as_index=False).agg(
            sun_sales=("sun", "sum"), mon_sales=("mon", "sum"),
            fri_sales=("fri", "sum"))
        y = wss[wss.d_week_seq.between(52, 103)]
        xx = wss.copy()
        xx["d_week_seq"] = xx.d_week_seq - 52
        m = y.merge(xx, on=["d_week_seq", "ss_store_sk"],
                    suffixes=("_y", "_x"))
        m = m[(m.sun_sales_x > 0) & (m.mon_sales_x > 0)
              & (m.fri_sales_x > 0)]
        m = m.merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
        assert len(m) > 0
        return pd.DataFrame({
            "s_store_name": m.s_store_name, "week1": m.d_week_seq,
            "r_sun": m.sun_sales_y / m.sun_sales_x,
            "r_mon": m.mon_sales_y / m.mon_sales_x,
            "r_fri": m.fri_sales_y / m.fri_sales_x})
    run(env, "q59", oracle, limit=200)


def test_q60(env):
    def oracle(F):
        dd, ca, it = F["date_dim"], F["customer_address"], F["item"]
        iids = set(it[it.i_category == "Children"].i_item_id)

        def chan(fact, date_col, item_col, addr_col, val_col):
            x = (F[fact]
                 .merge(dd[(dd.d_year == 1999) & (dd.d_moy == 9)],
                        left_on=date_col, right_on="d_date_sk")
                 .merge(it[it.i_item_id.isin(iids)], left_on=item_col,
                        right_on="i_item_sk")
                 .merge(ca[ca.ca_gmt_offset == -5], left_on=addr_col,
                        right_on="ca_address_sk"))
            return x.groupby("i_item_id", as_index=False)[val_col].sum()\
                .rename(columns={val_col: "total_sales"})

        u = pd.concat([
            chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
                 "ss_addr_sk", "ss_ext_sales_price"),
            chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_bill_addr_sk", "cs_ext_sales_price"),
            chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                 "ws_bill_addr_sk", "ws_ext_sales_price")])
        g = u.groupby("i_item_id", as_index=False)["total_sales"].sum()
        assert len(g) > 0
        return g
    run(env, "q60", oracle)


def test_q63(env):
    def oracle(F):
        it = F["item"]
        m = ((it.i_category.isin(["Books", "Children", "Electronics"])
              & it.i_class.isin(["class01", "class02", "class03", "class04"]))
             | (it.i_category.isin(["Women", "Music", "Men"])
                & it.i_class.isin(["class05", "class06", "class07",
                                   "class08"])))
        x = (F["store_sales"]
             .merge(F["date_dim"][F["date_dim"].d_year == 1999],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(it[m], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk"))
        g = x.groupby(["i_manager_id", "d_moy"], as_index=False)[
            "ss_sales_price"].sum().rename(
            columns={"ss_sales_price": "sum_sales"})
        g["avg_monthly"] = g.groupby("i_manager_id")[
            "sum_sales"].transform("mean")
        out = g[(g.avg_monthly > 0)
                & ((g.sum_sales - g.avg_monthly).abs() / g.avg_monthly
                   > 0.0001)]
        assert len(out) > 0
        out = out.rename(columns={"i_manager_id": "mgr"})[
            ["mgr", "sum_sales", "avg_monthly"]]
        # (mgr, sum_sales) is effectively unique (distinct float sums)
        return out.sort_values(["mgr", "sum_sales"]).head(100)
    run(env, "q63", oracle, limit=None)


def test_q66(env):
    def oracle(F):
        dd, w, sm = F["date_dim"], F["warehouse"], F["ship_mode"]
        carriers = ["DHL", "BARIAN", "UPS", "FEDEX", "AIRBORNE", "USPS",
                    "TBS", "ZOUROS", "MSC", "LATVIAN"]
        td = F["time_dim"]

        def chan(fact, date_col, wh_col, sm_col, price, qty,
                 time_col=None):
            x = (F[fact]
                 .merge(dd[dd.d_year == 1999], left_on=date_col,
                        right_on="d_date_sk")
                 .merge(w, left_on=wh_col, right_on="w_warehouse_sk")
                 .merge(sm[sm.sm_carrier.isin(carriers)], left_on=sm_col,
                        right_on="sm_ship_mode_sk"))
            if time_col is not None:
                x = x.merge(td[td.t_hour.between(8, 17)], left_on=time_col,
                            right_on="t_time_sk")
            for moy, col in [(1, "jan"), (2, "feb"), (3, "mar")]:
                x[col] = (x[price] * x[qty]).where(x.d_moy == moy, 0.0)
            return x.groupby(["w_warehouse_name", "w_warehouse_sq_ft",
                              "d_year"], as_index=False).agg(
                jan_sales=("jan", "sum"), feb_sales=("feb", "sum"),
                mar_sales=("mar", "sum"))

        u = pd.concat([
            chan("web_sales", "ws_sold_date_sk", "ws_warehouse_sk",
                 "ws_ship_mode_sk", "ws_ext_sales_price", "ws_quantity",
                 "ws_sold_time_sk"),
            chan("catalog_sales", "cs_sold_date_sk", "cs_warehouse_sk",
                 "cs_ship_mode_sk", "cs_ext_sales_price", "cs_quantity")])
        u["ship_carriers"] = "DHL,BARIAN"
        g = u.groupby(["w_warehouse_name", "w_warehouse_sq_ft",
                       "ship_carriers", "d_year"], as_index=False).agg(
            jan_sales=("jan_sales", "sum"), feb_sales=("feb_sales", "sum"),
            mar_sales=("mar_sales", "sum"))
        assert len(g) > 0
        return g
    run(env, "q66", oracle)


def test_q71(env):
    def oracle(F):
        dd, it, td = F["date_dim"], F["item"], F["time_dim"]
        dsel = dd[(dd.d_moy == 11) & (dd.d_year == 1999)][["d_date_sk"]]
        w = F["web_sales"].merge(dsel, left_on="ws_sold_date_sk",
                                 right_on="d_date_sk")
        w = w[["ws_ext_sales_price", "ws_item_sk", "ws_sold_time_sk"]]
        w.columns = ["ext_price", "sold_item_sk", "time_sk"]
        s = F["store_sales"].merge(dsel, left_on="ss_sold_date_sk",
                                   right_on="d_date_sk")
        s = s[["ss_ext_sales_price", "ss_item_sk", "ss_sold_time_sk"]]
        s.columns = ["ext_price", "sold_item_sk", "time_sk"]
        u = pd.concat([w, s])
        x = (u.merge(it[it.i_manager_id == 1], left_on="sold_item_sk",
                     right_on="i_item_sk")
             .merge(td[td.t_hour.between(7, 9) | td.t_hour.between(19, 21)],
                    left_on="time_sk", right_on="t_time_sk"))
        g = x.groupby(["i_brand", "i_brand_id", "t_hour", "t_minute"],
                      as_index=False)["ext_price"].sum()
        assert len(g) > 0
        return g.rename(columns={"i_brand_id": "brand_id",
                                 "i_brand": "brand"})[
            ["brand_id", "brand", "t_hour", "t_minute", "ext_price"]]
    run(env, "q71", oracle, limit=200)


def test_q73(env):
    def oracle(F):
        hd = F["household_demographics"]
        hsel = hd[hd.hd_buy_potential.isin(["501-1000", "5001-10000"])
                  & (hd.hd_vehicle_count > 0)]
        hsel = hsel[hsel.hd_dep_count / hsel.hd_vehicle_count > 0]
        x = (F["store_sales"]
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(hsel, left_on="ss_hdemo_sk", right_on="hd_demo_sk"))
        g = (x.groupby("ss_customer_sk", as_index=False).size()
             .rename(columns={"size": "cnt"}))
        g = g[g.cnt.between(3, 8)]
        out = g.merge(F["customer"], left_on="ss_customer_sk",
                      right_on="c_customer_sk")
        assert len(out) > 0
        return out[["c_last_name", "c_first_name", "c_customer_id", "cnt"]]
    run(env, "q73", oracle, limit=1000)


def test_q76(env):
    def oracle(F):
        dd, it = F["date_dim"], F["item"]

        def chan(fact, channel, col_name, promo, date_col, item_col, val):
            f = F[fact]
            x = (f[f[promo].isna()]
                 .merge(dd, left_on=date_col, right_on="d_date_sk")
                 .merge(it, left_on=item_col, right_on="i_item_sk"))
            x = x.assign(channel=channel, col_name=col_name,
                         ext_sales_price=x[val])
            return x[["channel", "col_name", "d_year", "d_qoy", "i_category",
                      "ext_sales_price"]]

        u = pd.concat([
            chan("store_sales", "store", "ss_promo_sk", "ss_promo_sk",
                 "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"),
            chan("web_sales", "web", "ws_promo_sk", "ws_promo_sk",
                 "ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price"),
            chan("catalog_sales", "catalog", "cs_promo_sk", "cs_promo_sk",
                 "cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price")])
        assert len(u) > 0
        g = u.groupby(["channel", "col_name", "d_year", "d_qoy",
                       "i_category"], as_index=False).agg(
            sales_cnt=("ext_sales_price", "size"),
            sales_amt=("ext_sales_price", "sum"))
        # ORDER BY covers the full (unique) group key: deterministic cut
        return g.sort_values(["channel", "col_name", "d_year", "d_qoy",
                              "i_category"]).head(500)
    run(env, "q76", oracle, limit=None)


def test_q84(env):
    def oracle(F):
        ib = F["income_band"]
        ib = ib[(ib.ib_lower_bound >= 10000) & (ib.ib_upper_bound <= 200000)]
        x = (F["customer"]
             .merge(F["customer_address"][
                 F["customer_address"].ca_city == "Riverside"],
                 left_on="c_current_addr_sk", right_on="ca_address_sk")
             .merge(F["household_demographics"], left_on="c_current_hdemo_sk",
                    right_on="hd_demo_sk")
             .merge(ib, left_on="hd_income_band_sk",
                    right_on="ib_income_band_sk")
             .merge(F["customer_demographics"], left_on="c_current_cdemo_sk",
                    right_on="cd_demo_sk"))
        assert len(x) > 0
        out = x.rename(columns={"c_customer_id": "customer_id",
                                "c_last_name": "customername"})
        return out[["customer_id", "customername"]].sort_values(
            "customer_id").head(100)
    run(env, "q84", oracle, limit=None)


def test_q85(env):
    def oracle(F):
        cd = F["customer_demographics"]
        x = (F["web_sales"]
             .merge(F["web_page"], left_on="ws_web_page_sk",
                    right_on="wp_web_page_sk")
             .merge(F["web_returns"],
                    left_on=["ws_item_sk", "ws_order_number"],
                    right_on=["wr_item_sk", "wr_order_number"])
             .merge(cd, left_on="wr_refunded_cdemo_sk", right_on="cd_demo_sk")
             .merge(F["reason"], left_on="wr_reason_sk",
                    right_on="r_reason_sk"))
        m = (((x.cd_marital_status == "M")
              & (x.cd_education_status == "Advanced Degree")
              & x.ws_sales_price.between(50, 150))
             | ((x.cd_marital_status == "S")
                & (x.cd_education_status == "College")
                & x.ws_sales_price.between(10, 100))
             | ((x.cd_marital_status == "W")
                & (x.cd_education_status == "2 yr Degree")
                & x.ws_sales_price.between(50, 200)))
        x = x[m]
        assert len(x) > 0
        return x.groupby("r_reason_desc", as_index=False).agg(
            a1=("ws_quantity", "mean"), a2=("wr_return_amt", "mean"),
            a3=("wr_fee", "mean"))
    run(env, "q85", oracle)


def test_q90(env):
    def oracle(F):
        x = (F["web_sales"]
             .merge(F["time_dim"], left_on="ws_sold_time_sk",
                    right_on="t_time_sk")
             .merge(F["web_page"][
                 F["web_page"].wp_char_count.between(2500, 5200)],
                 left_on="ws_web_page_sk", right_on="wp_web_page_sk"))
        amc = len(x[x.t_hour.between(8, 9)])
        pmc = len(x[x.t_hour.between(19, 20)])
        assert pmc > 0
        return pd.DataFrame([{"am_pm_ratio": amc / pmc}])
    run(env, "q90", oracle)


def test_q91(env):
    def oracle(F):
        dd = F["date_dim"]
        cd, hd = F["customer_demographics"], F["household_demographics"]
        x = (F["catalog_returns"]
             .merge(F["call_center"], left_on="cr_call_center_sk",
                    right_on="cc_call_center_sk")
             .merge(dd[dd.d_year == 1999],
                    left_on="cr_returned_date_sk", right_on="d_date_sk")
             .merge(F["customer"], left_on="cr_returning_customer_sk",
                    right_on="c_customer_sk")
             .merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
             .merge(hd, left_on="c_current_hdemo_sk", right_on="hd_demo_sk"))
        m = (((x.cd_marital_status == "M")
              & (x.cd_education_status == "Unknown"))
             | ((x.cd_marital_status == "W")
                & (x.cd_education_status == "Advanced Degree")))
        x = x[m & x.hd_buy_potential.str.startswith("Unknown")]
        assert len(x) > 0
        out = x.groupby(["cc_call_center_id", "cc_name"],
                        as_index=False)["cr_net_loss"].sum()
        return out.rename(columns={"cc_call_center_id": "call_center",
                                   "cr_net_loss": "returns_loss"})
    run(env, "q91", oracle)


def test_q93(env):
    def oracle(F):
        x = F["store_sales"].merge(
            F["store_returns"], left_on=["ss_item_sk", "ss_ticket_number"],
            right_on=["sr_item_sk", "sr_ticket_number"], how="left")
        x = x[x.sr_reason_sk == 5]
        x["act_sales"] = np.where(
            x.sr_return_quantity.notna(),
            (x.ss_quantity - x.sr_return_quantity) * x.ss_sales_price,
            x.ss_quantity * x.ss_sales_price)
        g = x.groupby("ss_customer_sk", as_index=False)["act_sales"].sum()
        g = g.rename(columns={"act_sales": "sumsales"})
        assert len(g) > 0
        return g.sort_values(["sumsales", "ss_customer_sk"]).head(100)
    run(env, "q93", oracle, limit=None)


def test_q81(env):
    def oracle(F):
        ctr = (F["catalog_returns"]
               .merge(F["date_dim"][F["date_dim"].d_year == 2000],
                      left_on="cr_returned_date_sk", right_on="d_date_sk")
               .merge(F["customer"], left_on="cr_returning_customer_sk",
                      right_on="c_customer_sk")
               .merge(F["customer_address"], left_on="c_current_addr_sk",
                      right_on="ca_address_sk")
               .groupby(["cr_returning_customer_sk", "ca_state"],
                        as_index=False)["cr_return_amount"].sum()
               .rename(columns={"cr_return_amount": "ctr_total_return"}))
        avg_by_state = ctr.groupby("ca_state")["ctr_total_return"].mean()
        x = ctr[ctr.ctr_total_return > 1.2 * ctr.ca_state.map(avg_by_state)]
        out = x.merge(F["customer"], left_on="cr_returning_customer_sk",
                      right_on="c_customer_sk")
        assert len(out) > 0
        return out[["c_customer_id", "c_first_name", "c_last_name",
                    "ctr_total_return"]].sort_values("c_customer_id").head(100)
    run(env, "q81", oracle, limit=None)


def test_q86(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["web_sales"]
             .merge(dd[dd.d_month_seq.between(12, 23)],
                    left_on="ws_sold_date_sk", right_on="d_date_sk")
             .merge(F["item"], left_on="ws_item_sk", right_on="i_item_sk"))
        assert len(x) > 0

        def agg(sub):
            return {"total_sum": sub.ws_net_paid.sum()}

        lv = rollup_levels(x, ["i_category", "i_class"], agg,
                           grouping_cols="all")
        lv["lochierarchy"] = lv["__g0"] + lv["__g1"]
        lv["parent"] = lv.i_category.where(lv["__g1"] == 0, None)
        lv["rank_within_parent"] = lv.groupby(
            ["lochierarchy", "parent"], dropna=False)["total_sum"].rank(
            method="min", ascending=False).astype(int)
        lv = lv.sort_values(
            ["lochierarchy", "i_category", "i_class"],
            ascending=[False, True, True], na_position="last").head(100)
        return lv[["total_sum", "i_category", "i_class", "lochierarchy",
                   "rank_within_parent"]]
    run(env, "q86", oracle, limit=None)


# --- the year-over-year / cross-channel family (round 4) --------------------

def test_q2(env):
    def oracle(F):
        ws = F["web_sales"][["ws_sold_date_sk", "ws_ext_sales_price"]].rename(
            columns={"ws_sold_date_sk": "sk", "ws_ext_sales_price": "p"})
        cs = F["catalog_sales"][
            ["cs_sold_date_sk", "cs_ext_sales_price"]].rename(
            columns={"cs_sold_date_sk": "sk", "cs_ext_sales_price": "p"})
        u = pd.concat([ws, cs]).merge(
            F["date_dim"], left_on="sk", right_on="d_date_sk")
        piv = u.pivot_table(index="d_week_seq", columns="d_day_name",
                            values="p", aggfunc="sum")
        wk = F["date_dim"][["d_week_seq", "d_year"]].drop_duplicates()
        days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
                "Friday", "Saturday"]
        y = piv.reindex(columns=days).reset_index().merge(wk, on="d_week_seq")
        a = y[y.d_year == 1999].copy()
        b = y[y.d_year == 2000].copy()
        b["join_seq"] = b.d_week_seq - 53
        m = a.merge(b, left_on="d_week_seq", right_on="join_seq",
                    suffixes=("_1", "_2"))
        out = pd.DataFrame({"week1": m.d_week_seq_1})
        for d in days:
            out["r_" + d[:3].lower()] = m[d + "_1"] / m[d + "_2"]
        return out.sort_values("week1")
    run(env, "q2", oracle, limit=None)


def _year_total(F, fact, cust_col, date_col, expr_fn, tag, years=None):
    x = (F[fact]
         .merge(F["customer"], left_on=cust_col, right_on="c_customer_sk")
         .merge(F["date_dim"], left_on=date_col, right_on="d_date_sk"))
    if years is not None:
        x = x[x.d_year.isin(years)]
    x = x.assign(val=expr_fn(x))
    g = x.groupby(["c_customer_id", "c_first_name", "c_last_name", "d_year"],
                  as_index=False)["val"].sum()
    g["sale_type"] = tag
    return g.rename(columns={"c_customer_id": "customer_id",
                             "val": "year_total"})


def _yoy_join(yt, chans, first=1999, sec=2000):
    """Self-join year_total instances keyed by customer_id; returns dict of
    per-(channel, year) frames indexed by customer_id."""
    out = {}
    for ch in chans:
        for yr, nm in ((first, "first"), (sec, "sec")):
            sub = yt[(yt.sale_type == ch) & (yt.d_year == yr)]
            out[f"{ch}_{nm}"] = sub.set_index("customer_id")
    return out


def test_q4(env):
    def oracle(F):
        yt = pd.concat([
            _year_total(F, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk",
                        lambda x: ((x.ss_ext_list_price
                                    - x.ss_ext_wholesale_cost
                                    - x.ss_ext_discount_amt)
                                   + x.ss_ext_sales_price) / 2, "s"),
            _year_total(F, "catalog_sales", "cs_bill_customer_sk",
                        "cs_sold_date_sk",
                        lambda x: ((x.cs_ext_list_price - x.cs_wholesale_cost
                                    - x.cs_ext_discount_amt)
                                   + x.cs_ext_sales_price) / 2, "c"),
            _year_total(F, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk",
                        lambda x: ((x.ws_ext_list_price
                                    - x.ws_ext_wholesale_cost
                                    - x.ws_ext_discount_amt)
                                   + x.ws_ext_sales_price) / 2, "w"),
        ])
        t = _yoy_join(yt, "scw")
        ids = (set(t["s_first"].index) & set(t["s_sec"].index)
               & set(t["c_first"].index) & set(t["c_sec"].index)
               & set(t["w_first"].index) & set(t["w_sec"].index))
        rows = []
        for cid in ids:
            sf, ssec = t["s_first"].loc[cid], t["s_sec"].loc[cid]
            cf, csec = t["c_first"].loc[cid], t["c_sec"].loc[cid]
            wf, wsec = t["w_first"].loc[cid], t["w_sec"].loc[cid]
            if not (sf.year_total > 0 and cf.year_total > 0
                    and wf.year_total > 0):
                continue
            cr = csec.year_total / cf.year_total
            sr = ssec.year_total / sf.year_total
            wr = wsec.year_total / wf.year_total
            if cr > sr and cr > wr:
                rows.append((cid, ssec.c_first_name, ssec.c_last_name))
        # ORDER BY customer_id is total (unique), LIMIT is deterministic
        return pd.DataFrame(
            rows, columns=["customer_id", "first", "last"]).sort_values(
            "customer_id").head(100)
    run(env, "q4", oracle, limit=None)


def test_q11(env):
    def oracle(F):
        yt = pd.concat([
            _year_total(F, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk",
                        lambda x: x.ss_ext_list_price - x.ss_ext_discount_amt,
                        "s"),
            _year_total(F, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk",
                        lambda x: x.ws_ext_list_price - x.ws_ext_discount_amt,
                        "w"),
        ])
        t = _yoy_join(yt, "sw")
        ids = (set(t["s_first"].index) & set(t["s_sec"].index)
               & set(t["w_first"].index) & set(t["w_sec"].index))
        rows = []
        for cid in ids:
            sf, ssec = t["s_first"].loc[cid], t["s_sec"].loc[cid]
            wf, wsec = t["w_first"].loc[cid], t["w_sec"].loc[cid]
            if not (sf.year_total > 0 and wf.year_total > 0):
                continue
            if (wsec.year_total / wf.year_total
                    > ssec.year_total / sf.year_total):
                rows.append((cid, ssec.c_first_name, ssec.c_last_name))
        cols = (["first", "last", "customer_id"] if "q11" == "q74"
                else ["customer_id"])
        return pd.DataFrame(
            rows, columns=["customer_id", "first", "last"]).sort_values(
            cols).head(100)
    run(env, "q11", oracle, limit=None)


def test_q74(env):
    def oracle(F):
        yt = pd.concat([
            _year_total(F, "store_sales", "ss_customer_sk",
                        "ss_sold_date_sk", lambda x: x.ss_net_paid, "s",
                        years=(1999, 2000)),
            _year_total(F, "web_sales", "ws_bill_customer_sk",
                        "ws_sold_date_sk", lambda x: x.ws_net_paid, "w",
                        years=(1999, 2000)),
        ])
        t = _yoy_join(yt, "sw")
        ids = (set(t["s_first"].index) & set(t["s_sec"].index)
               & set(t["w_first"].index) & set(t["w_sec"].index))
        rows = []
        for cid in ids:
            sf, ssec = t["s_first"].loc[cid], t["s_sec"].loc[cid]
            wf, wsec = t["w_first"].loc[cid], t["w_sec"].loc[cid]
            if not (sf.year_total > 0 and wf.year_total > 0):
                continue
            if (wsec.year_total / wf.year_total
                    > ssec.year_total / sf.year_total):
                rows.append((cid, ssec.c_first_name, ssec.c_last_name))
        cols = (["first", "last", "customer_id"] if "q74" == "q74"
                else ["customer_id"])
        return pd.DataFrame(
            rows, columns=["customer_id", "first", "last"]).sort_values(
            cols).head(100)
    run(env, "q74", oracle, limit=None)


def test_q97(env):
    def oracle(F):
        dd = F["date_dim"]
        dd = dd[(dd.d_month_seq >= 24) & (dd.d_month_seq <= 35)]
        ss = F["store_sales"].merge(dd, left_on="ss_sold_date_sk",
                                    right_on="d_date_sk")
        ssci = ss[["ss_customer_sk", "ss_item_sk"]].drop_duplicates().rename(
            columns={"ss_customer_sk": "customer_sk",
                     "ss_item_sk": "item_sk"})
        cs = F["catalog_sales"].merge(dd, left_on="cs_sold_date_sk",
                                      right_on="d_date_sk")
        csci = cs[["cs_bill_customer_sk", "cs_item_sk"]].drop_duplicates(
            ).rename(columns={"cs_bill_customer_sk": "customer_sk",
                              "cs_item_sk": "item_sk"})
        m = ssci.merge(csci, on=["customer_sk", "item_sk"], how="outer",
                       indicator=True)
        return pd.DataFrame([{
            "store_only": int((m._merge == "left_only").sum()),
            "catalog_only": int((m._merge == "right_only").sum()),
            "store_and_catalog": int((m._merge == "both").sum()),
        }])
    run(env, "q97", oracle, limit=None)


def _rollup_channel(detail):
    """ROLLUP (channel, id) over a detail frame with sales/returns/profit."""
    return rollup_levels(
        detail, ["channel", "id"],
        lambda sub: {"sales": sub.sales.sum(),
                     "returns_amt": sub.returns_amt.sum(),
                     "profit": sub.profit.sum()})


def test_q5(env):
    def oracle(F):
        dd = F["date_dim"]
        dd = dd[(dd.d_date_sk >= 2451100) & (dd.d_date_sk <= 2451114)]
        ss = F["store_sales"]; sr = F["store_returns"]
        s_part = pd.concat([
            pd.DataFrame({"store_sk": ss.ss_store_sk,
                          "date_sk": ss.ss_sold_date_sk,
                          "sales_price": ss.ss_ext_sales_price,
                          "profit": ss.ss_net_profit,
                          "return_amt": 0.0, "net_loss": 0.0}),
            pd.DataFrame({"store_sk": sr.sr_store_sk,
                          "date_sk": sr.sr_returned_date_sk,
                          "sales_price": 0.0, "profit": 0.0,
                          "return_amt": sr.sr_return_amt,
                          "net_loss": sr.sr_net_loss})])
        s_part = (s_part.merge(dd, left_on="date_sk", right_on="d_date_sk")
                  .merge(F["store"], left_on="store_sk",
                         right_on="s_store_sk")
                  .groupby("s_store_id", as_index=False)
                  .agg(sales=("sales_price", "sum"),
                       returns_amt=("return_amt", "sum"),
                       profit=("profit", "sum"),
                       profit_loss=("net_loss", "sum")))
        cs = F["catalog_sales"]; cr = F["catalog_returns"]
        c_part = pd.concat([
            pd.DataFrame({"center_sk": cs.cs_call_center_sk,
                          "date_sk": cs.cs_sold_date_sk,
                          "sales_price": cs.cs_ext_sales_price,
                          "profit": cs.cs_net_profit,
                          "return_amt": 0.0, "net_loss": 0.0}),
            pd.DataFrame({"center_sk": cr.cr_call_center_sk,
                          "date_sk": cr.cr_returned_date_sk,
                          "sales_price": 0.0, "profit": 0.0,
                          "return_amt": cr.cr_return_amount,
                          "net_loss": cr.cr_net_loss})])
        c_part = (c_part.merge(dd, left_on="date_sk", right_on="d_date_sk")
                  .merge(F["call_center"], left_on="center_sk",
                         right_on="cc_call_center_sk")
                  .groupby("cc_call_center_id", as_index=False)
                  .agg(sales=("sales_price", "sum"),
                       returns_amt=("return_amt", "sum"),
                       profit=("profit", "sum"),
                       profit_loss=("net_loss", "sum")))
        wsl = F["web_sales"]; wrt = F["web_returns"]
        wret = wrt.merge(wsl[["ws_item_sk", "ws_order_number",
                              "ws_web_site_sk"]],
                         left_on=["wr_item_sk", "wr_order_number"],
                         right_on=["ws_item_sk", "ws_order_number"])
        w_part = pd.concat([
            pd.DataFrame({"site_sk": wsl.ws_web_site_sk,
                          "date_sk": wsl.ws_sold_date_sk,
                          "sales_price": wsl.ws_ext_sales_price,
                          "profit": wsl.ws_net_profit,
                          "return_amt": 0.0, "net_loss": 0.0}),
            pd.DataFrame({"site_sk": wret.ws_web_site_sk,
                          "date_sk": wret.wr_returned_date_sk,
                          "sales_price": 0.0, "profit": 0.0,
                          "return_amt": wret.wr_return_amt,
                          "net_loss": wret.wr_net_loss})])
        w_part = (w_part.merge(dd, left_on="date_sk", right_on="d_date_sk")
                  .merge(F["web_site"], left_on="site_sk",
                         right_on="web_site_sk")
                  .groupby("web_site_id", as_index=False)
                  .agg(sales=("sales_price", "sum"),
                       returns_amt=("return_amt", "sum"),
                       profit=("profit", "sum"),
                       profit_loss=("net_loss", "sum")))
        detail = pd.concat([
            pd.DataFrame({"channel": "store channel", "id": s_part.s_store_id,
                          "sales": s_part.sales,
                          "returns_amt": s_part.returns_amt,
                          "profit": s_part.profit - s_part.profit_loss}),
            pd.DataFrame({"channel": "catalog channel",
                          "id": c_part.cc_call_center_id,
                          "sales": c_part.sales,
                          "returns_amt": c_part.returns_amt,
                          "profit": c_part.profit - c_part.profit_loss}),
            pd.DataFrame({"channel": "web channel", "id": w_part.web_site_id,
                          "sales": w_part.sales,
                          "returns_amt": w_part.returns_amt,
                          "profit": w_part.profit - w_part.profit_loss})])
        out = _rollup_channel(detail)
        return out[["channel", "id", "sales", "returns_amt", "profit"]]
    run(env, "q5", oracle, limit=None)


def test_q77(env):
    def oracle(F):
        lo, hi = 2451100, 2451129
        dd = F["date_dim"]
        dd = dd[(dd.d_date_sk >= lo) & (dd.d_date_sk <= hi)][["d_date_sk"]]
        ss = (F["store_sales"]
              .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
              .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
              .groupby("s_store_sk", as_index=False)
              .agg(sales=("ss_ext_sales_price", "sum"),
                   profit=("ss_net_profit", "sum")))
        sr = (F["store_returns"]
              .merge(dd, left_on="sr_returned_date_sk", right_on="d_date_sk")
              .merge(F["store"], left_on="sr_store_sk", right_on="s_store_sk")
              .groupby("s_store_sk", as_index=False)
              .agg(returns_amt=("sr_return_amt", "sum"),
                   profit_loss=("sr_net_loss", "sum")))
        s = ss.merge(sr, on="s_store_sk", how="left")
        cs = (F["catalog_sales"]
              .merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
              .groupby("cs_call_center_sk", as_index=False)
              .agg(sales=("cs_ext_sales_price", "sum"),
                   profit=("cs_net_profit", "sum")))
        cr = (F["catalog_returns"]
              .merge(dd, left_on="cr_returned_date_sk", right_on="d_date_sk")
              .groupby("cr_call_center_sk", as_index=False)
              .agg(returns_amt=("cr_return_amount", "sum"),
                   profit_loss=("cr_net_loss", "sum")))
        c = cs.merge(cr, left_on="cs_call_center_sk",
                     right_on="cr_call_center_sk", how="left")
        ws = (F["web_sales"]
              .merge(dd, left_on="ws_sold_date_sk", right_on="d_date_sk")
              .merge(F["web_page"], left_on="ws_web_page_sk",
                     right_on="wp_web_page_sk")
              .groupby("wp_web_page_sk", as_index=False)
              .agg(sales=("ws_ext_sales_price", "sum"),
                   profit=("ws_net_profit", "sum")))
        wr = (F["web_returns"]
              .merge(F["web_sales"][["ws_item_sk", "ws_order_number",
                                     "ws_web_page_sk"]],
                     left_on=["wr_item_sk", "wr_order_number"],
                     right_on=["ws_item_sk", "ws_order_number"])
              .merge(dd, left_on="wr_returned_date_sk", right_on="d_date_sk")
              .merge(F["web_page"], left_on="ws_web_page_sk",
                     right_on="wp_web_page_sk")
              .groupby("wp_web_page_sk", as_index=False)
              .agg(returns_amt=("wr_return_amt", "sum"),
                   profit_loss=("wr_net_loss", "sum")))
        w = ws.merge(wr, on="wp_web_page_sk", how="left")
        def chan(df, name, idcol):
            return pd.DataFrame({
                "channel": name, "id": df[idcol], "sales": df.sales,
                "returns_amt": df.returns_amt.fillna(0.0),
                "profit": df.profit - df.profit_loss.fillna(0.0)})
        detail = pd.concat([chan(s, "store channel", "s_store_sk"),
                            chan(c, "catalog channel", "cs_call_center_sk"),
                            chan(w, "web channel", "wp_web_page_sk")])
        out = _rollup_channel(detail)
        return out[["channel", "id", "sales", "returns_amt", "profit"]]
    run(env, "q77", oracle, limit=None)


def test_q80(env):
    def oracle(F):
        lo, hi = 2451100, 2451129
        dd = F["date_dim"]
        dd = dd[(dd.d_date_sk >= lo) & (dd.d_date_sk <= hi)][["d_date_sk"]]
        it = F["item"][F["item"].i_current_price > 50][["i_item_sk"]]
        pr = F["promotion"]
        pr = pr[pr.p_channel_tv == "N"][["p_promo_sk"]]
        def channel(sales, returns, skey, rkey, scol, rcol, idtab, idjoin,
                    idcol, date_col, item_col, promo_col, sp, np_, ra, nl):
            x = (sales.merge(returns, left_on=skey, right_on=rkey,
                             how="left")
                 .merge(dd, left_on=date_col, right_on="d_date_sk")
                 .merge(idtab, left_on=idjoin[0], right_on=idjoin[1])
                 .merge(it, left_on=item_col, right_on="i_item_sk")
                 .merge(pr, left_on=promo_col, right_on="p_promo_sk"))
            return (x.assign(
                sales=x[sp], returns_amt=x[ra].fillna(0.0),
                profit=x[np_] - x[nl].fillna(0.0))
                .groupby(idcol, as_index=False)
                .agg(sales=("sales", "sum"),
                     returns_amt=("returns_amt", "sum"),
                     profit=("profit", "sum"))
                .rename(columns={idcol: "id"}))
        s = channel(F["store_sales"], F["store_returns"],
                    ["ss_item_sk", "ss_ticket_number"],
                    ["sr_item_sk", "sr_ticket_number"], None, None,
                    F["store"], ("ss_store_sk", "s_store_sk"), "s_store_id",
                    "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
                    "ss_ext_sales_price", "ss_net_profit",
                    "sr_return_amt", "sr_net_loss")
        c = channel(F["catalog_sales"], F["catalog_returns"],
                    ["cs_item_sk", "cs_order_number"],
                    ["cr_item_sk", "cr_order_number"], None, None,
                    F["call_center"],
                    ("cs_call_center_sk", "cc_call_center_sk"),
                    "cc_call_center_id",
                    "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
                    "cs_ext_sales_price", "cs_net_profit",
                    "cr_return_amount", "cr_net_loss")
        w = channel(F["web_sales"], F["web_returns"],
                    ["ws_item_sk", "ws_order_number"],
                    ["wr_item_sk", "wr_order_number"], None, None,
                    F["web_site"], ("ws_web_site_sk", "web_site_sk"),
                    "web_site_id",
                    "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
                    "ws_ext_sales_price", "ws_net_profit",
                    "wr_return_amt", "wr_net_loss")
        detail = pd.concat([s.assign(channel="store channel"),
                            c.assign(channel="catalog channel"),
                            w.assign(channel="web channel")])
        out = _rollup_channel(detail)
        return out[["channel", "id", "sales", "returns_amt", "profit"]]
    run(env, "q80", oracle, limit=None)


def test_q75(env):
    def oracle(F):
        it = F["item"]
        it = it[it.i_category == "Electronics"]
        def detail(sales, returns, skeys, rkeys, icol, dcol, qty, amt, rqty,
                   ramt):
            x = (sales.merge(it, left_on=icol, right_on="i_item_sk")
                 .merge(F["date_dim"], left_on=dcol, right_on="d_date_sk")
                 .merge(returns, left_on=skeys, right_on=rkeys, how="left"))
            return pd.DataFrame({
                "d_year": x.d_year, "i_brand_id": x.i_brand_id,
                "i_class_id": x.i_class_id, "i_category_id": x.i_category_id,
                "i_manufact_id": x.i_manufact_id,
                "sales_cnt": x[qty] - x[rqty].fillna(0).astype(int),
                "sales_amt": x[amt] - x[ramt].fillna(0.0)})
        d = pd.concat([
            detail(F["catalog_sales"], F["catalog_returns"],
                   ["cs_order_number", "cs_item_sk"],
                   ["cr_order_number", "cr_item_sk"], "cs_item_sk",
                   "cs_sold_date_sk", "cs_quantity", "cs_ext_sales_price",
                   "cr_return_quantity", "cr_return_amount"),
            detail(F["store_sales"], F["store_returns"],
                   ["ss_ticket_number", "ss_item_sk"],
                   ["sr_ticket_number", "sr_item_sk"], "ss_item_sk",
                   "ss_sold_date_sk", "ss_quantity", "ss_ext_sales_price",
                   "sr_return_quantity", "sr_return_amt"),
            detail(F["web_sales"], F["web_returns"],
                   ["ws_order_number", "ws_item_sk"],
                   ["wr_order_number", "wr_item_sk"], "ws_item_sk",
                   "ws_sold_date_sk", "ws_quantity", "ws_ext_sales_price",
                   "wr_return_quantity", "wr_return_amt"),
        ]).drop_duplicates()  # UNION (not ALL)
        g = d.groupby(["d_year", "i_brand_id", "i_class_id", "i_category_id",
                       "i_manufact_id"], as_index=False).agg(
            sales_cnt=("sales_cnt", "sum"), sales_amt=("sales_amt", "sum"))
        cur = g[g.d_year == 2000]
        prv = g[g.d_year == 1999]
        m = cur.merge(prv, on=["i_brand_id", "i_class_id", "i_category_id",
                               "i_manufact_id"], suffixes=("_c", "_p"))
        m = m[m.sales_cnt_c / m.sales_cnt_p < 0.9]
        out = pd.DataFrame({
            "prev_year": m.d_year_p, "year": m.d_year_c,
            "i_brand_id": m.i_brand_id, "i_class_id": m.i_class_id,
            "i_category_id": m.i_category_id,
            "i_manufact_id": m.i_manufact_id,
            "prev_yr_cnt": m.sales_cnt_p, "curr_yr_cnt": m.sales_cnt_c,
            "sales_cnt_diff": m.sales_cnt_c - m.sales_cnt_p,
            "sales_amt_diff": m.sales_amt_c - m.sales_amt_p})
        return out.sort_values(["sales_cnt_diff", "sales_amt_diff"]).head(100)
    run(env, "q75", oracle, limit=None)


def test_q78(env):
    def oracle(F):
        def chan(sales, returns, skeys, rkeys, rnull, dcol, ycol, icol, ccol,
                 qty, wc, sp, pref):
            x = (sales.merge(returns[list(rkeys)], left_on=list(skeys),
                             right_on=list(rkeys), how="left")
                 .merge(F["date_dim"], left_on=dcol, right_on="d_date_sk"))
            x = x[x[rnull].isna()]
            g = x.groupby(["d_year", icol, ccol], as_index=False).agg(
                qty=(qty, "sum"), wc=(wc, "sum"), sp=(sp, "sum"))
            return g.rename(columns={
                "d_year": f"{pref}_sold_year", icol: f"{pref}_item_sk",
                ccol: f"{pref}_customer_sk", "qty": f"{pref}_qty",
                "wc": f"{pref}_wc", "sp": f"{pref}_sp"})
        ws = chan(F["web_sales"], F["web_returns"],
                  ("ws_order_number", "ws_item_sk"),
                  ("wr_order_number", "wr_item_sk"), "wr_order_number",
                  "ws_sold_date_sk", "d_year", "ws_item_sk",
                  "ws_bill_customer_sk", "ws_quantity", "ws_wholesale_cost",
                  "ws_sales_price", "ws")
        cs = chan(F["catalog_sales"], F["catalog_returns"],
                  ("cs_order_number", "cs_item_sk"),
                  ("cr_order_number", "cr_item_sk"), "cr_order_number",
                  "cs_sold_date_sk", "d_year", "cs_item_sk",
                  "cs_bill_customer_sk", "cs_quantity", "cs_wholesale_cost",
                  "cs_sales_price", "cs")
        ss = chan(F["store_sales"], F["store_returns"],
                  ("ss_ticket_number", "ss_item_sk"),
                  ("sr_ticket_number", "sr_item_sk"), "sr_ticket_number",
                  "ss_sold_date_sk", "d_year", "ss_item_sk",
                  "ss_customer_sk", "ss_quantity", "ss_wholesale_cost",
                  "ss_sales_price", "ss")
        m = (ss.merge(ws, left_on=["ss_sold_year", "ss_item_sk",
                                   "ss_customer_sk"],
                      right_on=["ws_sold_year", "ws_item_sk",
                                "ws_customer_sk"], how="left")
             .merge(cs, left_on=["ss_sold_year", "ss_item_sk",
                                 "ss_customer_sk"],
                    right_on=["cs_sold_year", "cs_item_sk",
                              "cs_customer_sk"], how="left"))
        m = m[(m.ws_qty.fillna(0) > 0) | (m.cs_qty.fillna(0) > 0)]
        m = m[m.ss_sold_year == 2000]
        out = pd.DataFrame({
            "customer": m.ss_customer_sk, "item": m.ss_item_sk,
            "ss_qty": m.ss_qty,
            "ratio": m.ss_qty / (m.ws_qty.fillna(0) + m.cs_qty.fillna(0)),
            "other_chan_qty": (m.ws_qty.fillna(0)
                               + m.cs_qty.fillna(0)).astype(int),
            "other_chan_wholesale": m.ws_wc.fillna(0) + m.cs_wc.fillna(0),
            "other_chan_sales_price": m.ws_sp.fillna(0) + m.cs_sp.fillna(0)})
        return out.sort_values(["customer", "item"]).head(100)
    run(env, "q78", oracle, limit=None)


def test_q8(env):
    def oracle(F):
        ca = F["customer_address"]; c = F["customer"]
        lit = {"AL", "IL", "MI", "TN", "CA", "NY"}
        m = c.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        cnt = m[m.c_preferred_cust_flag == "Y"].groupby("ca_state").size()
        good = (set(ca.ca_state.unique()) & lit
                & set(cnt[cnt > 40].index))
        dd = F["date_dim"]
        x = (F["store_sales"]
             .merge(dd[(dd.d_qoy == 2) & (dd.d_year == 1999)],
                    left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["store"][F["store"].s_state.isin(good)],
                    left_on="ss_store_sk", right_on="s_store_sk"))
        return (x.groupby("s_store_name", as_index=False)["ss_net_profit"]
                .sum().sort_values("s_store_name"))
    run(env, "q8", oracle, limit=None)


def test_q49(env):
    def oracle(F):
        def chan(name, sales, returns, skeys, rkeys, dcol, qty, rqty, amt,
                 ramt, profit):
            x = (sales.merge(returns, left_on=list(skeys),
                             right_on=list(rkeys), how="left")
                 .merge(F["date_dim"], left_on=dcol, right_on="d_date_sk"))
            x = x[(x[ramt] > 100) & (x[profit] > 1) & (x[amt] > 0)
                  & (x[qty] > 0) & (x.d_year == 2000)]
            g = x.groupby(skeys[1] if "item" in skeys[1] else skeys[1],
                          as_index=False).agg(
                rq=(rqty, lambda s: s.fillna(0).sum()),
                q=(qty, "sum"),
                ra=(ramt, lambda s: s.fillna(0).sum()),
                a=(amt, "sum"))
            g["return_ratio"] = g.rq / g.q
            g["currency_ratio"] = g.ra / g.a
            g["return_rank"] = g.return_ratio.rank(method="min").astype(int)
            g["currency_rank"] = g.currency_ratio.rank(
                method="min").astype(int)
            g = g[(g.return_rank <= 10) | (g.currency_rank <= 10)]
            out = pd.DataFrame({
                "channel": name, "item": g.iloc[:, 0],
                "return_ratio": g.return_ratio,
                "return_rank": g.return_rank,
                "currency_rank": g.currency_rank})
            return out
        w = chan("web", F["web_sales"], F["web_returns"],
                 ("ws_order_number", "ws_item_sk"),
                 ("wr_order_number", "wr_item_sk"), "ws_sold_date_sk",
                 "ws_quantity", "wr_return_quantity", "ws_net_paid",
                 "wr_return_amt", "ws_net_profit")
        c = chan("catalog", F["catalog_sales"], F["catalog_returns"],
                 ("cs_order_number", "cs_item_sk"),
                 ("cr_order_number", "cr_item_sk"), "cs_sold_date_sk",
                 "cs_quantity", "cr_return_quantity", "cs_ext_sales_price",
                 "cr_return_amount", "cs_net_profit")
        s = chan("store", F["store_sales"], F["store_returns"],
                 ("ss_ticket_number", "ss_item_sk"),
                 ("sr_ticket_number", "sr_item_sk"), "ss_sold_date_sk",
                 "ss_quantity", "sr_return_quantity", "ss_net_paid",
                 "sr_return_amt", "ss_net_profit")
        return pd.concat([w, c, s]).drop_duplicates()
    run(env, "q49", oracle, limit=None)


def test_q54(env):
    def oracle(F):
        it = F["item"]
        it = it[(it.i_category == "Music") & (it.i_class == "class01")]
        dd = F["date_dim"]
        sel = dd[(dd.d_moy == 3) & (dd.d_year == 2000)]
        u = pd.concat([
            F["catalog_sales"][["cs_sold_date_sk", "cs_bill_customer_sk",
                                "cs_item_sk"]].rename(columns={
                "cs_sold_date_sk": "sold_date_sk",
                "cs_bill_customer_sk": "customer_sk",
                "cs_item_sk": "item_sk"}),
            F["web_sales"][["ws_sold_date_sk", "ws_bill_customer_sk",
                            "ws_item_sk"]].rename(columns={
                "ws_sold_date_sk": "sold_date_sk",
                "ws_bill_customer_sk": "customer_sk",
                "ws_item_sk": "item_sk"})])
        mc = (u.merge(sel, left_on="sold_date_sk", right_on="d_date_sk")
              .merge(it, left_on="item_sk", right_on="i_item_sk")
              .merge(F["customer"], left_on="customer_sk",
                     right_on="c_customer_sk"))
        mc = mc[["c_customer_sk", "c_current_addr_sk"]].drop_duplicates()
        ms = int(sel.d_month_seq.iloc[0])
        dr = dd[(dd.d_month_seq >= ms + 1) & (dd.d_month_seq <= ms + 3)]
        rev = (mc.merge(F["store_sales"], left_on="c_customer_sk",
                        right_on="ss_customer_sk")
               .merge(F["customer_address"], left_on="c_current_addr_sk",
                      right_on="ca_address_sk")
               .merge(F["store"], left_on=["ca_county", "ca_state"],
                      right_on=["s_county", "s_state"])
               .merge(dr, left_on="ss_sold_date_sk", right_on="d_date_sk"))
        g = rev.groupby("c_customer_sk")["ss_ext_sales_price"].sum()
        seg = (g / 50).astype(int)
        out = seg.value_counts().rename_axis("segment").reset_index(
            name="num_customers")
        out["segment_base"] = out.segment * 50
        return out.sort_values(["segment", "num_customers"])
    run(env, "q54", oracle, limit=None)


def test_q56(env):
    def oracle(F):
        it = F["item"]
        ids = it[it.i_color.isin(["blue", "khaki", "plum"])].i_item_id
        itx = it[it.i_item_id.isin(set(ids))]
        dd = F["date_dim"]
        dd = dd[(dd.d_year == 2000) & (dd.d_moy == 2)]
        ca = F["customer_address"]
        ca = ca[ca.ca_gmt_offset == -5]
        def chan(fact, icol, dcol, acol, amt):
            x = (F[fact].merge(itx, left_on=icol, right_on="i_item_sk")
                 .merge(dd, left_on=dcol, right_on="d_date_sk")
                 .merge(ca, left_on=acol, right_on="ca_address_sk"))
            return x.groupby("i_item_id", as_index=False)[amt].sum().rename(
                columns={amt: "total_sales"})
        u = pd.concat([
            chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
                 "ss_addr_sk", "ss_ext_sales_price"),
            chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                 "cs_bill_addr_sk", "cs_ext_sales_price"),
            chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
                 "ws_bill_addr_sk", "ws_ext_sales_price")])
        g = u.groupby("i_item_id", as_index=False).total_sales.sum()
        return g.sort_values(["total_sales", "i_item_id"]).head(100)
    run(env, "q56", oracle, limit=None)


def test_q57(env):
    def oracle(F):
        dd = F["date_dim"]
        x = (F["catalog_sales"]
             .merge(F["item"], left_on="cs_item_sk", right_on="i_item_sk")
             .merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
             .merge(F["call_center"], left_on="cs_call_center_sk",
                    right_on="cc_call_center_sk"))
        x = x[(x.d_year == 1999) | ((x.d_year == 1998) & (x.d_moy == 12))
              | ((x.d_year == 2000) & (x.d_moy == 1))]
        v1 = x.groupby(["i_category", "i_brand", "cc_name", "d_year",
                        "d_moy"], as_index=False)["cs_sales_price"].sum(
            ).rename(columns={"cs_sales_price": "sum_sales"})
        v1["avg_monthly_sales"] = v1.groupby(
            ["i_category", "i_brand", "cc_name", "d_year"]
        ).sum_sales.transform("mean")
        v1 = v1.sort_values(["d_year", "d_moy"])
        v1["rn"] = v1.groupby(["i_category", "i_brand", "cc_name"]
                              ).cumcount() + 1
        lag = v1.copy(); lag["rn"] = lag.rn + 1
        lead = v1.copy(); lead["rn"] = lead.rn - 1
        m = (v1.merge(lag, on=["i_category", "i_brand", "cc_name", "rn"],
                      suffixes=("", "_lag"))
             .merge(lead, on=["i_category", "i_brand", "cc_name", "rn"],
                    suffixes=("", "_lead")))
        m = m[(m.d_year == 1999) & (m.avg_monthly_sales > 0)]
        m = m[abs(m.sum_sales - m.avg_monthly_sales)
              / m.avg_monthly_sales > 0.1]
        return pd.DataFrame({
            "i_category": m.i_category, "i_brand": m.i_brand,
            "cc_name": m.cc_name, "d_year": m.d_year, "d_moy": m.d_moy,
            "avg_monthly_sales": m.avg_monthly_sales,
            "sum_sales": m.sum_sales, "psum": m.sum_sales_lag,
            "nsum": m.sum_sales_lead})
    run(env, "q57", oracle, limit=None)


def test_q14(env):
    def oracle(F):
        dd = F["date_dim"]
        win = dd[(dd.d_year >= 1999) & (dd.d_year <= 2001)]
        it = F["item"]
        def bcc(fact, icol, dcol):
            x = (F[fact].merge(it, left_on=icol, right_on="i_item_sk")
                 .merge(win, left_on=dcol, right_on="d_date_sk"))
            return set(map(tuple, x[["i_brand_id", "i_class_id",
                                     "i_category_id"]].values))
        common = (bcc("store_sales", "ss_item_sk", "ss_sold_date_sk")
                  & bcc("catalog_sales", "cs_item_sk", "cs_sold_date_sk")
                  & bcc("web_sales", "ws_item_sk", "ws_sold_date_sk"))
        cross = set(it[[tuple(r) in common for r in
                        it[["i_brand_id", "i_class_id", "i_category_id"]
                           ].values]].i_item_sk)
        vals = []
        for fact, icol, dcol, q, lp in (
                ("store_sales", "ss_item_sk", "ss_sold_date_sk",
                 "ss_quantity", "ss_list_price"),
                ("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                 "cs_quantity", "cs_list_price"),
                ("web_sales", "ws_item_sk", "ws_sold_date_sk",
                 "ws_quantity", "ws_list_price")):
            x = F[fact].merge(win, left_on=dcol, right_on="d_date_sk")
            vals.append(x[q] * x[lp])
        avg_sales = pd.concat(vals).mean()
        sel = dd[(dd.d_year == 2001) & (dd.d_moy == 11)]
        frames = []
        for name, fact, icol, dcol, q, lp in (
                ("store", "store_sales", "ss_item_sk", "ss_sold_date_sk",
                 "ss_quantity", "ss_list_price"),
                ("catalog", "catalog_sales", "cs_item_sk",
                 "cs_sold_date_sk", "cs_quantity", "cs_list_price"),
                ("web", "web_sales", "ws_item_sk", "ws_sold_date_sk",
                 "ws_quantity", "ws_list_price")):
            x = (F[fact][F[fact][icol].isin(cross)]
                 .merge(it, left_on=icol, right_on="i_item_sk")
                 .merge(sel, left_on=dcol, right_on="d_date_sk"))
            x = x.assign(v=x[q] * x[lp])
            g = x.groupby(["i_brand_id", "i_class_id", "i_category_id"],
                          as_index=False).agg(sales=("v", "sum"),
                                              number_sales=("v", "size"))
            g = g[g.sales > avg_sales]
            g["channel"] = name
            frames.append(g)
        detail = pd.concat(frames)
        out = rollup_levels(
            detail, ["channel", "i_brand_id", "i_class_id", "i_category_id"],
            lambda sub: {"sales": sub.sales.sum(),
                         "number_sales": sub.number_sales.sum()})
        return out[["channel", "i_brand_id", "i_class_id", "i_category_id",
                    "sales", "number_sales"]]
    run(env, "q14", oracle, limit=None)


def test_q23(env):
    def oracle(F):
        dd = F["date_dim"]
        win = dd[dd.d_year.isin([1999, 2000])]
        freq = (F["store_sales"]
                .merge(win, left_on="ss_sold_date_sk", right_on="d_date_sk")
                .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
                .groupby("i_item_sk").size())
        freq_items = set(freq[freq > 4].index)
        spend = (F["store_sales"]
                 .merge(F["customer"], left_on="ss_customer_sk",
                        right_on="c_customer_sk")
                 .merge(win, left_on="ss_sold_date_sk", right_on="d_date_sk"))
        spend = spend.assign(v=spend.ss_quantity * spend.ss_sales_price)
        csales = spend.groupby("c_customer_sk").v.sum()
        cmax = csales.max()
        all_spend = F["store_sales"].merge(
            F["customer"], left_on="ss_customer_sk",
            right_on="c_customer_sk")
        all_spend = all_spend.assign(
            v=all_spend.ss_quantity * all_spend.ss_sales_price)
        best = all_spend.groupby("c_customer_sk").v.sum()
        best_customers = set(best[best > 0.5 * cmax].index)
        sel = dd[(dd.d_year == 2000) & (dd.d_moy == 3)]
        total = 0.0
        for fact, dcol, icol, ccol, q, lp in (
                ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                 "cs_bill_customer_sk", "cs_quantity", "cs_list_price"),
                ("web_sales", "ws_sold_date_sk", "ws_item_sk",
                 "ws_bill_customer_sk", "ws_quantity", "ws_list_price")):
            x = F[fact].merge(sel, left_on=dcol, right_on="d_date_sk")
            x = x[x[icol].isin(freq_items) & x[ccol].isin(best_customers)]
            total += (x[q] * x[lp]).sum()
        return pd.DataFrame([{"total_sales": total}])
    run(env, "q23", oracle, limit=None)


def test_q24(env):
    def oracle(F):
        st = F["store"]
        st = st[(st.s_number_employees >= 200)
                & (st.s_number_employees <= 290)]
        x = (F["store_sales"]
             .merge(F["store_returns"],
                    left_on=["ss_ticket_number", "ss_item_sk"],
                    right_on=["sr_ticket_number", "sr_item_sk"])
             .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
             .merge(F["item"], left_on="ss_item_sk", right_on="i_item_sk")
             .merge(F["customer"], left_on="ss_customer_sk",
                    right_on="c_customer_sk")
             .merge(F["customer_address"], left_on="c_current_addr_sk",
                    right_on="ca_address_sk"))
        x = x[x.s_state == x.ca_state]
        ssales = x.groupby(["c_last_name", "c_first_name", "s_store_name",
                            "i_color"], as_index=False)["ss_net_paid"].sum(
            ).rename(columns={"ss_net_paid": "netpaid"})
        thresh = 0.05 * ssales.netpaid.mean()
        pink = ssales[ssales.i_color == "pink"]
        g = pink.groupby(["c_last_name", "c_first_name", "s_store_name"],
                         as_index=False).netpaid.sum()
        return g[g.netpaid > thresh].rename(columns={"netpaid": "paid"})
    run(env, "q24", oracle, limit=None)


def test_q64(env):
    def oracle(F):
        cr = F["catalog_returns"]
        m = F["catalog_sales"].merge(
            cr, left_on=["cs_item_sk", "cs_order_number"],
            right_on=["cr_item_sk", "cr_order_number"])
        m = m.assign(refund=m.cr_refunded_cash + m.cr_net_loss)
        g = m.groupby("cs_item_sk", as_index=False).agg(
            sale=("cs_ext_list_price", "sum"), refund=("refund", "sum"))
        cs_ui = set(g[g.sale > 2 * g.refund].cs_item_sk)
        it = F["item"]
        it = it[it.i_color.isin(["green", "red", "blue", "pink", "white",
                                 "black"])
                & (it.i_current_price >= 1) & (it.i_current_price <= 100)]
        x = (F["store_sales"]
             .merge(F["store_returns"],
                    left_on=["ss_item_sk", "ss_ticket_number"],
                    right_on=["sr_item_sk", "sr_ticket_number"])
             .merge(F["date_dim"], left_on="ss_sold_date_sk",
                    right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk")
             .merge(it, left_on="ss_item_sk", right_on="i_item_sk"))
        x = x[x.ss_item_sk.isin(cs_ui)]
        cs = x.groupby(["i_product_name", "i_item_sk", "s_store_name",
                        "d_year"], as_index=False).agg(
            cnt=("ss_item_sk", "size"), s1=("ss_wholesale_cost", "sum"),
            s2=("ss_list_price", "sum"), s3=("ss_coupon_amt", "sum"))
        a = cs[cs.d_year == 1999]
        b = cs[cs.d_year == 2000]
        m2 = a.merge(b, on=["i_item_sk", "s_store_name"],
                     suffixes=("_1", "_2"))
        m2 = m2[m2.cnt_2 <= m2.cnt_1]
        return pd.DataFrame({
            "product_name": m2.i_product_name_1, "store_name": m2.s_store_name,
            "year1": m2.d_year_1, "year2": m2.d_year_2,
            "cnt1": m2.cnt_1, "cnt2": m2.cnt_2,
            "s11": m2.s1_1, "s21": m2.s2_1, "s31": m2.s3_1,
            "s12": m2.s1_2, "s22": m2.s2_2, "s32": m2.s3_2})
    run(env, "q64", oracle, limit=None)


def test_q70(env):
    def oracle(F):
        dd = F["date_dim"]
        dd = dd[(dd.d_month_seq >= 24) & (dd.d_month_seq <= 35)]
        x = (F["store_sales"]
             .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
             .merge(F["store"], left_on="ss_store_sk", right_on="s_store_sk"))
        per_state = x.groupby("s_state").ss_net_profit.sum()
        ranked = per_state.rank()  # rank within partition of itself == 1
        good = set(per_state.index)  # ranking <= 5 always true per-state
        x = x[x.s_state.isin(good)]
        out = rollup_levels(
            x, ["s_state", "s_county"],
            lambda sub: {"total_sum": sub.ss_net_profit.sum()})
        out["lochierarchy"] = out.s_state.isna().astype(int) \
            + out.s_county.isna().astype(int)
        return out[["total_sum", "s_state", "s_county", "lochierarchy"]]
    run(env, "q70", oracle, limit=None)


def test_q72(env):
    def oracle(F):
        cd = F["customer_demographics"]
        cd = cd[cd.cd_marital_status == "D"]
        hd = F["household_demographics"]
        hd = hd[hd.hd_buy_potential == ">10000"]
        dd = F["date_dim"]
        x = (F["catalog_sales"]
             .merge(F["inventory"], left_on="cs_item_sk",
                    right_on="inv_item_sk")
             .merge(F["warehouse"], left_on="inv_warehouse_sk",
                    right_on="w_warehouse_sk")
             .merge(F["item"], left_on="cs_item_sk", right_on="i_item_sk")
             .merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
             .merge(hd, left_on="cs_bill_hdemo_sk", right_on="hd_demo_sk")
             .merge(dd.add_suffix("_1"), left_on="cs_sold_date_sk",
                    right_on="d_date_sk_1")
             .merge(dd.add_suffix("_2"), left_on="inv_date_sk",
                    right_on="d_date_sk_2")
             .merge(dd.add_suffix("_3"), left_on="cs_ship_date_sk",
                    right_on="d_date_sk_3")
             .merge(F["promotion"], left_on="cs_promo_sk",
                    right_on="p_promo_sk", how="left")
             .merge(F["catalog_returns"],
                    left_on=["cs_item_sk", "cs_order_number"],
                    right_on=["cr_item_sk", "cr_order_number"], how="left"))
        x = x[(x.d_week_seq_1 == x.d_week_seq_2)
              & (x.inv_quantity_on_hand < x.cs_quantity)
              & (x.d_date_sk_3 > x.d_date_sk_1 + 5)
              & (x.d_year_1 == 1999)]
        g = x.groupby(["i_item_desc", "w_warehouse_name", "d_week_seq_1"],
                      as_index=False).agg(
            no_promo=("p_promo_sk", lambda s: int(s.isna().sum())),
            promo=("p_promo_sk", lambda s: int(s.notna().sum())),
            total_cnt=("p_promo_sk", "size"))
        g = g.sort_values(
            ["total_cnt", "i_item_desc", "w_warehouse_name", "d_week_seq_1"],
            ascending=[False, True, True, True]).head(100)
        return g
    run(env, "q72", oracle, limit=None)


def test_q83(env):
    def oracle(F):
        dd = F["date_dim"]
        dates = pd.to_datetime(["2000-06-30", "2000-09-27", "2000-11-17"])
        weeks = set(dd[dd.d_date.isin(dates)].d_week_seq)
        sks = set(dd[dd.d_week_seq.isin(weeks)].d_date_sk)
        def items(fact, icol, dcol, qty):
            x = F[fact][F[fact][dcol].isin(sks)].merge(
                F["item"], left_on=icol, right_on="i_item_sk")
            return x.groupby("i_item_id")[qty].sum()
        sr = items("store_returns", "sr_item_sk", "sr_returned_date_sk",
                   "sr_return_quantity")
        cr = items("catalog_returns", "cr_item_sk", "cr_returned_date_sk",
                   "cr_return_quantity")
        wr = items("web_returns", "wr_item_sk", "wr_returned_date_sk",
                   "wr_return_quantity")
        ids = set(sr.index) & set(cr.index) & set(wr.index)
        rows = []
        for i in sorted(ids):
            s, c, w = sr[i], cr[i], wr[i]
            tot = s + c + w
            rows.append((i, s, s / tot / 3.0 * 100, c, c / tot / 3.0 * 100,
                         w, w / tot / 3.0 * 100, tot / 3.0))
        return pd.DataFrame(rows, columns=[
            "item_id", "sr_item_qty", "sr_dev", "cr_item_qty", "cr_dev",
            "wr_item_qty", "wr_dev", "average"])
    run(env, "q83", oracle, limit=None)


def test_q95(env):
    def oracle(F):
        ws = F["web_sales"]
        multi = (ws.groupby("ws_order_number").ws_warehouse_sk.nunique())
        ws_wh = set(multi[multi > 1].index)
        dd = F["date_dim"]
        dd = dd[(dd.d_date >= pd.Timestamp("2000-02-01"))
                & (dd.d_date <= pd.Timestamp("2000-04-01"))]
        ca = F["customer_address"]
        wsite = F["web_site"]
        x = (ws.merge(dd, left_on="ws_ship_date_sk", right_on="d_date_sk")
             .merge(ca[ca.ca_state == "IL"], left_on="ws_bill_addr_sk",
                    right_on="ca_address_sk")
             .merge(wsite[wsite.web_company_name == "pri0"],
                    left_on="ws_web_site_sk", right_on="web_site_sk"))
        returned = set(F["web_returns"].wr_order_number) & ws_wh
        x = x[x.ws_order_number.isin(ws_wh)
              & x.ws_order_number.isin(returned)]
        return pd.DataFrame([{
            "order_count": x.ws_order_number.nunique(),
            "total_shipping_cost": x.ws_ext_list_price.sum(),
            "total_net_profit": x.ws_net_profit.sum()}])
    run(env, "q95", oracle, limit=None)
