"""Resource groups + admission control (runtime/workgroup.py).

Reference behavior modeled: be/src/compute_env/workgroup/work_group.h:145
(group limits, big-query caps) + fe qe/scheduler/slot/SlotManager.java
(slot queueing and timeouts). pandas-free: the assertions are about
admission behavior, not results.
"""

import threading
import time

import pytest

from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.runtime.workgroup import AdmissionError


def _mk():
    s = Session()
    s.sql("create table wt (a int, b int)")
    s.sql("insert into wt values (1, 10), (2, 20), (3, 30), (4, 40)")
    return s


def test_create_show_drop_and_set():
    s = _mk()
    s.sql("create resource group rg1 with (concurrency_limit = 2, "
          "max_scan_rows = 1000, cpu_weight = 5)")
    rows = s.sql("show resource groups")
    assert rows == [("rg1", 2, 1000, 0, 5, 0, 0, 0)]
    # information_schema surface
    r = s.sql("select name, concurrency_limit, max_scan_rows from "
              "information_schema.resource_groups").rows()
    assert r == [("rg1", 2, 1000)]
    with pytest.raises(ValueError, match="already exists"):
        s.sql("create resource group rg1")
    s.sql("create or replace resource group rg1 with (concurrency_limit = 3)")
    assert s.sql("show resource groups")[0][1] == 3
    with pytest.raises(ValueError, match="unknown resource group"):
        s.sql("set resource_group = 'nope'")
    s.sql("set resource_group = 'rg1'")
    assert s.resource_group == "rg1"
    s.sql("drop resource group rg1")
    assert s.sql("show resource groups") == []
    with pytest.raises(ValueError, match="unknown"):
        s.sql("drop resource group rg1")
    s.sql("drop resource group if exists rg1")
    with pytest.raises(ValueError, match="unknown resource group propert"):
        s.sql("create resource group rg2 with (bogus_prop = 1)")


def test_big_query_limits_reject():
    s = _mk()
    s.sql("create resource group tiny with (max_scan_rows = 2)")
    s.sql("set resource_group = 'tiny'")
    with pytest.raises(AdmissionError, match="big-query limit"):
        s.sql("select sum(a) from wt")
    # DDL/small statements unaffected; clearing the group unthrottles
    s.sql("set resource_group = ''")
    assert s.sql("select count(*) from wt").rows() == [(4,)]
    s.sql("create resource group thin with (mem_limit_bytes = 8)")
    s.sql("set resource_group = 'thin'")
    with pytest.raises(AdmissionError, match="memory limit"):
        s.sql("select sum(b) from wt")


def test_concurrency_slots_throttle_and_release():
    """One slot in rg_slow: a long-running query (python UDF holds the
    device callback) blocks a same-group query into the admission queue
    until timeout, while a session in ANOTHER group proceeds — the
    quota-limited group throttles, the other does not."""
    s = _mk()
    s.sql("""create function napping(a bigint) returns bigint as '
import time
def napping(a):
    time.sleep(0.6)
    return a
'""")
    s.sql("create resource group rg_slow with (concurrency_limit = 1)")
    s.sql("create resource group rg_free with (concurrency_limit = 4)")

    holder = Session(s.catalog)
    holder.sql("set resource_group = 'rg_slow'")
    blocked = Session(s.catalog)
    blocked.sql("set resource_group = 'rg_slow'")
    free = Session(s.catalog)
    free.sql("set resource_group = 'rg_free'")

    config.set("query_queue_timeout_s", 0.15)
    errors, done = [], []

    def run_holder():
        done.append(holder.sql("select max(napping(a)) from wt").rows())

    t = threading.Thread(target=run_holder)
    t.start()
    time.sleep(0.25)  # holder is inside its 0.6s sleep, slot taken
    try:
        with pytest.raises(AdmissionError, match="queue timeout"):
            blocked.sql("select count(*) from wt")
        # a different group is not throttled by rg_slow's slot
        assert free.sql("select count(*) from wt").rows() == [(4,)]
    finally:
        t.join()
    assert done and len(done[0]) == 1
    # slot released: the blocked session now passes admission
    config.set("query_queue_timeout_s", 5.0)
    assert blocked.sql("select count(*) from wt").rows() == [(4,)]
    wm = s.workgroups()
    assert wm.timeout_total >= 1
    assert wm.running.get("rg_slow", 0) == 0
    config.set("query_queue_timeout_s", 10.0)
    s.sql("drop function napping")


def test_resource_groups_survive_restart(tmp_path):
    d = str(tmp_path / "db")
    s = Session(data_dir=d)
    s.sql("create resource group keepme with (concurrency_limit = 7, "
          "max_scan_rows = 123)")
    s.sql("create resource group dropme")
    s.sql("drop resource group dropme")
    s.checkpoint_metadata()
    s.sql("create resource group tailrg with (cpu_weight = 9)")
    s2 = Session(data_dir=d)
    got = {r[0]: r for r in s2.sql("show resource groups")}
    assert got["keepme"][1] == 7 and got["keepme"][2] == 123
    assert got["tailrg"][4] == 9
    assert "dropme" not in got


def test_non_admin_cannot_manage_groups():
    s = _mk()
    s.sql("create user peasant identified by 'x'")
    s.sql("grant select on wt to peasant")
    s.current_user = "peasant"
    with pytest.raises(PermissionError):
        s.sql("create resource group sneaky")
    s.current_user = "root"
