"""Global runtime-filter framework tests: bloom-bitset probe filters,
min/max edge semantics, cross-shard merges, and two-phase scan pruning
(reference: be/src/exec_primitive/runtime_filter/ + the global merge in
orchestration/runtime_filter_worker.h)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from starrocks_tpu import types as T
from starrocks_tpu.column import HostTable
from starrocks_tpu.column.column import Chunk, Field, Schema
from starrocks_tpu.exprs.ir import Col
from starrocks_tpu.ops.join import bloom_filter_mask, runtime_filter_mask
from starrocks_tpu.parallel.mesh import make_mesh, shard_map
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.sql.physical import LUT_JOIN_MAX_RANGE, bloom_rf_bits
from starrocks_tpu.storage.catalog import Catalog


def _key_chunk(keys, valid=None):
    keys = jnp.asarray(np.asarray(keys, dtype=np.int64))
    v = None if valid is None else jnp.asarray(np.asarray(valid, dtype=bool))
    return Chunk(
        Schema((Field("k", T.BIGINT, valid is not None),)),
        (keys,), (v,), None,
    )


@pytest.fixture()
def rf_strategy_reset():
    old = config.get("runtime_filter_strategy")
    yield
    config.set("runtime_filter_strategy", old)


# --- min/max edge semantics --------------------------------------------------


def test_minmax_rf_all_null_build_is_all_false():
    """All-NULL build side: bmin (I64MAX) > bmax (I64MIN), so the probe
    mask is ALL-FALSE — the intended INNER/LEFT-SEMI semantics (an empty
    build key set matches nothing). A refactor flipping the inverted range
    into an all-true mask would break the probe compaction that trusts the
    mask to be a SUBSET of true matches."""
    probe = _key_chunk(np.arange(16))
    build = _key_chunk(np.arange(4), valid=np.zeros(4, dtype=bool))
    mask = runtime_filter_mask(probe, build, (Col("k"),), (Col("k"),))
    assert not bool(jnp.any(mask))


def test_minmax_rf_dead_build_rows_excluded_from_bounds():
    """Dead (unselected) build rows must not widen the min/max bounds."""
    build = Chunk(
        Schema((Field("k", T.BIGINT, False),)),
        (jnp.asarray(np.array([50, 60, 999999], dtype=np.int64)),),
        (None,),
        jnp.asarray(np.array([True, True, False])),
    )
    probe = _key_chunk(np.array([40, 50, 60, 70, 999999]))
    mask = np.asarray(runtime_filter_mask(
        probe, build, (Col("k"),), (Col("k"),)))
    assert mask.tolist() == [False, True, True, False, False]


# --- bloom property: never a false negative ----------------------------------


@pytest.mark.parametrize("n_build,n_probe,key_range,bits", [
    (100, 5000, 1 << 16, 4096),
    (500, 8000, 1 << 40, 8192),
    (2000, 4000, 1 << 62, 1 << 15),
    (50, 1000, 1000, 4096),  # dense narrow range
])
def test_bloom_rf_never_false_negative(n_build, n_probe, key_range, bits):
    rng = np.random.default_rng(n_build + n_probe)
    build_keys = rng.choice(key_range, size=n_build, replace=False)
    probe_keys = rng.integers(0, key_range, size=n_probe)
    # guarantee real matches exist
    probe_keys[:: max(n_probe // n_build, 1)] = rng.choice(
        build_keys, size=len(probe_keys[:: max(n_probe // n_build, 1)]))
    mask = np.asarray(bloom_filter_mask(
        _key_chunk(probe_keys), _key_chunk(build_keys),
        (Col("k"),), (Col("k"),), bits=bits,
    ))
    in_build = np.isin(probe_keys, build_keys)
    # every probe row with a matching build key MUST survive
    assert bool(np.all(mask[in_build])), "bloom RF false-negatived a match"
    # and the filter actually filters: most non-matching rows drop
    non_match = int((~in_build).sum())
    if non_match > 100:
        kept = int((mask & ~in_build).sum())
        assert kept < non_match * 0.5, (kept, non_match)


def test_bloom_rf_null_probe_keys_drop():
    probe = _key_chunk(np.array([1, 2, 3, 4]),
                       valid=np.array([True, False, True, False]))
    build = _key_chunk(np.array([1, 2, 3, 4]))
    mask = np.asarray(bloom_filter_mask(
        probe, build, (Col("k"),), (Col("k"),), bits=4096))
    assert mask.tolist() == [True, False, True, False]


def test_bloom_rf_bits_sizing():
    bits, exactish = bloom_rf_bits(1000.0, 1 << 23)
    assert bits >= 8 * 1000 and bits & (bits - 1) == 0 and exactish
    # capped sizing is no longer near-exact
    bits, exactish = bloom_rf_bits(100_000.0, 1 << 17)
    assert bits == 1 << 17 and not exactish
    # hopeless (<1 bit/key under the cap): no filter at all
    assert bloom_rf_bits(1e9, 1 << 20) is None


# --- cross-shard merge (the global-RF collective) ----------------------------


def test_bloom_rf_cross_shard_pmax_keeps_remote_matches(eight_devices):
    """Sharded build: each shard holds a DIFFERENT key subset. The bitsets
    must OR-merge across shards (pmax) so a probe row whose match lives on
    a remote shard still survives on every shard."""
    mesh = make_mesh(8)
    rng = np.random.default_rng(7)
    per_shard = 32
    build_keys = rng.choice(1 << 40, size=8 * per_shard, replace=False)
    probe_keys = np.concatenate(
        [build_keys, rng.integers(0, 1 << 40, size=512)])

    def step(bk_local, pk_all):
        build = Chunk(Schema((Field("k", T.BIGINT, False),)), (bk_local,),
                      (None,), None)
        probe = Chunk(Schema((Field("k", T.BIGINT, False),)), (pk_all,),
                      (None,), None)
        return bloom_filter_mask(probe, build, (Col("k"),), (Col("k"),),
                                 axis="d", bits=8192)

    fn = jax.jit(shard_map(step, mesh=mesh,
                           in_specs=(P("d"), P()), out_specs=P("d")))
    mask = np.asarray(fn(
        jnp.asarray(build_keys), jnp.asarray(probe_keys)
    )).reshape(8, len(probe_keys))
    # EVERY shard keeps EVERY matching probe row, including rows whose
    # build key lives on a different shard
    assert bool(mask[:, : len(build_keys)].all()), (
        "cross-shard pmax merge lost a remote-shard match")
    # identical merged bitset on every shard -> identical masks
    assert bool((mask == mask[0]).all())


def test_minmax_rf_cross_shard_pmin_pmax(eight_devices):
    """Sharded build bounds merge via pmin/pmax: the global range covers
    every shard's keys even though each shard sees a narrow local range."""
    mesh = make_mesh(8)
    build_keys = np.arange(8 * 16, dtype=np.int64) * 1000  # 0..127000
    probe_keys = np.array([0, 500, 127000, 127001, -5], dtype=np.int64)

    def step(bk_local, pk_all):
        build = Chunk(Schema((Field("k", T.BIGINT, False),)), (bk_local,),
                      (None,), None)
        probe = Chunk(Schema((Field("k", T.BIGINT, False),)), (pk_all,),
                      (None,), None)
        return runtime_filter_mask(probe, build, (Col("k"),), (Col("k"),),
                                   axis="d")

    fn = jax.jit(shard_map(step, mesh=mesh,
                           in_specs=(P("d"), P()), out_specs=P("d")))
    mask = np.asarray(fn(
        jnp.asarray(build_keys), jnp.asarray(probe_keys)
    )).reshape(8, len(probe_keys))
    assert mask[0].tolist() == [True, True, True, False, False]
    assert bool((mask == mask[0]).all())


# --- SQL level: bloom engages where the dense range cannot -------------------


def _wide_key_catalog(n_fact=20_000, n_dim=200, seed=0):
    """Join keys sparse over a 2^40 range — far past LUT_JOIN_MAX_RANGE and
    DENSE_RF_MAX_RANGE, so the dense-bitmap/LUT paths cannot engage and
    before this round the probe only got the weak min/max filter."""
    rng = np.random.default_rng(seed)
    keys = rng.choice(1 << 40, size=n_fact, replace=False).astype(np.int64)
    assert int(keys.max() - keys.min()) > LUT_JOIN_MAX_RANGE
    dim_keys = rng.choice(keys, size=n_dim, replace=False)
    cat = Catalog()
    cat.register("fact", HostTable.from_pydict({
        "k": keys, "v": np.arange(n_fact, dtype=np.int64)}))
    cat.register("dim", HostTable.from_pydict({
        "k": dim_keys.astype(np.int64),
        "w": np.ones(n_dim, dtype=np.int64)}), unique_keys=[("k",)])
    return cat


def test_bloom_rf_sql_wide_keys_prunes_and_matches_off(rf_strategy_reset):
    q = ("SELECT sum(f.v) AS sv, count(*) AS c "
         "FROM fact f JOIN dim d ON f.k = d.k")
    s = Session(_wide_key_catalog())
    s.sql("SET runtime_filter_strategy='bloom'")
    r_bloom = s.sql(q).rows()
    ctrs = {k: v for k, (v, _) in s.last_profile.counters.items()}
    assert ctrs.get("rf_rows_pruned", 0) > 0, ctrs
    assert ctrs.get("rf_bloom_bits", 0) > 0, ctrs
    s.sql("SET runtime_filter_strategy='off'")
    r_off = s.sql(q).rows()
    assert "rf_rows_pruned" not in s.last_profile.counters
    assert r_bloom == r_off
    # auto also picks bloom here (dense range unavailable) and agrees
    s.sql("SET runtime_filter_strategy='auto'")
    assert s.sql(q).rows() == r_off
    assert s.last_profile.counters["rf_rows_pruned"][0] > 0


def test_bloom_rf_pushdown_below_probe_filter_chain(rf_strategy_reset):
    """A probe-side WHERE leaves an LFilter chain over the scan; the RF
    mask applies at the chain BOTTOM (pushdown) and results still match
    strategy='off' exactly."""
    q = ("SELECT sum(f.v) AS sv, count(*) AS c "
         "FROM fact f JOIN dim d ON f.k = d.k WHERE f.v % 3 = 0")
    s = Session(_wide_key_catalog(seed=3))
    s.sql("SET runtime_filter_strategy='bloom'")
    r_bloom = s.sql(q).rows()
    assert s.last_profile.counters["rf_rows_pruned"][0] > 0
    s.sql("SET runtime_filter_strategy='off'")
    assert s.sql(q).rows() == r_bloom


def test_minmax_strategy_still_correct(rf_strategy_reset):
    q = ("SELECT sum(f.v) AS sv, count(*) AS c "
         "FROM fact f JOIN dim d ON f.k = d.k")
    s = Session(_wide_key_catalog(seed=5))
    s.sql("SET runtime_filter_strategy='minmax'")
    r_mm = s.sql(q).rows()
    s.sql("SET runtime_filter_strategy='off'")
    assert s.sql(q).rows() == r_mm


# --- two-phase scan-level pruning --------------------------------------------


def test_scan_rf_prunes_segments_and_matches_off(tmp_path, rf_strategy_reset):
    """Multi-segment stored probe + selective dimension build: build key
    bounds evaluated on host numpy prune probe parquet files via their
    zonemaps (rf_segments_pruned > 0) with unchanged query results."""
    s = Session(data_dir=str(tmp_path))
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT)")
    s.sql("CREATE TABLE dim (k BIGINT, attr VARCHAR)")
    # 4 rowsets with disjoint key ranges -> 4 parquet files with zonemaps
    for base in (0, 1000, 2000, 3000):
        vals = ", ".join(f"({base + i}, {i})" for i in range(100))
        s.sql(f"INSERT INTO fact VALUES {vals}")
    s.sql("INSERT INTO dim VALUES (2005, 'x'), (2010, 'x'), (500, 'y')")
    q = ("SELECT sum(f.v) AS sv, count(*) AS c "
         "FROM fact f JOIN dim d ON f.k = d.k WHERE d.attr = 'x'")
    r_auto = s.sql(q).rows()
    ctrs = {k: v for k, (v, _) in s.last_profile.counters.items()}
    assert ctrs.get("rf_segments_pruned", 0) > 0, ctrs
    s.sql("SET runtime_filter_strategy='off'")
    r_off = s.sql(q).rows()
    assert "rf_segments_pruned" not in s.last_profile.counters
    assert r_auto == r_off == [(15, 2)]


def test_scan_rf_empty_build_prunes_everything(tmp_path, rf_strategy_reset):
    """A build-side filter matching NOTHING yields the empty-build sentinel
    bounds: every probe segment prunes and the join returns no rows."""
    s = Session(data_dir=str(tmp_path))
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT)")
    s.sql("CREATE TABLE dim (k BIGINT, attr VARCHAR)")
    for base in (0, 1000):
        vals = ", ".join(f"({base + i}, {i})" for i in range(50))
        s.sql(f"INSERT INTO fact VALUES {vals}")
    s.sql("INSERT INTO dim VALUES (10, 'y')")
    q = ("SELECT count(*) AS c FROM fact f JOIN dim d ON f.k = d.k "
         "WHERE d.attr = 'nope'")
    assert s.sql(q).rows() == [(0,)]
    ctrs = {k: v for k, (v, _) in s.last_profile.counters.items()}
    assert ctrs.get("rf_segments_pruned", 0) == 2, ctrs


def test_scan_rf_respects_dml_invalidation(tmp_path, rf_strategy_reset):
    """Growing the dimension AFTER a pruned run must widen the bounds on
    the next run — stale pruned snapshots would silently drop rows."""
    s = Session(data_dir=str(tmp_path))
    s.sql("CREATE TABLE fact (k BIGINT, v BIGINT)")
    s.sql("CREATE TABLE dim (k BIGINT, attr VARCHAR)")
    for base in (0, 1000, 2000):
        vals = ", ".join(f"({base + i}, {i})" for i in range(50))
        s.sql(f"INSERT INTO fact VALUES {vals}")
    s.sql("INSERT INTO dim VALUES (2005, 'x')")
    q = ("SELECT count(*) AS c FROM fact f JOIN dim d ON f.k = d.k "
         "WHERE d.attr = 'x'")
    assert s.sql(q).rows() == [(1,)]
    s.sql("INSERT INTO dim VALUES (5, 'x')")  # key in a previously-pruned file
    assert s.sql(q).rows() == [(2,)]
