"""Interprocedural effect-checker tests (ISSUE 18).

Golden BAD fixtures prove each of the four contracts rejects what it
exists to reject — an acquire a raise can leak, a blocking loop a KILL
cannot land in, expensive work under a lockdep lock, a thread without a
daemon flag or a stop — and twin GOOD fixtures prove the recognized safe
shapes (with-items, assign-then-try-finally, the gate form, arm/disarm
pairing, transitive checkpoints, thread-target loops) pass clean. Each
suppression annotation is exercised with and without a reason (a bare
tag is the `--strict-warn` ratchet's warn). Then the real package:
`starrocks_tpu/` must be strict-clean — zero errors AND zero warns —
under the same gate tools/concur_lint.py runs ahead of pytest.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from starrocks_tpu.analysis import effects_check

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(rep, severity=None):
    return [f.rule for f in rep.findings
            if severity in (None, f.severity)]


def _one(rep, rule):
    hits = [f for f in rep.findings if f.rule == rule]
    assert len(hits) == 1, f"expected one {rule}, got {rep.findings}"
    return hits[0]


# === contract 1: exception-safe acquire =======================================

C1_BAD_LOCK = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def grab(self):
        self._lock.acquire()
        do_work()
        self._lock.release()
'''


def test_unprotected_lock_acquire_rejected():
    f = _one(effects_check.check_fixture(C1_BAD_LOCK),
             "unprotected-acquire")
    assert f.severity == "error"
    assert f.where == "starrocks_tpu/fixture.py:9"  # the acquire site
    assert "lock" in f.message and "try-finally" in f.message


C1_BAD_SOCKET = '''
import http.client

class Beater:
    def beat(self):
        conn = http.client.HTTPConnection("coord", 80)
        conn.request("GET", "/")   # an OSError here leaks the socket
        conn.close()
'''

C1_GOOD_SOCKET = '''
import http.client

class Beater:
    def beat(self):
        conn = http.client.HTTPConnection("coord", 80)
        try:
            conn.request("GET", "/")
        finally:
            conn.close()
'''


def test_socket_constructor_is_an_acquire():
    f = _one(effects_check.check_fixture(C1_BAD_SOCKET),
             "unprotected-acquire")
    assert "socket" in f.message
    assert _rules(effects_check.check_fixture(C1_GOOD_SOCKET)) == []


C1_BAD_SLOT = '''
class M:
    def admit(self, g):
        return lambda: None

    def admission(self, g):
        release = self.admit(g)
        register(release)     # a raise HERE leaks the slot
        try:
            return release
        finally:
            release()
'''

C1_GOOD_SLOT = '''
class M:
    def admit(self, g):
        return lambda: None

    def admission(self, g):
        release = self.admit(g)
        try:
            register(release)
            return release
        finally:
            release()
'''

C1_GOOD_GATE = '''
class T:
    def try_shared(self, tabs):
        return True

    def fast(self, gate, tabs):
        if not gate.try_shared(tabs):
            return None
        try:
            return run()
        finally:
            gate.release_shared(tabs)
'''


def test_slot_acquire_needs_immediate_try_finally():
    f = _one(effects_check.check_fixture(C1_BAD_SLOT),
             "unprotected-acquire")
    assert "slot" in f.message
    assert _rules(effects_check.check_fixture(C1_GOOD_SLOT)) == []
    # the gate form: `if not gate.try_shared(): return MISS` + try-finally
    assert _rules(effects_check.check_fixture(C1_GOOD_GATE)) == []


C1_BAD_ARM = '''
from starrocks_tpu.runtime import failpoint

def inject(name):
    failpoint.arm(name)   # armed forever: no disarm on any path
    run()
'''

C1_GOOD_ARM = '''
from starrocks_tpu.runtime import failpoint

def inject(name):
    failpoint.arm(name)
    try:
        run()
    finally:
        failpoint.disarm(name)
'''


def test_failpoint_arm_must_pair_with_disarm():
    f = _one(effects_check.check_fixture(C1_BAD_ARM),
             "unprotected-acquire")
    assert "disarm" in f.message
    assert _rules(effects_check.check_fixture(C1_GOOD_ARM)) == []


def test_with_item_open_is_protected():
    rep = effects_check.check_fixture('''
def read(p):
    with open(p) as f:
        return f.read()
''')
    assert _rules(rep) == []


# === contract 2: checkpoint density ==========================================

C2_BAD = '''
import time

class Pool:
    def drain(self):
        while pending():
            time.sleep(0.05)
'''

C2_GOOD_DIRECT = '''
import time

class Pool:
    def drain(self, ctx):
        while pending():
            ctx.checkpoint("drain")
            time.sleep(0.05)
'''

C2_GOOD_TRANSITIVE = '''
import time

class Pool:
    def _step(self, ctx):
        ctx.checkpoint("step")
        time.sleep(0.05)

    def drain(self, ctx):
        while pending():
            self._step(ctx)
'''

C2_GOOD_THREAD_TARGET = '''
import threading
import time

class Sampler:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            time.sleep(0.05)

    def stop(self):
        pass
'''


def test_checkpoint_free_blocking_loop_rejected():
    f = _one(effects_check.check_fixture(C2_BAD),
             "checkpoint-free-blocking-loop")
    assert f.severity == "error"
    assert f.where == "starrocks_tpu/fixture.py:6"  # the loop
    assert "sleep" in f.message and "checkpoint" in f.message


def test_checkpointed_loops_pass_direct_and_transitive():
    assert _rules(effects_check.check_fixture(C2_GOOD_DIRECT)) == []
    assert _rules(effects_check.check_fixture(C2_GOOD_TRANSITIVE)) == []


def test_thread_target_loops_exempt():
    # a daemon service loop is not query context: no checkpoint needed
    assert _rules(effects_check.check_fixture(C2_GOOD_THREAD_TARGET)) == []


# === contract 3: no blocking under lock ======================================

C3_BAD = '''
from starrocks_tpu import lockdep

class Cache:
    def __init__(self):
        self._lock = lockdep.lock("Cache._lock")

    def build(self, fn, x):
        with self._lock:
            return fn.lower(x).compile()
'''

C3_GOOD = '''
from starrocks_tpu import lockdep

class Cache:
    def __init__(self):
        self._lock = lockdep.lock("Cache._lock")

    def build(self, fn, x):
        comp = fn.lower(x).compile()   # expensive work OUTSIDE the lock
        with self._lock:
            self._slot = comp
        return comp
'''

C3_BAD_TRANSITIVE = '''
import time
from starrocks_tpu import lockdep

class Store:
    def __init__(self):
        self._lock = lockdep.lock("Store._lock")

    def _settle(self):
        time.sleep(0.1)

    def mutate(self):
        with self._lock:
            self._settle()
'''

C3_GOOD_WAIT = '''
from starrocks_tpu import lockdep

class Q:
    def __init__(self):
        self._lock = lockdep.condition("Q._lock")

    def pop(self):
        with self._lock:
            while not self._items:
                self._lock.wait(timeout=0.5)
'''


def test_compile_under_lock_rejected():
    f = _one(effects_check.check_fixture(C3_BAD), "blocking-under-lock")
    assert f.severity == "error"
    assert f.where == "starrocks_tpu/fixture.py:10"  # the blocking site
    assert "compile" in f.message and "Cache._lock" in f.message
    assert _rules(effects_check.check_fixture(C3_GOOD)) == []


def test_blocking_under_lock_found_through_calls():
    f = _one(effects_check.check_fixture(C3_BAD_TRANSITIVE),
             "blocking-under-lock")
    assert "sleep" in f.message and "_settle" in f.message


def test_condition_wait_under_its_lock_allowed():
    # Condition.wait RELEASES the lock while parked: the standard
    # wait-loop is not a blocking-under-lock violation (C2 still applies
    # to loops, but this loop blocks only on "wait")
    rep = effects_check.check_fixture(C3_GOOD_WAIT)
    assert "blocking-under-lock" not in _rules(rep)


# === contract 4: daemon-thread lifecycle =====================================

C4_BAD = '''
import threading

class Svc:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
'''

C4_GOOD = '''
import threading

class Svc:
    def ensure_started(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        pass

    def stop(self):
        self._t.join(timeout=2)
'''


def test_non_daemon_thread_and_missing_stop_rejected():
    rep = effects_check.check_fixture(C4_BAD)
    rules = _rules(rep, "error")
    assert "non-daemon-thread" in rules and "thread-without-stop" in rules
    assert all(f.where == "starrocks_tpu/fixture.py:6"
               for f in rep.findings)
    assert _rules(effects_check.check_fixture(C4_GOOD)) == []


# === contract 4b: subprocess lifecycle =======================================

C4_PROC_BAD = '''
import subprocess

class Fleet:
    def spawn(self):
        self._p = subprocess.Popen(["sleep", "60"])
'''

C4_PROC_GOOD = '''
import subprocess

class Fleet:
    def spawn(self):
        self._p = subprocess.Popen(["sleep", "60"])

    def stop(self):
        self._p.terminate()
        self._p.wait(timeout=5)
'''


def test_popen_without_owner_stop_rejected():
    rep = effects_check.check_fixture(C4_PROC_BAD)
    assert _one(rep, "proc-without-stop").severity == "error"
    assert _rules(effects_check.check_fixture(C4_PROC_GOOD)) == []
    assert effects_check.check_fixture(C4_PROC_GOOD).stats["procs"] == 1


def test_popen_counts_as_proc_acquire_site():
    from starrocks_tpu.analysis import astwalk

    sites = effects_check.acquire_sites(
        [astwalk.parse_fixture(C4_PROC_GOOD, "starrocks_tpu/fixture.py")])
    procs = [s for s in sites if s.kind == "proc"]
    assert len(procs) == 1 and procs[0].func.endswith(".spawn")
    assert procs[0].line == 6
    # ownership (stop/terminate on the owner class) is the guard for a
    # child process, not a with-block — no unprotected-acquire finding
    assert "unprotected-acquire" not in _rules(
        effects_check.check_fixture(C4_PROC_GOOD))


# === suppression annotations =================================================

def test_blocking_ok_with_reason_suppresses_and_counts():
    rep = effects_check.check_fixture(C3_BAD.replace(
        "return fn.lower(x).compile()",
        "return fn.lower(x).compile()  "
        "# lint: blocking-ok — warm-path recompile is bounded and rare"))
    assert _rules(rep) == []
    assert rep.stats["suppressions"] == 1
    assert rep.stats["suppressions_unexplained"] == 0


def test_blocking_ok_without_reason_warns():
    rep = effects_check.check_fixture(C3_BAD.replace(
        "return fn.lower(x).compile()",
        "return fn.lower(x).compile()  # lint: blocking-ok"))
    assert _rules(rep, "error") == []          # still suppresses...
    assert _rules(rep, "warn") == ["suppression-missing-reason"]
    assert rep.stats["suppressions_unexplained"] == 1


def test_checkpoint_exempt_with_reason_suppresses():
    rep = effects_check.check_fixture(C2_BAD.replace(
        "while pending():",
        "while pending():  # lint: checkpoint-exempt — reaper loop IS "
        "the enforcement"))
    assert _rules(rep) == []
    assert rep.stats["suppressions"] == 1


def test_checkpoint_exempt_without_reason_warns():
    rep = effects_check.check_fixture(C2_BAD.replace(
        "while pending():",
        "while pending():  # lint: checkpoint-exempt"))
    assert _rules(rep, "error") == []
    assert _rules(rep, "warn") == ["suppression-missing-reason"]


# === the real package ========================================================

def test_package_effects_strict_clean():
    """The gate tools/concur_lint.py --strict-warn runs: zero errors AND
    zero warns — every reviewed exception carries a reason."""
    rep = effects_check.check_package()
    errors = [f for f in rep.findings if f.severity == "error"]
    warns = [f for f in rep.findings if f.severity == "warn"]
    assert errors == [], "\n".join(str(f) for f in errors)
    assert warns == [], "\n".join(str(f) for f in warns)
    assert rep.stats["suppressions_unexplained"] == 0
    # the census is real: the runtime DOES carry reviewed exceptions
    assert rep.stats["suppressions"] >= 5
    assert rep.stats["acquire_sites"] >= 20
    assert rep.stats["threads"] >= 5


def test_acquire_sites_enumeration_for_chaos_cross_check():
    from starrocks_tpu.analysis import astwalk

    sites = effects_check.acquire_sites(astwalk.package_sources())
    kinds = {s.kind for s in sites}
    # the kinds chaos_fuzz cross-checks against failpoint coverage (no
    # raw "lock" sites: every package lock acquire is a `with` — which
    # is the contract)
    assert {"file", "slot", "failpoint", "socket"} <= kinds
    assert any(s.rel.endswith("runtime/workgroup.py") and s.kind == "slot"
               for s in sites)
    assert all(s.line > 0 and s.func and s.module for s in sites)


def test_concur_lint_json_is_machine_readable():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "concur_lint.py"),
         "--json", "--strict-warn"],
        capture_output=True, text=True, check=False)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] is True and payload["errors"] == 0
    assert payload["suppressions_unexplained"] == 0
    assert payload["stats"]["effects"]["functions"] > 1000
    assert isinstance(payload["findings"], list)


def test_manifest_pins_effects_check_to_analysis_only():
    """Satellite: the analyzer must stay loadable without jax — its
    module_rule allows only the shared walk and the resolver it reuses."""
    with open(os.path.join(REPO, "module_boundary_manifest.json")) as f:
        manifest = json.load(f)
    rule = manifest["module_rules"]["analysis/effects_check.py"]
    assert set(rule["allow"]) == {"analysis.astwalk",
                                  "analysis.concur_check"}
    assert rule.get("external", []) == []
