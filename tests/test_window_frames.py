"""Explicit window frame (ROWS/RANGE BETWEEN) tests vs python oracles.

Reference behavior: be/src/exec/analytor.h:54 — frame-based analytic
evaluation with ROWS/RANGE offsets clamped to partition bounds."""

import math

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.sql.parser import ParseError, parse


@pytest.fixture(scope="module")
def sess():
    s = Session()
    rng = np.random.default_rng(42)
    n = 400
    g = np.sort(rng.integers(0, 8, n))
    df = pd.DataFrame({"g": g})
    # unique, non-contiguous order key per partition (deterministic ROWS)
    df["k"] = df.groupby("g").cumcount() * 3 + rng.integers(0, 3, n)
    v = np.round(rng.normal(50, 20, n), 2)
    nulls = rng.random(n) < 0.12
    s.sql("create table wf (g int, k int, v double)")
    rows = ", ".join(
        f"({a}, {b}, {'null' if nu else c})"
        for a, b, c, nu in zip(df.g, df.k, v, nulls))
    s.sql(f"insert into wf values {rows}")
    s._df = pd.DataFrame(
        {"g": df.g, "k": df.k, "v": np.where(nulls, np.nan, v)}
    ).sort_values(["g", "k"]).reset_index(drop=True)
    return s


def oracle(df, fn, mode, s, e):
    """Row-by-row frame evaluation per partition (df sorted by g, k)."""
    out = []
    for _, grp in df.groupby("g", sort=True):
        vals = grp["v"].to_numpy()
        keys = grp["k"].to_numpy()
        n = len(grp)
        for i in range(n):
            if mode == "rows":
                lo = {"up": 0, "p": i - (s[1] or 0), "cr": i,
                      "f": i + (s[1] or 0)}[s[0]]
                hi = {"uf": n - 1, "p": i - (e[1] or 0), "cr": i,
                      "f": i + (e[1] or 0)}[e[0]]
            else:  # range over k (ints, no ties by construction)
                lo = {"up": 0, "cr": i}.get(s[0])
                hi = {"uf": n - 1, "cr": i}.get(e[0])
                if lo is None:
                    t = keys[i] + (-s[1] if s[0] == "p" else s[1])
                    lo = int(np.searchsorted(keys, t, side="left"))
                if hi is None:
                    t = keys[i] + (-e[1] if e[0] == "p" else e[1])
                    hi = int(np.searchsorted(keys, t, side="right")) - 1
            lo, hi = max(lo, 0), min(hi, n - 1)
            w = vals[lo:hi + 1] if lo <= hi else np.array([])
            wv = w[~np.isnan(w)]
            if fn == "count":
                out.append(len(wv))
            elif len(wv) == 0:
                out.append(np.nan)
            elif fn == "sum":
                out.append(wv.sum())
            elif fn == "avg":
                out.append(wv.mean())
            elif fn == "min":
                out.append(wv.min())
            elif fn == "max":
                out.append(wv.max())
            elif fn == "first_value":
                out.append(w[0] if len(w) else np.nan)
            elif fn == "last_value":
                out.append(w[-1] if len(w) else np.nan)
    return np.array(out, dtype=float)


def run(sess, frame_sql, fns=("sum", "avg", "min", "max", "count")):
    cols = ", ".join(
        f"{fn}(v) over (partition by g order by k {frame_sql}) c{i}"
        for i, fn in enumerate(fns))
    r = sess.sql(f"select g, k, {cols} from wf order by g, k")
    return pd.DataFrame(
        r.rows(), columns=["g", "k"] + [f"c{i}" for i in range(len(fns))])


def check(sess, mode, s, e, frame_sql,
          fns=("sum", "avg", "min", "max", "count")):
    got = run(sess, frame_sql, fns)
    for i, fn in enumerate(fns):
        exp = oracle(sess._df, fn, mode, s, e)
        g = got[f"c{i}"].astype(float).to_numpy()
        np.testing.assert_allclose(g, exp, rtol=1e-9, atol=1e-9,
                                   err_msg=f"{fn} {frame_sql}")


def test_rows_preceding_current(sess):
    check(sess, "rows", ("p", 2), ("cr", None),
          "rows between 2 preceding and current row")


def test_rows_single_bound_shorthand(sess):
    check(sess, "rows", ("p", 3), ("cr", None), "rows 3 preceding")


def test_rows_mixed_bounds(sess):
    check(sess, "rows", ("p", 1), ("f", 2),
          "rows between 1 preceding and 2 following")


def test_rows_unbounded_to_following(sess):
    check(sess, "rows", ("up", None), ("f", 1),
          "rows between unbounded preceding and 1 following")


def test_rows_current_to_unbounded(sess):
    check(sess, "rows", ("cr", None), ("uf", None),
          "rows between current row and unbounded following")


def test_rows_empty_frames(sess):
    check(sess, "rows", ("f", 3), ("f", 5),
          "rows between 3 following and 5 following")
    check(sess, "rows", ("p", 5), ("p", 3),
          "rows between 5 preceding and 3 preceding")


def test_range_offsets(sess):
    check(sess, "range", ("p", 5), ("f", 5),
          "range between 5 preceding and 5 following")
    check(sess, "range", ("p", 7), ("cr", None),
          "range between 7 preceding and current row")


def test_range_unbounded_combo(sess):
    check(sess, "range", ("up", None), ("f", 4),
          "range between unbounded preceding and 4 following")


def test_first_last_value_frames(sess):
    got = run(sess, "rows between 1 preceding and 1 following",
              fns=("first_value", "last_value"))
    for i, fn in enumerate(("first_value", "last_value")):
        exp = oracle(sess._df, fn, "rows", ("p", 1), ("f", 1))
        g = got[f"c{i}"].astype(float).to_numpy()
        both_nan = np.isnan(g) & np.isnan(exp)
        np.testing.assert_allclose(
            np.where(both_nan, 0, g), np.where(both_nan, 0, exp),
            rtol=1e-9, err_msg=fn)


def test_desc_order_rows_frame(sess):
    r = sess.sql("""select g, k,
        sum(v) over (partition by g order by k desc
                     rows between 2 preceding and current row) s
        from wf order by g, k""")
    got = pd.DataFrame(r.rows(), columns=["g", "k", "s"])
    # oracle: reverse each partition, rolling(3), reverse back
    exp = []
    for _, grp in sess._df.groupby("g", sort=True):
        vals = grp["v"].to_numpy()[::-1]
        roll = pd.Series(vals).rolling(3, min_periods=1).sum().to_numpy()[::-1]
        exp.extend(roll)
    exp = np.array(exp)
    g = got["s"].astype(float).to_numpy()
    both_nan = np.isnan(g) & np.isnan(exp)
    np.testing.assert_allclose(np.where(both_nan, 0, g),
                               np.where(both_nan, 0, exp), rtol=1e-9)


def test_desc_order_range_frame(sess):
    r = sess.sql("""select g, k,
        sum(v) over (partition by g order by k desc
                     range between 6 preceding and current row) s
        from wf order by g, k""")
    got = pd.DataFrame(r.rows(), columns=["g", "k", "s"])
    exp = []
    for _, grp in sess._df.groupby("g", sort=True):
        vals = grp["v"].to_numpy()
        keys = grp["k"].to_numpy()
        for i in range(len(grp)):
            # DESC: "6 preceding" = keys in [k_i, k_i + 6]
            m = (keys >= keys[i]) & (keys <= keys[i] + 6)
            w = vals[m]
            w = w[~np.isnan(w)]
            exp.append(w.sum() if len(w) else np.nan)
    exp = np.array(exp)
    g = got["s"].astype(float).to_numpy()
    both_nan = np.isnan(g) & np.isnan(exp)
    np.testing.assert_allclose(np.where(both_nan, 0, g),
                               np.where(both_nan, 0, exp), rtol=1e-9)


def test_running_sum_matches_explicit_default(sess):
    """The explicit default frame must agree with the implicit one."""
    a = sess.sql("""select sum(v) over (partition by g order by k) s
                    from wf order by g, k""").rows()
    b = sess.sql("""select sum(v) over (partition by g order by k
        range between unbounded preceding and current row) s
        from wf order by g, k""").rows()
    ga = np.array([r[0] for r in a], dtype=float)
    gb = np.array([r[0] for r in b], dtype=float)
    both_nan = np.isnan(ga) & np.isnan(gb)
    np.testing.assert_allclose(np.where(both_nan, 0, ga),
                               np.where(both_nan, 0, gb), rtol=1e-12)


def test_range_frame_decimal_key():
    """RANGE offsets are user-unit even though DECIMAL keys are scaled ints."""
    s = Session()
    s.sql("create table wd (g int, k decimal(10, 2), v double)")
    ks = [1.00, 1.25, 1.50, 3.00, 3.10, 9.99]
    vs = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0]
    s.sql("insert into wd values " + ", ".join(
        f"(1, {k}, {v})" for k, v in zip(ks, vs)))
    r = s.sql("""select k, sum(v) over (order by k
        range between 0.5 preceding and current row) s
        from wd order by k""")
    got = [row[1] for row in r.rows()]
    exp = []
    for i, k in enumerate(ks):
        exp.append(sum(v for kk, v in zip(ks, vs) if k - 0.5 <= kk <= k))
    np.testing.assert_allclose(got, exp, rtol=1e-9)


def test_frame_parse_errors():
    with pytest.raises(ParseError):
        parse("select sum(v) over (order by k rows between -1 preceding "
              "and current row) from t")
    with pytest.raises(ParseError):
        parse("select sum(v) over (order by k rows 1.5 preceding) from t")
    with pytest.raises(ParseError):
        parse("select sum(v) over (partition by g rows 2 preceding) from t")
    with pytest.raises(ParseError):
        parse("select sum(v) over (order by k rows between current row "
              "and 2 preceding) from t")
    with pytest.raises(ParseError):
        parse("select sum(v) over (order by k rows between unbounded "
              "following and current row) from t")
    with pytest.raises(ParseError):
        parse("select rank() over (order by k rows 2 preceding) from t")
    with pytest.raises(ParseError):
        parse("select sum(v) over (order by k, g range between 2 preceding "
              "and current row) from t")
