"""ARRAY type + DECIMAL128 differential tests.

Layouts under test (re-design of be/src/column/array_column.h offsets+values
and be/src/types/logical_type.h DECIMAL128): arrays as [cap, K+1] wide
columns (length prefix), decimal128 as [cap, 4] 32-bit limb matrices.
"""

import random

import pytest

from starrocks_tpu import types as T
from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.sql("CREATE TABLE t (id BIGINT, nums ARRAY<BIGINT>, "
          "tags ARRAY<VARCHAR>, txt VARCHAR)")
    s.sql("INSERT INTO t VALUES "
          "(1, array(3, 1, 2, 1), array('x', 'y'), 'a,b,c'),"
          "(2, array(9), array('z'), 'solo'),"
          "(3, array(5, 5), array('y', 'x', 'y'), 'p,,q')")
    return s


def test_array_functions(sess):
    r = sess.sql("SELECT array_length(nums), element_at(nums, 2), "
                 "array_contains(nums, 1), array_position(tags, 'y') "
                 "FROM t ORDER BY id").rows()
    assert r == [(4, 1, True, 2), (1, None, False, 0), (2, 5, False, 1)]
    r = sess.sql("SELECT array_sum(nums), array_avg(nums), array_min(nums),"
                 " array_max(nums) FROM t ORDER BY id").rows()
    assert r == [(7, 1.75, 1, 3), (9, 9.0, 9, 9), (10, 5.0, 5, 5)]
    r = sess.sql("SELECT array_sort(nums), array_distinct(nums), "
                 "array_sort(tags) FROM t ORDER BY id").rows()
    assert r[0] == ([1, 1, 2, 3], [1, 2, 3], ["x", "y"])
    assert r[2] == ([5, 5], [5], ["x", "y", "y"])
    r = sess.sql("SELECT split(txt, ',') FROM t ORDER BY id").rows()
    assert r == [(["a", "b", "c"],), (["solo"],), (["p", "", "q"],)]


def test_unnest(sess):
    r = sess.sql("SELECT id, x FROM t, unnest(nums) u(x) "
                 "ORDER BY id, x").rows()
    assert r == [(1, 1), (1, 1), (1, 2), (1, 3), (2, 9), (3, 5), (3, 5)]
    r = sess.sql("SELECT tag, count(*) c FROM t, unnest(tags) u(tag) "
                 "GROUP BY tag ORDER BY tag").rows()
    assert r == [("x", 2), ("y", 3), ("z", 1)]
    # filter above unnest on the element
    r = sess.sql("SELECT sum(x) FROM t, unnest(nums) u(x) WHERE x > 2").rows()
    assert r == [(3 + 9 + 5 + 5,)]


def test_array_agg_roundtrip(sess):
    r = sess.sql("SELECT id, array_sort(array_agg(x)) FROM t, "
                 "unnest(nums) u(x) GROUP BY id ORDER BY id").rows()
    assert r == [(1, [1, 1, 2, 3]), (2, [9]), (3, [5, 5])]


def test_array_agg_capacity_overflow():
    """Groups larger than the default 256-element array capacity must
    trigger the adaptive recompile, not truncate."""
    s = Session()
    s.sql("CREATE TABLE big (g BIGINT, v BIGINT)")
    rows = ", ".join(f"({i % 2}, {i})" for i in range(700))
    s.sql(f"INSERT INTO big VALUES {rows}")
    r = s.sql("SELECT g, array_length(array_agg(v)) FROM big "
              "GROUP BY g ORDER BY g").rows()
    assert r == [(0, 350), (1, 350)]


def test_array_storage_roundtrip(tmp_path):
    s = Session(data_dir=str(tmp_path))
    s.sql("CREATE TABLE at (id BIGINT, a ARRAY<INT>, s ARRAY<VARCHAR>)")
    s.sql("INSERT INTO at VALUES (1, array(1, 2), array('p', 'q')),"
          "(2, array(7), array('r'))")
    s2 = Session(data_dir=str(tmp_path))  # parquet + manifest replay
    r = s2.sql("SELECT id, a, s FROM at ORDER BY id").rows()
    assert r == [(1, [1, 2], ["p", "q"]), (2, [7], ["r"])]
    r = s2.sql("SELECT id, x FROM at, unnest(s) u(x) ORDER BY id, x").rows()
    assert r == [(1, "p"), (1, "q"), (2, "r")]


def test_decimal128_exact_aggregation():
    random.seed(7)
    vals = [random.randint(-10**30, 10**30) for _ in range(1000)]
    gs = [i % 4 for i in range(1000)]
    cat = Catalog()
    cat.register("d", HostTable.from_pydict(
        {"g": gs, "v": vals}, types={"v": T.DECIMAL(38, 0)}))
    s = Session(cat)
    r = s.sql("SELECT g, sum(v), count(v) FROM d GROUP BY g ORDER BY g").rows()
    for g, sd, c in r:
        exp = sum(v for v, gg in zip(vals, gs) if gg == g)
        assert int(sd) == exp  # exact 128-bit sums vs python ints
        assert c == 250
    # global aggregation too
    r = s.sql("SELECT sum(v) FROM d").rows()
    assert int(r[0][0]) == sum(vals)


def test_decimal128_scale_and_storage(tmp_path):
    s = Session(data_dir=str(tmp_path))
    s.sql("CREATE TABLE m (id BIGINT, amt DECIMAL(38, 4))")
    s.sql("INSERT INTO m VALUES (1, 123456789012345678901234.5678),"
          "(2, -0.0001), (3, 99)")
    s2 = Session(data_dir=str(tmp_path))
    import decimal

    r = s2.sql("SELECT id, amt FROM m ORDER BY id").rows()
    assert r[0][1] == decimal.Decimal("123456789012345678901234.5678")
    assert r[1][1] == decimal.Decimal("-0.0001")
    assert r[2][1] == decimal.Decimal("99")
    r = s2.sql("SELECT sum(amt) FROM m").rows()
    assert r[0][0] == decimal.Decimal("123456789012345678901233.5677") + \
        decimal.Decimal("99") + decimal.Decimal("1")


def test_review_regressions():
    import decimal

    s = Session()
    # NULL array rows with empty dictionaries must concat cleanly
    s.sql("CREATE TABLE n (a ARRAY<VARCHAR>)")
    s.sql("INSERT INTO n VALUES (NULL)")
    s.sql("INSERT INTO n VALUES (array('k'))")
    assert s.sql("SELECT a FROM n").rows() == [(None,), (["k"],)]
    # array() promotes mixed numerics and merges string dictionaries
    s.sql("CREATE TABLE p (x BIGINT, s1 VARCHAR, s2 VARCHAR)")
    s.sql("INSERT INTO p VALUES (1, 'aa', 'bb')")
    r = s.sql("SELECT array(x, 2.5) m, array(s1, s2, 'cc') st FROM p").rows()
    assert r == [([1.0, 2.5], ["aa", "bb", "cc"])]
    # half-even rounding matches the narrow-decimal path
    s.sql("CREATE TABLE rr (d DECIMAL(38, 2))")
    s.sql("INSERT INTO rr VALUES (1.006)")
    assert s.sql("SELECT d FROM rr").rows() == [(decimal.Decimal("1.01"),)]
    # round 4: dec128 min/max and comparisons are now real operations
    assert s.sql("SELECT min(d), max(d) FROM rr").rows() == [
        (decimal.Decimal("1.01"), decimal.Decimal("1.01"))]
    assert s.sql("SELECT count(*) FROM rr WHERE d > 1").rows() == [(1,)]
    assert s.sql("SELECT count(*) FROM rr WHERE d > 2").rows() == [(0,)]


def test_dec128_storage_precision(tmp_path):
    """38-digit values survive the parquet flush bit-exactly (regression:
    default decimal context rounded to 28 digits at _to_arrow)."""
    import decimal

    s = Session(data_dir=str(tmp_path))
    s.sql("CREATE TABLE w (d DECIMAL(38, 2))")
    s.sql("INSERT INTO w VALUES (123456789012345678901234567890123456.78)")
    s2 = Session(data_dir=str(tmp_path))
    assert s2.sql("SELECT d FROM w").rows() == [
        (decimal.Decimal("123456789012345678901234567890123456.78"),)]
