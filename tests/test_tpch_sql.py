"""SQL-level differential tests: all 22 TPC-H queries vs pandas oracles.

Reference analog: the SQL-regression tier (test/ SQL-tester, SURVEY §4 tier 3)
— run full SQL text through parse/analyze/optimize/execute and diff results."""

import math

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import tpch_catalog

from tpch_oracle import ORACLES, load_frames
from tpch_queries import QUERIES

SF = 0.01


@pytest.fixture(scope="module")
def session():
    return Session(tpch_catalog(sf=SF))


@pytest.fixture(scope="module")
def frames(session):
    return load_frames(session.catalog)


def _norm(v):
    if v is None:
        return None
    if isinstance(v, (np.floating, float)):
        return float(v)
    if isinstance(v, (np.integer, int)):
        return float(v)
    if isinstance(v, pd.Timestamp):
        return v.strftime("%Y-%m-%d")
    if isinstance(v, np.datetime64):
        return str(v)[:10]
    return str(v)


def _cmp_rows(got, exp, qid, ordered):
    assert len(got) == len(exp), f"Q{qid}: {len(got)} rows vs oracle {len(exp)}"
    if not ordered:
        got = sorted(got, key=str)
        exp = sorted(exp, key=str)
    for i, (g, e) in enumerate(zip(got, exp)):
        assert len(g) == len(e), f"Q{qid} row {i}: arity {len(g)} vs {len(e)}"
        for j, (gv, ev) in enumerate(zip(g, e)):
            gn, en = _norm(gv), _norm(ev)
            if gn is None or en is None:
                assert gn is None and en is None, f"Q{qid} row {i} col {j}: {gn} vs {en}"
            elif isinstance(gn, float) and isinstance(en, float):
                if math.isnan(en):
                    assert math.isnan(gn), f"Q{qid} row {i} col {j}: {gn} vs NaN"
                else:
                    tol = max(abs(en), 1.0) * 1e-6
                    assert abs(gn - en) <= tol, f"Q{qid} row {i} col {j}: {gn} vs {en}"
            else:
                assert gn == en, f"Q{qid} row {i} col {j}: {gn!r} vs {en!r}"


# queries whose full output order is deterministic given the sort keys
FULLY_ORDERED = {1, 4, 5, 6, 7, 8, 9, 12, 14, 17, 19, 20, 22}


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_query(session, frames, qid):
    res = session.sql(QUERIES[qid])
    got = res.rows()
    exp_df = ORACLES[qid](frames)
    exp = [tuple(r) for r in exp_df.itertuples(index=False)]
    _cmp_rows(got, exp, qid, ordered=qid in FULLY_ORDERED)
