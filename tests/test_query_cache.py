"""Two-tier query cache (starrocks_tpu/cache/): correctness of reuse,
invalidation, eviction, and the verified cache key.

Reference behavior: be/src/exec/query_cache/ (per-tablet partial-
aggregation states with multi-version delta reuse) behind the FE's
enable_query_cache session variable. The invariants under test:

- a warm full-result hit returns byte-identical rows without executing;
- ANY mutation path (session DML, direct TabletStore calls) drops stale
  full-result entries — never a stale row served;
- after an append the partial-aggregation tier re-aggregates ONLY the new
  segments (asserted via qcache_partial_hits / qcache_rows_saved) and the
  merged result matches an uncached run;
- nondeterministic expressions are never cached;
- the LRU evicts past query_cache_capacity_mb;
- the result cache key is VERIFIED complete (analysis/key_check.py
  check_cache_reads + tools/src_lint.py R3);
- enable_query_cache=off is bit-identical to the uncached engine.
"""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture
def qcache_on():
    config.set("enable_query_cache", True)
    config.set("plan_verify_level", "strict")
    try:
        yield
    finally:
        config.set("enable_query_cache", False)
        config.set("query_cache_capacity_mb", 256)
        config.set("plan_verify_level", "warn")


def _counters(sess):
    return {k: v for k, (v, _) in sess.last_profile.counters.items()}


def _mem_session(n=1000):
    cat = Catalog()
    cat.register("t", HostTable.from_pydict({
        "k": np.arange(n) % 7, "v": np.arange(n) * 1.0}))
    return Session(cat)


def _stored_session(tmp_path, batches=((0, 2000), (2000, 4000))):
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table t (k int, v double)")
    for lo, hi in batches:  # one rowset file per INSERT
        vals = ",".join(f"({i % 5},{float(i)})" for i in range(lo, hi))
        s.sql(f"insert into t values {vals}")
    return s


AGG = "select k, sum(v) as s, count(*) as c from t group by k order by k"


# --- full-result tier --------------------------------------------------------

def test_full_result_hit_identical(qcache_on):
    s = _mem_session()
    r1 = s.sql(AGG)
    r2 = s.sql(AGG)
    assert _counters(s).get("qcache_hits") == 1
    assert r2.rows() == r1.rows()
    # the hit path never touched optimizer/compiler
    assert "optimize" not in _counters(s)


def test_insert_drops_stale_entry(qcache_on):
    s = _mem_session()
    s.sql(AGG)
    s.sql(AGG)
    assert _counters(s).get("qcache_hits") == 1
    s.sql("insert into t values (1, 99.0)")
    r = s.sql(AGG)
    c = _counters(s)
    assert c.get("qcache_hits", 0) == 0 and c.get("qcache_misses") == 1
    got = {row[0]: row[1] for row in r.rows()}
    exp = {k: sum(float(i) for i in range(1000) if i % 7 == k)
           for k in range(7)}
    exp[1] += 99.0
    assert all(abs(got[k] - exp[k]) < 1e-6 for k in exp)


def test_set_trace_knob_misses(qcache_on):
    """A SET on any trace-declared knob changes the result key: the old
    entry must not serve (the stale-trace bug class, closed for results)."""
    s = _mem_session()
    s.sql(AGG)
    old = config.get("enable_runtime_filters")
    try:
        config.set("enable_runtime_filters", not old)
        s.sql(AGG)
        assert _counters(s).get("qcache_hits", 0) == 0
    finally:
        config.set("enable_runtime_filters", old)


def test_nondeterministic_never_cached(qcache_on):
    s = _mem_session()
    for q in ("select rand() as r from t limit 1",
              "select now() as n from t limit 1"):
        s.sql(q)
        assert "qcache_uncacheable" in s.last_profile.infos
        s.sql(q)
        c = _counters(s)
        assert c.get("qcache_hits", 0) == 0 and "qcache_misses" not in c


def test_lru_eviction_tiny_budget(qcache_on):
    from starrocks_tpu.cache.query_cache import QCACHE_EVICTIONS

    s = _mem_session()
    config.set("query_cache_capacity_mb", 0)  # every store evicts at once
    e0 = QCACHE_EVICTIONS.value
    s.sql(AGG)
    s.sql(AGG)
    assert _counters(s).get("qcache_hits", 0) == 0
    assert QCACHE_EVICTIONS.value > e0
    assert s.cache.qcache.resident_bytes == 0


def test_off_is_uncached(qcache_on):
    config.set("enable_query_cache", False)
    s = _mem_session()
    s.sql(AGG)
    s.sql(AGG)
    c = _counters(s)
    assert "qcache_hits" not in c and "qcache_misses" not in c
    assert s.cache.qcache.resident_bytes == 0


# --- partial-aggregation tier (stored tables) --------------------------------

def test_partial_tier_delta_reuse(qcache_on, tmp_path):
    s = _stored_session(tmp_path)
    s.sql(AGG)  # cold: both segments aggregate, states cached
    assert _counters(s).get("qcache_partial_hits") == 0
    s.sql(AGG)
    assert _counters(s).get("qcache_hits") == 1  # full-result short-circuit
    # append a THIRD segment: full-result entry drops, the partial tier
    # must reuse the 2 cached states and scan only the new 1000 rows
    vals = ",".join(f"({i % 5},{float(i)})" for i in range(4000, 5000))
    s.sql(f"insert into t values {vals}")
    r = s.sql(AGG)
    c = _counters(s)
    assert c.get("qcache_partial_hits") == 2
    assert c.get("qcache_rows_saved") == 4000
    got = {row[0]: (row[1], row[2]) for row in r.rows()}
    for k in range(5):
        vs = [float(i) for i in range(5000) if i % 5 == k]
        assert abs(got[k][0] - sum(vs)) < 1e-3 and got[k][1] == len(vs)


def test_partial_tier_string_keys_and_avg(qcache_on, tmp_path):
    """Per-segment string dictionaries must remap through the state merge,
    and avg must decompose/merge exactly (sum+count split)."""
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table t (g varchar, v double)")
    names = ["aa", "bb", "cc"]
    for lo, hi in ((0, 1500), (1500, 3000)):
        vals = ",".join(
            f"('{names[i % 3]}',{float(i)})" for i in range(lo, hi))
        s.sql(f"insert into t values {vals}")
    q = ("select g, avg(v) as a, count(*) as c from t "
         "group by g order by g")
    config.set("enable_query_cache", False)
    base = s.sql(q).rows()
    config.set("enable_query_cache", True)
    got = s.sql(q).rows()
    assert [r[0] for r in got] == [r[0] for r in base]
    for a, b in zip(got, base):
        assert abs(a[1] - b[1]) < 1e-9 and a[2] == b[2]
    vals = ",".join(f"('{names[i % 3]}',{float(i)})"
                    for i in range(3000, 3600))
    s.sql(f"insert into t values {vals}")
    r = s.sql(q)
    assert _counters(s).get("qcache_partial_hits") == 2
    for g, a, c in r.rows():
        vs = [float(i) for i in range(3600) if names[i % 3] == g]
        assert c == len(vs) and abs(a - sum(vs) / len(vs)) < 1e-9


def test_upsert_delvec_recomputes_segment(qcache_on, tmp_path):
    """A primary-key upsert moves a segment's delete vector: its cached
    state must MISS (the version token pins delvec) and the masked rows
    must leave the aggregate."""
    d = str(tmp_path / "db")
    s = Session(data_dir=d)
    s.sql("create table t (k int, v double, primary key (k))")
    s.sql("insert into t values " + ",".join(
        f"({i},{float(i)})" for i in range(100)))
    s.sql("insert into t values " + ",".join(
        f"({i},{float(i)})" for i in range(100, 200)))
    q = "select sum(v) as s, count(*) as c from t"
    s.sql(q)
    # upsert rewrites k=5 (segment 1 gains a delvec entry + new rowset)
    s.sql("insert into t values (5, 500.0)")
    r = s.sql(q)
    row = r.rows()[0]
    assert row[1] == 200
    assert abs(row[0] - (sum(range(200)) - 5.0 + 500.0)) < 1e-6


def test_direct_store_compaction_invalidates(qcache_on, tmp_path):
    """Storage-level mutations that bypass session DML (explicit
    compaction) must still drop full-result entries — the TabletStore
    mutation listener -> catalog data-epoch path."""
    s = _stored_session(tmp_path)
    s.sql(AGG)
    s.sql(AGG)
    assert _counters(s).get("qcache_hits") == 1
    s.store.compact_table("t")
    s.sql(AGG)
    assert _counters(s).get("qcache_hits", 0) == 0


# --- distributed -------------------------------------------------------------

def test_distributed_partial_merge_matches_uncached(qcache_on, tmp_path):
    s = Session(data_dir=str(tmp_path / "db"), dist_shards=2)
    s.sql("create table t (k int, v double)")
    for lo, hi in ((0, 1500), (1500, 3000)):
        vals = ",".join(f"({i % 5},{float(i)})" for i in range(lo, hi))
        s.sql(f"insert into t values {vals}")
    config.set("enable_query_cache", False)
    base = s.sql(AGG).rows()
    config.set("enable_query_cache", True)
    got = s.sql(AGG).rows()
    assert [r[0] for r in got] == [r[0] for r in base]
    for a, b in zip(got, base):
        assert abs(a[1] - b[1]) < 1e-6 and a[2] == b[2]
    vals = ",".join(f"({i % 5},{float(i)})" for i in range(3000, 3600))
    s.sql(f"insert into t values {vals}")
    r = s.sql(AGG)
    c = _counters(s)
    assert c.get("qcache_partial_hits") == 2 and c.get("qcache_rows_saved") == 3000
    for k, sm, cnt in r.rows():
        vs = [float(i) for i in range(3600) if i % 5 == k]
        assert cnt == len(vs) and abs(sm - sum(vs)) < 1e-3


# --- verified cache key ------------------------------------------------------

def test_check_cache_reads_flags_undeclared_knob():
    from starrocks_tpu.analysis.key_check import check_cache_reads

    assert check_cache_reads({"enable_query_cache"}) == []      # cache_key
    assert check_cache_reads({"runtime_filter_strategy"}) == []  # trace
    assert check_cache_reads({"enable_mv_rewrite"}) == []        # opt key
    assert check_cache_reads({"max_recompiles"}) == []           # host loop
    bad = check_cache_reads({"some_undeclared_knob"})
    assert len(bad) == 1 and bad[0].invariant == "knob-outside-result-key"


def test_strict_declines_to_cache_on_escapee(qcache_on):
    """An undeclared knob read during a cached execution fails strict mode
    (and the result is not stored)."""
    from starrocks_tpu.analysis import VerifyError

    s = _mem_session()
    if "test_unkeyed_knob" not in config._fields:  # escapee probe knob
        config.define("test_unkeyed_knob", 7)

    from starrocks_tpu.runtime import executor as ex
    real_uncached = ex.Executor._execute_plain_uncached

    def leaky(self, plan, profile):
        config.get("test_unkeyed_knob")
        return real_uncached(self, plan, profile)

    ex.Executor._execute_plain_uncached = leaky
    try:
        with pytest.raises(VerifyError):
            s.sql(AGG)
    finally:
        ex.Executor._execute_plain_uncached = real_uncached
    assert s.cache.qcache.resident_bytes == 0


def test_src_lint_r3_flags_undeclared_literal(tmp_path):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import src_lint

    os.makedirs(tmp_path / "starrocks_tpu" / "cache")
    bad = tmp_path / "starrocks_tpu" / "cache" / "keys.py"
    bad.write_text("def k():\n"
                   "    return (config.get('batch_rows_threshold'),\n"
                   "            config.get('enable_query_cache'))\n")
    old = src_lint.REPO
    src_lint.REPO = str(tmp_path)
    try:
        findings = src_lint.lint_cache_keys()
    finally:
        src_lint.REPO = old
    assert len(findings) == 1 and "batch_rows_threshold" in findings[0]
    # the real keys.py is clean
    assert src_lint.lint_cache_keys() == []


# --- external tables in the metadata image -----------------------------------

def test_external_defs_in_image_checkpoint(qcache_on, tmp_path):
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    ext = tmp_path / "ext"
    ext.mkdir()
    pq.write_table(pa.table(pd.DataFrame(
        {"k": [1, 2, 2], "v": [1.0, 2.0, 3.0]})), str(ext / "a.parquet"))
    d = str(tmp_path / "db")
    s = Session(data_dir=d)
    s.sql(f"create external table e from '{ext}'")
    r1 = s.sql("select k, sum(v) as s from e group by k order by k").rows()
    s.checkpoint_metadata()
    # image (not just the sidecar) carries the def
    img = s.store.read_image()
    assert img["catalog"]["external_tables"] == {"e": str(ext)}
    # a restored catalog registers the same handle with the same file-stat
    # data version: cache validity agrees across restarts
    s2 = Session(data_dir=d)
    assert s2.catalog.data_version("e")[1:] == s.catalog.data_version("e")[1:]
    r2 = s2.sql("select k, sum(v) as s from e group by k order by k").rows()
    assert r2 == r1
    # external file mutation changes the data version -> stale entry drops
    s2.sql("select k, sum(v) as s from e group by k order by k")
    pq.write_table(pa.table(pd.DataFrame(
        {"k": [1], "v": [10.0]})), str(ext / "b.parquet"))
    s2.catalog.get_table("e").invalidate()
    s2.cache.invalidate("e")  # the external refresh idiom (device cols too)
    r3 = s2.sql("select k, sum(v) as s from e group by k order by k")
    assert _counters(s2).get("qcache_hits", 0) == 0
    got = {r[0]: r[1] for r in r3.rows()}
    assert got == {1: 11.0, 2: 5.0}


def test_drop_external_survives_restart(tmp_path):
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    ext = tmp_path / "ext"
    ext.mkdir()
    pq.write_table(pa.table(pd.DataFrame({"k": [1]})),
                   str(ext / "a.parquet"))
    d = str(tmp_path / "db")
    s = Session(data_dir=d)
    s.sql(f"create external table e from '{ext}'")
    s.checkpoint_metadata()
    s.sql("drop table e")
    s2 = Session(data_dir=d)  # image says create, journal tail says drop
    assert s2.catalog.get_table("e") is None
