"""Spilled WINDOW: forced-small partition groups must match the in-HBM
window exactly (completes VERDICT r4 item 9; the Grace recipe applied to
PARTITION BY disjointness)."""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture()
def cat():
    rng = np.random.default_rng(4)
    n = 40_000
    c = Catalog()
    c.register("ev", HostTable.from_pydict({
        "u": rng.integers(0, 900, n),
        "ts": rng.integers(0, 100_000, n),
        "amt": np.round(rng.random(n) * 100, 2),
    }))
    return c


QUERIES = [
    # rank family + running agg over partitions
    """select u, ts, row_number() over (partition by u order by ts, amt) rn,
              sum(amt) over (partition by u order by ts, amt) running
       from ev where ts < 60000""",
    # lead/lag with defaults
    """select u, ts, lag(amt, 1) over (partition by u order by ts, amt) p,
              rank() over (partition by u order by amt desc, ts) r
       from ev""",
]


def _norm(rows):
    return sorted(
        tuple(round(v, 6) if isinstance(v, float) else v for v in r)
        for r in rows)


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_spill_window_matches_device(cat, qi):
    q = QUERIES[qi]
    base = Session(cat).sql(q).rows()
    config.set("batch_rows_threshold", 4096)
    config.set("spill_batch_rows", 6000)
    try:
        s = Session(cat)
        spill = s.sql(q).rows()
        assert "spill_window" in s.last_profile.render()
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)
    assert _norm(spill) == _norm(base)


def test_spill_window_null_partition_keys_one_group():
    """NULL partition keys must form ONE window partition in the spilled
    path, matching the device window's both-NULL-equal rule."""
    rng = np.random.default_rng(7)
    n = 9000
    keys = [None if i % 7 == 0 else int(i % 50) for i in range(n)]
    c = Catalog()
    c.register("t", HostTable.from_pydict({
        "k": keys, "v": rng.integers(0, 1000, n)}))
    q = "select k, count(*) over (partition by k) c from t"
    base = Session(c).sql(q).rows()
    config.set("batch_rows_threshold", 1024)
    config.set("spill_batch_rows", 2000)
    try:
        s = Session(c)
        spill = s.sql(q).rows()
        assert "spill_window" in s.last_profile.render()
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)
    assert sorted(spill, key=str) == sorted(base, key=str)
