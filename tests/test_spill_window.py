"""Spilled WINDOW: forced-small partition groups must match the in-HBM
window exactly (completes VERDICT r4 item 9; the Grace recipe applied to
PARTITION BY disjointness)."""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture()
def cat():
    rng = np.random.default_rng(4)
    n = 40_000
    c = Catalog()
    c.register("ev", HostTable.from_pydict({
        "u": rng.integers(0, 900, n),
        "ts": rng.integers(0, 100_000, n),
        "amt": np.round(rng.random(n) * 100, 2),
    }))
    return c


QUERIES = [
    # rank family + running agg over partitions
    """select u, ts, row_number() over (partition by u order by ts, amt) rn,
              sum(amt) over (partition by u order by ts, amt) running
       from ev where ts < 60000""",
    # lead/lag with defaults
    """select u, ts, lag(amt, 1) over (partition by u order by ts, amt) p,
              rank() over (partition by u order by amt desc, ts) r
       from ev""",
]


def _norm(rows):
    return sorted(
        tuple(round(v, 6) if isinstance(v, float) else v for v in r)
        for r in rows)


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_spill_window_matches_device(cat, qi):
    q = QUERIES[qi]
    base = Session(cat).sql(q).rows()
    config.set("batch_rows_threshold", 4096)
    config.set("spill_batch_rows", 6000)
    try:
        s = Session(cat)
        spill = s.sql(q).rows()
        assert "spill_window" in s.last_profile.render()
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)
    assert _norm(spill) == _norm(base)


def test_spill_window_null_partition_keys_one_group():
    """NULL partition keys must form ONE window partition in the spilled
    path, matching the device window's both-NULL-equal rule."""
    rng = np.random.default_rng(7)
    n = 9000
    keys = [None if i % 7 == 0 else int(i % 50) for i in range(n)]
    c = Catalog()
    c.register("t", HostTable.from_pydict({
        "k": keys, "v": rng.integers(0, 1000, n)}))
    q = "select k, count(*) over (partition by k) c from t"
    base = Session(c).sql(q).rows()
    config.set("batch_rows_threshold", 1024)
    config.set("spill_batch_rows", 2000)
    try:
        s = Session(c)
        spill = s.sql(q).rows()
        assert "spill_window" in s.last_profile.render()
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)
    assert sorted(spill, key=str) == sorted(base, key=str)


def test_streaming_window_skewed_partition_beyond_budget():
    """One PARTITION BY group holds ~90% of rows — the Grace hash-split
    would need the whole partition resident, so the STREAMING path
    (global sort + peer-cut chunks + carried running state) must kick in
    and still match pandas exactly (runtime/batched.py
    execute_streaming_window)."""
    import numpy as np
    import pandas as pd

    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.column import HostTable

    rng = np.random.RandomState(7)
    n = 4000
    g = np.where(rng.rand(n) < 0.9, 1, rng.randint(2, 6, n)).astype(np.int64)
    o = rng.randint(0, 300, n).astype(np.int64)  # many peer ties
    v = rng.randint(-50, 50, n).astype(np.int64)

    s = Session()
    s.catalog.register("skw", HostTable.from_pydict(
        {"g": g, "o": o, "v": v}))
    # one window spec (one LWindow node) of peer-deterministic functions:
    # row_number over ties would differ between engines
    q = ("select g, o, v, "
         "rank() over (partition by g order by o) rk, "
         "dense_rank() over (partition by g order by o) dk, "
         "sum(v) over (partition by g order by o) rs, "
         "min(v) over (partition by g order by o) rmin, "
         "count(v) over (partition by g order by o) rc "
         "from skw")

    config.set("batch_rows_threshold", 512)
    config.set("spill_batch_rows", 512)
    try:
        got = s.sql(q)
        prof = s.last_profile.render()
        assert "stream_chunks" in prof, prof[:800]
        rows = sorted(got.rows())
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)

    df = pd.DataFrame({"g": g, "o": o, "v": v})
    df = df.sort_values(["g", "o"], kind="stable").reset_index(drop=True)
    gb = df.groupby("g", sort=False)
    df["rk"] = gb["o"].rank(method="min").astype(np.int64)
    df["dk"] = gb["o"].rank(method="dense").astype(np.int64)
    # default RANGE frame: peers included -> per (g, o) totals, cumulative
    agg = df.groupby(["g", "o"])["v"].agg(["sum", "min", "count"])
    cum = agg.groupby(level=0).cumsum()
    df = df.join(cum.rename(columns={
        "sum": "rs", "min": "rmin2", "count": "rc"}), on=["g", "o"])
    df["rmin"] = df.join(agg.groupby(level=0)["min"].cummin().rename(
        "rmin3"), on=["g", "o"])["rmin3"]
    exp_rows = sorted(
        tuple(r) for r in df[
            ["g", "o", "v", "rk", "dk", "rs", "rmin", "rc"]].itertuples(
            index=False))
    assert len(rows) == len(exp_rows)
    mismatch = [i for i, (a, b) in enumerate(zip(rows, exp_rows))
                if tuple(a) != tuple(b)]
    assert not mismatch, (mismatch[:5], rows[mismatch[0]],
                          exp_rows[mismatch[0]]) if mismatch else None


def test_streaming_window_null_carry():
    """Locally-NULL running values in a later chunk must surface the
    CARRIED state (the partition had live inputs in earlier chunks)."""
    import numpy as np

    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.column import HostTable

    n = 1200
    g = np.zeros(n, np.int64)
    o = np.arange(n, dtype=np.int64)
    # the second half of the partition is all NULL (None -> NULL)
    v = [float(i) if i < 600 else None for i in range(n)]
    s = Session()
    s.catalog.register("nls", HostTable.from_pydict(
        {"g": g, "o": o, "v": v}))
    config.set("batch_rows_threshold", 256)
    config.set("spill_batch_rows", 256)
    try:
        rows = s.sql(
            "select o, sum(v) over (partition by g order by o) rs, "
            "min(v) over (partition by g order by o) rm from nls"
        ).rows()
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)
    got = {r[0]: (r[1], r[2]) for r in rows}
    full = float(np.arange(600).sum())
    assert got[599] == (full, 0.0)
    # rows in the NULL tail carry the partition's running state forward
    assert got[700] == (full, 0.0)
    assert got[1199] == (full, 0.0)
