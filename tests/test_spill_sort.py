"""Spilled ORDER BY: forced-small batches must match the in-HBM sort
exactly (VERDICT r4 item 9; reference analog:
be/src/compute_env/sorting/merge_path.h external sort, re-designed as
device-evaluated sort operands + host global order)."""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture()
def cat():
    rng = np.random.default_rng(3)
    n = 50_000
    vals = rng.integers(-1000, 1000, n).astype(float) / 4
    nulls = rng.random(n) < 0.05
    c = Catalog()
    c.register("big", HostTable.from_pydict({
        "k": rng.integers(0, 500, n),
        "v": [None if nz else float(x) for x, nz in zip(vals, nulls)],
        "s": [f"s{i % 97}" for i in range(n)],
    }))
    return c


QUERIES = [
    "select k, v, s from big order by v, k",
    "select k, v from big where k < 250 order by v desc, k desc",
    "select k, v, s from big order by s, v nulls first limit 500",
    "select k + 1 as k1, v from big order by k1 desc, v limit 100",
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_spill_sort_matches_device_sort(cat, qi):
    q = QUERIES[qi]
    base = Session(cat).sql(q).rows()
    config.set("batch_rows_threshold", 4096)
    config.set("spill_batch_rows", 7000)
    try:
        spill = Session(cat).sql(q).rows()
        # the spill path actually engaged
        prof_sess = Session(cat)
        prof_sess.sql(q)
        assert "spill_sort" in prof_sess.last_profile.render()
    finally:
        config.set("batch_rows_threshold", 0)
        config.set("spill_batch_rows", 0)
    assert spill == base
