"""Short-circuit point-query plane (runtime/point.py + storage probe API).

The lane's contract: every statement it serves must be VALUE-IDENTICAL to
the full analytic path (`SET enable_short_circuit = off`) across the whole
torture matrix — hit / miss / deleted / multi-version rows, IN lists,
projections, interleaved DML — while staying inside the lifecycle plane
(killable in flight, chaos-clean at its failpoint, zero leaked slots or
bytes) and riding the per-table statement gate so point traffic on one
table never queues behind DML on another.
"""

import threading
import time

import pytest

from starrocks_tpu.runtime import failpoint, lifecycle, point
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.failpoint import FailPointError
from starrocks_tpu.runtime.lifecycle import (
    ACCOUNTANT, REGISTRY, QueryCancelledError,
)
from starrocks_tpu.runtime.serving import (
    _FAST_MISS, ServingTier, StatementGate, SERVE_POINT_INLINE,
)
from starrocks_tpu.runtime.session import Session


@pytest.fixture(autouse=True)
def _lane_knob():
    prev = config.get("enable_short_circuit")
    config.set("enable_short_circuit", True)
    yield
    config.set("enable_short_circuit", prev)


def _mk(tmp_path, name="db"):
    s = Session(data_dir=str(tmp_path / name))
    s.sql("create table kv (k bigint, v varchar, n bigint, primary key(k))")
    s.sql("insert into kv values "
          "(1, 'a', 10), (2, 'b', 20), (3, 'c', 30), (4, 'd', null)")
    return s


def _ab(s, sql):
    """Run `sql` through the lane and through the full path; both must
    agree on rows AND column names."""
    config.set("enable_short_circuit", True)
    on = s.sql(sql)
    config.set("enable_short_circuit", False)
    off = s.sql(sql)
    config.set("enable_short_circuit", True)
    assert on.rows() == off.rows(), sql
    assert on.column_names == off.column_names, sql
    return on


def _leak_snapshot(s):
    wm = getattr(s.catalog, "workgroups", None)
    return {
        "process_bytes": ACCOUNTANT.snapshot()["process_bytes"],
        "slots": sum(wm.running.values()) if wm is not None else 0,
        "registry": len(REGISTRY.snapshot()),
    }


# --- equality torture matrix --------------------------------------------------


def test_point_select_matrix_equals_full_path(tmp_path):
    s = _mk(tmp_path)
    lookups0 = point.POINT_LOOKUPS.value
    _ab(s, "select * from kv where k = 2")                 # hit, star
    _ab(s, "select v from kv where k = 2")                 # projection
    _ab(s, "select n, v from kv where k = 3")              # reordered proj
    _ab(s, "select v from kv where k = 99")                # miss
    _ab(s, "select * from kv where k in (1, 3, 99)")       # mixed IN
    _ab(s, "select * from kv where k in (2, 2, 2)")        # duplicate keys
    _ab(s, "select n from kv where k = 4")                 # NULL value col
    assert point.POINT_LOOKUPS.value > lookups0


def test_point_sees_deleted_and_multiversion_rows(tmp_path):
    s = _mk(tmp_path)
    # multi-version: upsert the same key twice; the lane must serve the
    # LIVE version (delvec masks the superseded row)
    s.sql("insert into kv values (2, 'b2', 21)")
    s.sql("insert into kv values (2, 'b3', 22)")
    r = _ab(s, "select v, n from kv where k = 2")
    assert r.rows() == [("b3", 22)]
    # deleted: a point read of a delvec'd key is a miss, identically
    config.set("enable_short_circuit", False)
    s.sql("delete from kv where k = 3")
    config.set("enable_short_circuit", True)
    r = _ab(s, "select * from kv where k = 3")
    assert r.rows() == []
    # reinsert after delete is visible again
    s.sql("insert into kv values (3, 'c9', 33)")
    r = _ab(s, "select v from kv where k = 3")
    assert r.rows() == [("c9",)]


def test_point_dml_equals_full_path_end_state(tmp_path):
    """Apply the same UPDATE/DELETE script through the lane and through
    the full path on twin stores; final table contents must agree."""
    script = [
        "update kv set n = 77 where k = 1",
        "delete from kv where k = 2",
        "update kv set n = null where k = 3",
        "update kv set n = 0 where k = 99",        # zero-hit update
        "delete from kv where k = 99",             # zero-hit delete
        "delete from kv where k in (3, 4)",
    ]
    s_on = _mk(tmp_path, "on")
    s_off = _mk(tmp_path, "off")
    affected_on, affected_off = [], []
    for stmt in script:
        config.set("enable_short_circuit", True)
        affected_on.append(s_on.sql(stmt))
        config.set("enable_short_circuit", False)
        affected_off.append(s_off.sql(stmt))
    assert affected_on == affected_off
    config.set("enable_short_circuit", False)
    full = "select k, v, n from kv order by k"
    assert s_on.sql(full).rows() == s_off.sql(full).rows()
    config.set("enable_short_circuit", True)


def test_point_update_varchar_column(tmp_path):
    """The lane's delta-write path handles varchar SET columns (the full
    analytic path cannot compile a string-literal CASE rewrite); verify
    the write through both read paths."""
    s = _mk(tmp_path)
    assert s.sql("update kv set v = 'zz', n = 77 where k = 1") == 1
    r = _ab(s, "select v, n from kv where k = 1")
    assert r.rows() == [("zz", 77)]


def test_point_read_your_writes_interleaved(tmp_path):
    s = _mk(tmp_path)
    for i in range(5):
        s.sql(f"update kv set n = {100 + i} where k = 1")
        assert s.sql("select n from kv where k = 1").rows() == [(100 + i,)]
    s.sql("delete from kv where k = 1")
    assert s.sql("select n from kv where k = 1").rows() == []
    s.sql("insert into kv values (1, 'back', 1)")
    assert s.sql("select v from kv where k = 1").rows() == [("back",)]


def test_off_keeps_lane_cold(tmp_path):
    s = _mk(tmp_path)
    config.set("enable_short_circuit", False)
    before = point.POINT_LOOKUPS.value
    s.sql("select * from kv where k = 1")
    s.sql("update kv set n = 5 where k = 1")
    assert point.POINT_LOOKUPS.value == before
    config.set("enable_short_circuit", True)


def test_point_statement_class_and_profile(tmp_path):
    s = _mk(tmp_path)
    r = s.sql("select v from kv where k = 1")
    assert r.profile is not None and r.profile.name == "point"
    assert s.last_profile is r.profile
    # non-PK predicates never enter the lane
    before = point.POINT_LOOKUPS.value
    s.sql("select v from kv where n = 10")
    assert point.POINT_LOOKUPS.value == before


# --- lifecycle: KILL + chaos --------------------------------------------------


def test_kill_in_flight_point_query(tmp_path):
    s = _mk(tmp_path)

    def kill_current():
        ctx = lifecycle.current()
        assert ctx is not None
        REGISTRY.cancel(ctx.qid, requester="root", admin=True)

    before = _leak_snapshot(s)
    with failpoint.scoped("point::probe", action=kill_current):
        with pytest.raises(QueryCancelledError, match="cancelled at stage"):
            s.sql("select * from kv where k = 1")
    assert _leak_snapshot(s) == before
    # lane healthy afterwards
    assert s.sql("select v from kv where k = 1").rows() == [("a",)]


def test_chaos_raise_at_point_probe_zero_leaks(tmp_path):
    s = _mk(tmp_path)
    before = _leak_snapshot(s)
    with failpoint.scoped("point::probe"):
        with pytest.raises(FailPointError, match="point::probe"):
            s.sql("select * from kv where k = 1")
    assert _leak_snapshot(s) == before
    assert s.store._journal_lock.acquire(blocking=False)
    s.store._journal_lock.release()
    assert s.sql("select v from kv where k = 1").rows() == [("a",)]


def test_chaos_raise_at_delete_rows_zero_leaks(tmp_path):
    s = _mk(tmp_path)
    before = _leak_snapshot(s)
    with failpoint.scoped("store::delete_rows"):
        with pytest.raises(FailPointError, match="store::delete_rows"):
            s.sql("delete from kv where k = 1")
    assert _leak_snapshot(s) == before
    # the failed delete left the row intact and the store serving
    assert s.sql("select v from kv where k = 1").rows() == [("a",)]
    assert s.sql("delete from kv where k = 1") == 1
    assert s.sql("select v from kv where k = 1").rows() == []


# --- per-table statement gate (NEXT 7g) ---------------------------------------


def test_gate_point_read_flows_past_dml_on_other_table():
    g = StatementGate()
    with g.exclusive("x", frozenset()):
        # reads of another table flow freely
        assert g.try_shared(frozenset(("y",)))
        g.release_shared(frozenset(("y",)))
        # reads of the DML's table are barred
        assert not g.try_shared(frozenset(("x",)))
        # footprint-unknown readers are barred by ANY table writer
        assert not g.try_shared()
    # gate fully released
    assert g.try_shared(frozenset(("x",)))
    g.release_shared(frozenset(("x",)))


def test_gate_global_exclusive_excludes_table_traffic():
    g = StatementGate()
    assert g.try_shared(frozenset(("y",)))
    entered = []

    def ddl():
        with g.exclusive():
            entered.append("ddl")

    th = threading.Thread(target=ddl)
    th.start()
    deadline = time.monotonic() + 5
    while not g._writers_waiting and time.monotonic() < deadline:
        time.sleep(0.005)
    # a QUEUED global writer bars new readers of any kind
    assert not g.try_shared(frozenset(("z",)))
    assert not entered
    g.release_shared(frozenset(("y",)))
    th.join(timeout=5)
    assert entered == ["ddl"]
    assert g.try_shared()
    g.release_shared()


def test_gate_table_writer_waits_for_same_table_reader():
    g = StatementGate()
    assert g.try_shared(frozenset(("x",)))
    entered = []

    def dml():
        with g.exclusive("x", frozenset()):
            entered.append("w")

    th = threading.Thread(target=dml)
    th.start()
    deadline = time.monotonic() + 5
    while not g._table_writers_waiting.get("x") \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    assert not entered
    # writer preference: new readers of x are barred while it waits
    assert not g.try_shared(frozenset(("x",)))
    # ...but readers of unrelated tables still flow
    assert g.try_shared(frozenset(("y",)))
    g.release_shared(frozenset(("y",)))
    g.release_shared(frozenset(("x",)))
    th.join(timeout=5)
    assert entered == ["w"]


def test_tier_point_inline_and_isolation_from_other_table_dml(tmp_path):
    s = Session(data_dir=str(tmp_path / "tier"))
    s.sql("create table pk_t (k bigint, v varchar, primary key(k))")
    s.sql("insert into pk_t values (1, 'one'), (2, 'two')")
    s.sql("create table locked (k bigint, v varchar, primary key(k))")
    s.sql("insert into locked values (1, 'x')")
    tier = ServingTier(s, pool_size=2)
    try:
        c = tier.new_session()
        n0 = SERVE_POINT_INLINE.value
        assert tier.execute(c, "select v from pk_t where k = 2").rows() \
            == [("two",)]
        assert SERVE_POINT_INLINE.value == n0 + 1
        # while DML holds `locked` exclusively, the point read of pk_t is
        # still served inline (per-table gate), not queued behind it
        with tier.gate.exclusive("locked", frozenset()):
            assert tier.execute(c, "select v from pk_t where k = 1").rows() \
                == [("one",)]
            assert SERVE_POINT_INLINE.value == n0 + 2
            # a point read of the LOCKED table must decline the inline
            # lane (gate contended) and go to the writer-ordered pool path
            assert tier._try_point_inline(
                c, "select v from locked where k = 1") is _FAST_MISS
        # point DML through the tier keeps working
        assert tier.execute(c, "update pk_t set v = 'uno' where k = 1") == 1
        assert tier.execute(c, "select v from pk_t where k = 1").rows() \
            == [("uno",)]
    finally:
        tier.shutdown()


def test_tier_point_inline_respects_off_switch(tmp_path):
    s = Session(data_dir=str(tmp_path / "tier2"))
    s.sql("create table pk_t (k bigint, v varchar, primary key(k))")
    s.sql("insert into pk_t values (1, 'one')")
    tier = ServingTier(s, pool_size=2)
    try:
        c = tier.new_session()
        config.set("enable_short_circuit", False)
        n0 = SERVE_POINT_INLINE.value
        assert tier.execute(c, "select v from pk_t where k = 1").rows() \
            == [("one",)]
        assert SERVE_POINT_INLINE.value == n0
    finally:
        config.set("enable_short_circuit", True)
        tier.shutdown()


# --- conservative fallbacks ---------------------------------------------------


def test_fallback_shapes_never_enter_lane(tmp_path):
    s = _mk(tmp_path)
    s.sql("create view vv as select * from kv")
    before = point.POINT_LOOKUPS.value
    falls = [
        "select * from kv where k = 1 and n = 10",   # non-PK residual
        "select * from kv where k > 1",              # range, not point
        "select * from vv where k = 1",              # view
        "select v from kv where k = 1 or k = 2",     # OR, not IN
    ]
    config.set("enable_short_circuit", False)
    off_rows = [s.sql(q).rows() for q in falls]
    config.set("enable_short_circuit", True)
    on_rows = [s.sql(q).rows() for q in falls]
    assert on_rows == off_rows
    assert point.POINT_LOOKUPS.value == before


def test_in_list_cap_falls_back(tmp_path):
    s = _mk(tmp_path)
    before = point.POINT_LOOKUPS.value
    keys = ", ".join(str(i) for i in range(point.MAX_POINT_KEYS + 1))
    r = _ab(s, f"select k from kv where k in ({keys})")
    assert sorted(r.rows()) == [(1,), (2,), (3,), (4,)]
    assert point.POINT_LOOKUPS.value == before  # over cap: analytic path


# --- static gate: R8 point-query-scope ----------------------------------------


def test_src_lint_r8_point_scope():
    import ast
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "sr_src_lint", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "src_lint.py"))
    sl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sl)

    class _MS:
        def __init__(self, rel, src):
            self.rel, self.src, self.tree = rel, src, ast.parse(src)
            self.path = rel

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    point_src = open(os.path.join(repo, sl.POINT_MODULE)).read()
    sess_src = open(os.path.join(repo, sl.SESSION_MODULE)).read()
    good = [_MS(sl.POINT_MODULE, point_src), _MS(sl.SESSION_MODULE, sess_src)]
    assert sl.lint_point_scope(good) == []
    # serving-side execution call: exactly the laundering R8 exists for
    bad = good + [_MS(
        os.path.join("starrocks_tpu", "runtime", "serving.py"),
        "def f(session, sql):\n    return point.try_execute(session, sql)\n")]
    f = sl.lint_point_scope(bad)
    assert len(f) == 1 and "point-query-scope" in f[0]
    # a second entry inside session.py but outside _sql_inner is equally bad
    rogue = sess_src + "\ndef rogue(s, t):\n    return point.try_execute(s, t)\n"
    f = sl.lint_point_scope(
        [_MS(sl.POINT_MODULE, point_src), _MS(sl.SESSION_MODULE, rogue)])
    assert len(f) == 1
