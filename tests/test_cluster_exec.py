"""Fault-tolerant cluster runtime: coordinator/worker fragment scheduling
over the host exchange plane (runtime/cluster_exec.py).

What's under test, end to end with REAL worker processes:
  * a multi-fragment SQL query (hash exchange) scheduled across workers
    answers oracle-identical to the single-process engine, cold and warm;
  * DML between queries triggers version-based table re-sync;
  * SIGKILL of a worker MID-FRAGMENT -> the fragment is re-placed and the
    query still answers correctly within `cluster_fragment_retries`, the
    heartbeat plane journals `heartbeat_loss`, the dead-workers gauge
    rises and the default heartbeat_loss alert fires;
  * a respawned worker reconnects: gauge decrements exactly once,
    `heartbeat_reconnect` journals once, registration (exchange addr)
    re-advertises, alert resolves;
  * a network partition (blackholed worker) times out and re-places;
  * losing EVERY worker exhausts retries into a typed WorkerLostError
    carrying worker id + fragment id — with zero leaked admission slots,
    zero leaked accountant bytes, an empty query registry and an `error`
    terminal audit record (the lost worker must never wedge a query or
    corrupt the coordinator).

Monitor-side unit tests (no subprocesses) drive the ALIVE->DEAD->ALIVE
round trip with a fake clock via ClusterMonitor._scan(now).

Heavier randomized kill/partition schedules live in
`tools/chaos_fuzz.py --cluster` (run_tier1.sh chaos stage,
SR_TPU_CLUSTER_CHAOS=1); this file keeps the deterministic contract.
"""

import socket
import threading
import time

import pytest

import starrocks_tpu.sql.distributed as D
from starrocks_tpu.runtime import cluster_exec as CE
from starrocks_tpu.runtime.alerts import ALERTS
from starrocks_tpu.runtime.audit import AUDIT
from starrocks_tpu.runtime.cluster import ALIVE, DEAD, WORKERS_DEAD, ClusterMonitor
from starrocks_tpu.runtime.cluster_exec import ClusterRuntime, WorkerLostError
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.events import EVENTS
from starrocks_tpu.runtime.lifecycle import ACCOUNTANT, REGISTRY
from starrocks_tpu.runtime.session import Session

# The canonical 3-fragment query: scan+shuffle join, shuffled agg, topn.
SQL = ("select d.v, sum(t.b) s from t join d on t.a = d.k "
       "group by d.v order by s desc, d.v limit 5")


def _gauge_alert_sample(v: float) -> dict:
    """History-ring-shaped sample for ALERTS.evaluate (gauges section)."""
    return {"gauges": {"sr_tpu_cluster_workers_dead": float(v)}}


# ---------------------------------------------------------------------------
# wire protocol (no subprocesses)
# ---------------------------------------------------------------------------

def test_wire_roundtrip_small():
    a, b = socket.socketpair()
    try:
        a.settimeout(5)
        b.settimeout(5)
        CE._send_msg(a, {"type": "PING", "n": 3}, {"x": list(range(10))})
        hdr, payload = CE._recv_msg(b)
        assert hdr == {"type": "PING", "n": 3}
        assert payload == {"x": list(range(10))}
        # headers may ride with no payload frame at all
        CE._send_msg(b, {"type": "OK"})
        hdr2, payload2 = CE._recv_msg(a)
        assert hdr2 == {"type": "OK"} and payload2 is None
    finally:
        a.close()
        b.close()


def test_wire_roundtrip_chunked_large():
    """A payload bigger than the 1 MB send slice crosses intact (the
    chunked-send path that keeps big BOOTSTRAP frames from tripping the
    0.1 s poll timeout)."""
    a, b = socket.socketpair()
    blob = {"data": b"\xab" * (3 << 20)}
    got = {}

    def rx():
        b.settimeout(5)
        got["msg"] = CE._recv_msg(b)

    th = threading.Thread(target=rx)
    th.start()
    try:
        a.settimeout(0.1)  # force the send loop through its timeout ticks
        ticks = []
        CE._send_msg(a, {"type": "BOOTSTRAP"}, blob,
                     on_wait=lambda: ticks.append(1))
        th.join(timeout=10)
        assert not th.is_alive()
        hdr, payload = got["msg"]
        assert hdr["type"] == "BOOTSTRAP"
        assert payload["data"] == blob["data"]
    finally:
        a.close()
        b.close()


def test_worker_lost_error_fields():
    e = WorkerLostError("w3", 7, "connection refused")
    assert e.worker_id == "w3" and e.fid == 7
    assert "w3" in str(e) and "7" in str(e)


# ---------------------------------------------------------------------------
# monitor round trip with a fake clock (satellite: reconnect semantics)
# ---------------------------------------------------------------------------

def test_monitor_reconnect_fake_clock():
    """beat -> (clock jump) DEAD -> beat -> ALIVE: exactly one
    heartbeat_loss, exactly one heartbeat_reconnect, gauge decremented
    exactly once, registration preserved across the outage. interval_s=60
    parks the real watchdog thread so `_scan(now)` is the only clock."""
    mon = ClusterMonitor(port=0, interval_s=60.0, miss_limit=3,
                         bind_host="127.0.0.1")
    try:
        reg = {"addr": ["127.0.0.1", 4242], "fragments": [0, 2]}
        mon.beat("wA", reg)
        assert mon.members()["wA"]["state"] == ALIVE
        assert mon.registration("wA") == reg

        now = time.monotonic()
        loss0 = EVENTS.stats().get("heartbeat_loss", 0)
        mon._scan(now + 60.0 * 3 + 1)  # past interval_s * miss_limit
        assert mon.members()["wA"]["state"] == DEAD
        assert WORKERS_DEAD.value == 1
        assert EVENTS.stats().get("heartbeat_loss", 0) == loss0 + 1

        # a second scan while already DEAD must not double-journal
        mon._scan(now + 60.0 * 3 + 2)
        assert EVENTS.stats().get("heartbeat_loss", 0) == loss0 + 1
        assert WORKERS_DEAD.value == 1

        # reconnect: one beat flips DEAD->ALIVE, gauge drops exactly once,
        # one heartbeat_reconnect, registration re-advertised
        rec0 = EVENTS.stats().get("heartbeat_reconnect", 0)
        mon.beat("wA", reg)
        assert mon.members()["wA"]["state"] == ALIVE
        assert WORKERS_DEAD.value == 0
        assert EVENTS.stats().get("heartbeat_reconnect", 0) == rec0 + 1
        assert mon.registration("wA") == reg

        # further beats are plain refreshes: no extra reconnect events
        mon.beat("wA", reg)
        assert EVENTS.stats().get("heartbeat_reconnect", 0) == rec0 + 1
        assert WORKERS_DEAD.value == 0
    finally:
        mon.close()


def test_monitor_flap_decrements_gauge_once():
    """Two workers die; one flaps back repeatedly — the gauge tracks the
    SET of DEAD workers (recomputed under the lock), never double
    decrements."""
    mon = ClusterMonitor(port=0, interval_s=60.0, miss_limit=3,
                         bind_host="127.0.0.1")
    try:
        mon.beat("w0")
        mon.beat("w1")
        now = time.monotonic()
        mon._scan(now + 400)
        assert WORKERS_DEAD.value == 2
        for _ in range(3):  # flapping beats from w0 only
            mon.beat("w0")
        assert WORKERS_DEAD.value == 1
        mon.beat("w1")
        assert WORKERS_DEAD.value == 0
    finally:
        mon.close()


# ---------------------------------------------------------------------------
# live cluster: coordinator + 2 worker processes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    """One shared 2-worker cluster + coordinator session for the module.

    Tests below run IN ORDER (file order; tier-1 runs with -p no:randomly)
    and the final test deliberately kills the whole fleet, so it must stay
    last."""
    old_shard, old_groups = D.SHARD_THRESHOLD_ROWS, D.SHUFFLE_AGG_MIN_GROUPS
    old_dist = config.get("dist_fragments")
    old_to = config.get("cluster_exec_timeout_s")
    D.SHARD_THRESHOLD_ROWS = 100
    D.SHUFFLE_AGG_MIN_GROUPS = 10
    s = Session(dist_shards=2)
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values "
          + ", ".join(f"({i % 97}, {i % 7})" for i in range(400)))
    s.sql("create table d (k int, v int)")
    s.sql("insert into d values "
          + ", ".join(f"({i}, {i * 10})" for i in range(97)))
    config.set("dist_fragments", True)
    oracle = s.sql(SQL).rows()  # single-process oracle, pre-attach
    cr = ClusterRuntime(n_workers=2, shards=2, hb_interval_s=0.1,
                        hb_miss_limit=3).start(s)
    cr.attach(s)
    try:
        yield s, cr, oracle
    finally:
        s.catalog.cluster_runtime = None
        cr.stop()
        config.set("dist_fragments", old_dist)
        config.set("cluster_exec_timeout_s", old_to)
        D.SHARD_THRESHOLD_ROWS = old_shard
        D.SHUFFLE_AGG_MIN_GROUPS = old_groups


def _pad(sql: str, n: int) -> str:
    """Unique query text per run so the coordinator query cache can't
    short-circuit the cluster path."""
    return sql + " " * n


def test_cluster_query_matches_oracle(cluster):
    s, cr, oracle = cluster
    got = s.sql(_pad(SQL, 1)).rows()
    assert got == oracle
    assert s.last_profile is not None
    assert "cluster_workers" in s.last_profile.render()
    assert cr.stats()["fragments_total"] >= 2


def test_cluster_warm_run_uses_worker_cache(cluster):
    s, cr, oracle = cluster
    shipped0 = sum(len(w.plans) for w in cr.workers())
    got = s.sql(_pad(SQL, 2)).rows()
    assert got == oracle
    # identical logical plan -> same fingerprint -> nothing new shipped
    assert sum(len(w.plans) for w in cr.workers()) == shipped0


def test_cluster_dml_resyncs_tables(cluster):
    s, cr, oracle = cluster
    s.sql("insert into t values (0, 100)")
    got = s.sql(_pad(SQL, 3)).rows()
    s.catalog.cluster_runtime = None  # local oracle for the new data
    try:
        want = s.sql(_pad(SQL, 4)).rows()
    finally:
        s.catalog.cluster_runtime = cr
    assert got == want


def test_kill_worker_mid_fragment_retries_and_alerts(cluster):
    """The headline contract: SIGKILL a worker while it holds an in-flight
    fragment. The query must NOT wedge, must answer oracle-correct via
    re-placement, and the observability plane must see the death."""
    s, cr, _ = cluster
    s.catalog.cluster_runtime = None
    try:
        oracle = s.sql(_pad(SQL, 5)).rows()
    finally:
        s.catalog.cluster_runtime = cr
    loss0 = EVENTS.stats().get("heartbeat_loss", 0)
    retries0 = cr.stats()["retries_total"]
    cr.inject_fault("w0", "delay", seconds=2.0, times=1)
    res = {}

    def run():
        try:
            res["rows"] = s.sql(_pad(SQL, 6)).rows()
        except Exception as e:  # noqa: BLE001 — surfaced via assert below
            res["err"] = e

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.6)  # let the query reach the delayed fragment on w0
    cr.kill_worker("w0")
    th.join(timeout=90)
    assert not th.is_alive(), "query wedged after worker SIGKILL"
    assert res.get("rows") == oracle, res
    assert cr.stats()["retries_total"] > retries0

    # heartbeat plane: coordinator-side loss event + gauge within 5s
    deadline = time.monotonic() + 5
    while (time.monotonic() < deadline
           and EVENTS.stats().get("heartbeat_loss", 0) <= loss0):
        time.sleep(0.05)
    assert EVENTS.stats().get("heartbeat_loss", 0) > loss0
    assert WORKERS_DEAD.value >= 1
    # the stock heartbeat_loss alert fires on the gauge
    af0 = EVENTS.stats().get("alert_fire", 0)
    ALERTS.evaluate(_gauge_alert_sample(WORKERS_DEAD.value))
    assert EVENTS.stats().get("alert_fire", 0) == af0 + 1


def test_respawn_reconnects_and_resolves(cluster):
    """Replacement worker re-registers over the heartbeat plane: gauge
    back to zero, exactly one reconnect event, addr re-advertised, the
    heartbeat_loss alert resolves — and the revived worker serves
    fragments again."""
    s, cr, _ = cluster
    rec0 = EVENTS.stats().get("heartbeat_reconnect", 0)
    cr.respawn_worker("w0")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and WORKERS_DEAD.value > 0:
        time.sleep(0.05)
    assert WORKERS_DEAD.value == 0
    assert EVENTS.stats().get("heartbeat_reconnect", 0) == rec0 + 1
    assert "addr" in cr.monitor.registration("w0")
    ar0 = EVENTS.stats().get("alert_resolve", 0)
    ALERTS.evaluate(_gauge_alert_sample(0.0))
    assert EVENTS.stats().get("alert_resolve", 0) == ar0 + 1
    s.catalog.cluster_runtime = None
    try:
        oracle = s.sql(_pad(SQL, 7)).rows()
    finally:
        s.catalog.cluster_runtime = cr
    assert s.sql(_pad(SQL, 8)).rows() == oracle
    assert len(cr.alive_workers()) == 2


def test_partition_blackhole_replaces_fragment(cluster):
    """A blackholed worker (receives, never replies) looks like a network
    partition: the per-request deadline promotes it to _WorkerGone and the
    fragment re-places onto the healthy worker."""
    s, cr, _ = cluster
    s.catalog.cluster_runtime = None
    try:
        oracle = s.sql(_pad(SQL, 9)).rows()
    finally:
        s.catalog.cluster_runtime = cr
    retries0 = cr.stats()["retries_total"]
    config.set("cluster_exec_timeout_s", 1.5)
    try:
        cr.inject_fault("w1", "blackhole", seconds=8.0, times=1)
        got = s.sql(_pad(SQL, 10)).rows()
    finally:
        config.set("cluster_exec_timeout_s", 30.0)
    assert got == oracle
    assert cr.stats()["retries_total"] > retries0
    time.sleep(1.0)  # drain w1's blackhole window before the next test


def test_total_worker_loss_raises_typed_error_without_leaks(cluster):
    """LAST (kills the whole fleet): retry exhaustion surfaces a typed
    WorkerLostError naming worker + fragment, and the coordinator leaks
    NOTHING — no admission slots, no accountant bytes, no registry
    entries — and audit records the statement as `error`."""
    s, cr, _ = cluster
    # quiesce, then baseline the accountant with no query in flight
    s.catalog.cluster_runtime = None
    try:
        s.sql(_pad(SQL, 11)).rows()
    finally:
        s.catalog.cluster_runtime = cr
    base_bytes = ACCOUNTANT.snapshot()["process_bytes"]
    cr.kill_worker("w0")
    cr.kill_worker("w1")
    config.set("cluster_exec_timeout_s", 2.0)
    try:
        with pytest.raises(WorkerLostError) as ei:
            s.sql(_pad(SQL, 12)).rows()
    finally:
        config.set("cluster_exec_timeout_s", 30.0)
    assert ei.value.fid >= 0
    assert ei.value.worker_id
    wm = getattr(s.catalog, "workgroups", None)
    slots = sum(wm.running.values()) if wm is not None else 0
    assert slots == 0, f"leaked admission slots: {slots}"
    assert len(REGISTRY.snapshot()) == 0
    leak = ACCOUNTANT.snapshot()["process_bytes"] - base_bytes
    assert leak == 0, f"leaked {leak} accountant bytes"
    AUDIT.flush()
    last = AUDIT.snapshot()[-1]
    assert last["state"] == "error", last
    # catalog intact: a local (non-cluster) query still answers
    s.catalog.cluster_runtime = None
    assert s.sql(_pad(SQL, 13)).rows() == s.sql(_pad(SQL, 14)).rows()
