"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's PseudoCluster strategy (fe test
pseudocluster/PseudoCluster.java:1 — multi-"node" cluster in one JVM): we fake
a multi-chip TPU slice with 8 host CPU devices so sharding/exchange logic is
exercised without hardware.

Environment note: this container preloads an `axon` PJRT plugin (real-TPU
tunnel) via sitecustomize, which force-sets jax_platforms="axon,cpu" — eager
test ops would each take a network round trip (or hang). The conftest flips
the already-imported jax config back to cpu *before any backend initializes*,
and widens the host platform to 8 virtual devices.
"""

import os

# Lock-witness (starrocks_tpu/lockdep.py): run every factory-created lock
# through DebugLock for the whole tier-1 + chaos run, recording the global
# lock-ORDER graph; the session-teardown fixture below fails the run on a
# cycle. Must be set before the FIRST starrocks_tpu import — module-level
# singletons (metrics registry, failpoint registry, query registry) create
# their locks at import time. SR_TPU_LOCK_WITNESS=0 opts out.
os.environ.setdefault("SR_TPU_LOCK_WITNESS", "1")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite is compile-dominated (>9 min
# cold); warm runs reuse compiled programs across processes and rounds.
# Routed through the repo's own config knob so there is one wiring path.
from starrocks_tpu.runtime.config import config as _sr_config  # noqa: E402

if not _sr_config.get("compilation_cache_dir"):
    _sr_config.set(
        "compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     ".xla_cache"),
        force=True,  # not runtime-mutable; the harness sets it pre-backend
    )

# Static verification in warn mode for the whole tier-1 suite: every
# optimized plan and every fresh compile runs the analysis/ passes; findings
# log + count in the profile but never fail a test (strict enforcement lives
# in tools/plan_lint.py and the golden fixtures of test_plan_verifier.py).
# SR_TPU_PLAN_VERIFY_LEVEL overrides (e.g. "off" to time the suite bare).
if "SR_TPU_PLAN_VERIFY_LEVEL" not in os.environ:
    _sr_config.set("plan_verify_level", "warn")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: failpoint/kill/timeout/mem-limit fault-injection scenarios "
        "(tests/test_chaos.py; also run as a dedicated stage in "
        "tools/run_tier1.sh)")


@pytest.fixture(scope="session", autouse=True)
def lock_witness_gate():
    """Teardown gate of the runtime lock-witness: after the whole session
    (647 tests' worth of real interleavings) the global lock-order graph
    must be acyclic — a cycle means two threads CAN deadlock, and the
    report carries both acquisition stacks. Tests that deliberately seed
    inversions use private lockdep.Witness instances, so this graph stays
    clean by construction."""
    from starrocks_tpu import lockdep

    yield
    cycles = lockdep.WITNESS.order_cycles()
    assert not cycles, (
        "runtime lock-witness found lock-order cycle(s):\n"
        + lockdep.WITNESS.render(cycles))


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
