"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's PseudoCluster strategy (fe test
pseudocluster/PseudoCluster.java:1 — multi-"node" cluster in one JVM): we fake
a multi-chip TPU slice with 8 host devices so sharding/exchange logic is
exercised without hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
