"""Differential tests for the scalar/aggregate function breadth wave
(exprs/functions_ext.py + ops/aggregate.py families) vs python/pandas
oracles — the per-function differential tier of SURVEY §4."""

import datetime
import math

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.sql("create table t (i bigint, x double, y double, s varchar, "
          "d date, dt datetime)")
    rows = [
        (1, 0.5, 2.0, "hello world", "2023-01-15", "2023-01-15 10:30:45"),
        (2, -1.25, 4.0, "Abc", "2024-02-29", "2024-02-29 23:59:59"),
        (3, 9.0, -3.0, "", "2021-12-31", "2021-12-31 00:00:01"),
        (4, 100.0, 7.5, "x,y,z", "2020-06-01", "2020-06-01 12:00:00"),
        (5, 2.0, None, "Hello", "2023-11-05", "2023-11-05 06:07:08"),
    ]
    vals = ", ".join(
        "({}, {}, {}, '{}', '{}', '{}')".format(
            i, x, "null" if y is None else y, s_, d, dt)
        for i, x, y, s_, d, dt in rows
    )
    s.sql(f"insert into t values {vals}")
    return s


def rows1(s, q):
    return [r[0] for r in s.sql(q).rows()]


def test_math_family(sess):
    got = sess.sql(
        "select sin(x), cos(x), atan(x), sign(x), truncate(x, 1), "
        "log10(abs(x) + 1), log(2, 8), pmod(i, 3), degrees(x), sqrt(abs(x)) "
        "from t order by i").rows()
    xs = [0.5, -1.25, 9.0, 100.0, 2.0]
    for row, x, i in zip(got, xs, [1, 2, 3, 4, 5]):
        assert row[0] == pytest.approx(math.sin(x))
        assert row[1] == pytest.approx(math.cos(x))
        assert row[2] == pytest.approx(math.atan(x))
        assert row[3] == (0 if x == 0 else math.copysign(1, x))
        assert row[4] == pytest.approx(math.trunc(x * 10) / 10)
        assert row[5] == pytest.approx(math.log10(abs(x) + 1))
        assert row[6] == pytest.approx(3.0)
        assert row[7] == i % 3
        assert row[8] == pytest.approx(math.degrees(x))
        assert row[9] == pytest.approx(math.sqrt(abs(x)))


def test_bit_and_conditional(sess):
    got = sess.sql(
        "select bitand(i, 3), bitor(i, 8), bitxor(i, 1), "
        "bit_shift_left(i, 2), ifnull(y, -1.0), nullif(i, 3) "
        "from t order by i").rows()
    ys = [2.0, 4.0, -3.0, 7.5, -1.0]
    for row, i, y in zip(got, [1, 2, 3, 4, 5], ys):
        assert row[0] == i & 3
        assert row[1] == i | 8
        assert row[2] == i ^ 1
        assert row[3] == i << 2
        assert row[4] == pytest.approx(y)
        assert row[5] == (None if i == 3 else i)


def test_date_family(sess):
    got = sess.sql(
        "select dayofyear(d), weekofyear(d), last_day(d), date_trunc('month', d), "
        "date_trunc('week', d), to_days(d), hour(dt), minute(dt), second(dt), "
        "unix_timestamp(dt), dayname(d), monthname(d), "
        "date_sub(d, 10), months_add(d, 2), timestampdiff(day, d, '2024-06-01') "
        "from t order by i").rows()
    dates = ["2023-01-15", "2024-02-29", "2021-12-31", "2020-06-01", "2023-11-05"]
    dts = ["2023-01-15 10:30:45", "2024-02-29 23:59:59", "2021-12-31 00:00:01",
           "2020-06-01 12:00:00", "2023-11-05 06:07:08"]
    for row, dstr, dtstr in zip(got, dates, dts):
        d = datetime.date.fromisoformat(dstr)
        ts = pd.Timestamp(dstr)
        dt = datetime.datetime.fromisoformat(dtstr)
        assert row[0] == d.timetuple().tm_yday
        assert row[1] == d.isocalendar()[1]
        assert row[2] == str((ts + pd.offsets.MonthEnd(0)).date())
        assert row[3] == dstr[:8] + "01"
        expected_week = d - datetime.timedelta(days=d.weekday())
        assert row[4] == str(expected_week)
        assert row[5] == d.toordinal() + 365  # MySQL TO_DAYS vs proleptic ordinal
        assert row[6] == dt.hour and row[7] == dt.minute or (
            row[6] == dt.hour and row[7] == dt.minute)
        assert row[8] == dt.second
        assert row[9] == int(dt.replace(tzinfo=datetime.timezone.utc).timestamp())
        assert row[10] == d.strftime("%A")
        assert row[11] == d.strftime("%B")
        assert row[12] == str(d - datetime.timedelta(days=10))
        assert row[13] == str((ts + pd.DateOffset(months=2)).date())
        assert row[14] == (datetime.date(2024, 6, 1) - d).days


def test_string_family(sess):
    got = sess.sql(
        "select reverse(s), repeat(s, 2), lpad(s, 5, '*'), left(s, 3), "
        "right(s, 3), ascii(s), locate('l', s), concat_ws('-', s, 'E'), "
        "split_part(s, ',', 2), regexp_extract(s, '([a-z]+)', 1), "
        "md5(s), initcap(s), null_or_empty(s) "
        "from t order by i").rows()
    strs = ["hello world", "Abc", "", "x,y,z", "Hello"]
    import hashlib

    for row, s in zip(got, strs):
        assert row[0] == s[::-1]
        assert row[1] == s * 2
        assert row[2] == (s[:5] if len(s) >= 5 else "*" * (5 - len(s)) + s)
        assert row[3] == s[:3]
        assert row[4] == (s[-3:] if s else "")
        assert row[5] == (ord(s[0]) if s else 0)
        assert row[6] == s.find("l") + 1
        assert row[7] == f"{s}-E"
        parts = s.split(",")
        assert row[8] == (parts[1] if len(parts) >= 2 else "")
        import re as _re

        m = _re.search("([a-z]+)", s)
        assert row[9] == (m.group(1) if m else "")
        assert row[10] == hashlib.md5(s.encode()).hexdigest()
        assert row[11] == s.title()
        assert row[12] == (len(s) == 0)


def test_str_to_date(sess):
    got = rows1(sess, "select str_to_date(s, '%Y-%m-%d') from t order by i")
    assert got == [None, None, None, None, None]
    s2 = Session()
    s2.sql("create table u (s varchar)")
    s2.sql("insert into u values ('2023-07-04'), ('bad')")
    assert rows1(s2, "select str_to_date(s, '%Y-%m-%d') from u order by s") == [
        "2023-07-04", None]


def test_variance_family(sess):
    df = pd.DataFrame({"x": [0.5, -1.25, 9.0, 100.0, 2.0]})
    got = sess.sql(
        "select var_pop(x), var_samp(x), stddev(x), stddev_samp(x), "
        "variance(x), std(x) from t").rows()[0]
    assert got[0] == pytest.approx(df.x.var(ddof=0))
    assert got[1] == pytest.approx(df.x.var(ddof=1))
    assert got[2] == pytest.approx(df.x.std(ddof=0))
    assert got[3] == pytest.approx(df.x.std(ddof=1))
    assert got[4] == pytest.approx(df.x.var(ddof=0))
    assert got[5] == pytest.approx(df.x.std(ddof=0))


def test_variance_grouped_and_distributed():
    s = Session()
    s.sql("create table g (k varchar, v double)")
    s.sql("insert into g values ('a', 1.0), ('a', 2.0), ('a', 4.0), "
          "('b', 10.0), ('b', 10.0), ('c', 3.0)")
    df = pd.DataFrame({
        "k": ["a", "a", "a", "b", "b", "c"],
        "v": [1.0, 2.0, 4.0, 10.0, 10.0, 3.0]})
    want_pop = df.groupby("k").v.var(ddof=0)
    want_samp = df.groupby("k").v.var(ddof=1)
    for shards in (None, 8):
        s2 = Session(s.catalog, dist_shards=shards) if shards else s
        rows = s2.sql("select k, var_pop(v), var_samp(v) from g group by k "
                      "order by k").rows()
        for k, vp, vs in rows:
            assert vp == pytest.approx(want_pop[k])
            if math.isnan(want_samp[k]):
                assert vs is None  # n=1: sample variance undefined
            else:
                assert vs == pytest.approx(want_samp[k])


def test_covar_corr():
    s = Session()
    s.sql("create table c (k varchar, x double, y double)")
    s.sql("insert into c values ('a', 1.0, 2.0), ('a', 2.0, 4.5), "
          "('a', 3.0, 5.9), ('b', 1.0, 9.0), ('b', 2.0, 7.0)")
    df = pd.DataFrame({
        "k": ["a", "a", "a", "b", "b"],
        "x": [1.0, 2.0, 3.0, 1.0, 2.0],
        "y": [2.0, 4.5, 5.9, 9.0, 7.0]})
    rows = s.sql("select k, covar_pop(x, y), covar_samp(x, y), corr(x, y) "
                 "from c group by k order by k").rows()
    for k, cp, cs, cr in rows:
        sub = df[df.k == k]
        assert cp == pytest.approx(np.cov(sub.x, sub.y, ddof=0)[0, 1])
        assert cs == pytest.approx(np.cov(sub.x, sub.y, ddof=1)[0, 1])
        assert cr == pytest.approx(np.corrcoef(sub.x, sub.y)[0, 1])


def test_percentile_median():
    s = Session()
    s.sql("create table p (k varchar, v double)")
    vals = {"a": [1.0, 2.0, 3.0, 4.0, 10.0], "b": [5.0, 7.0]}
    ins = ", ".join(f"('{k}', {v})" for k, vs in vals.items() for v in vs)
    s.sql(f"insert into p values {ins}")
    for shards in (None, 8):
        s2 = Session(s.catalog, dist_shards=shards) if shards else s
        rows = s2.sql(
            "select k, median(v), percentile_cont(v, 0.25), "
            "percentile_disc(v, 0.5) from p group by k order by k").rows()
        for k, med, q25, d50 in rows:
            arr = np.asarray(vals[k])
            assert med == pytest.approx(np.percentile(arr, 50))
            assert q25 == pytest.approx(np.percentile(arr, 25))
            # disc: smallest value with cum_dist >= 0.5
            idx = math.ceil(0.5 * len(arr)) - 1
            assert d50 == pytest.approx(np.sort(arr)[idx])


def test_any_value_bool_aliases(sess):
    # any_value / approx_count_distinct / ndv parse and give sane answers
    got = sess.sql("select any_value(i), approx_count_distinct(s) "
                   "from t").rows()[0]
    assert got[0] == 1
    assert got[1] == 5
    assert rows1(sess, "select ndv(d) from t") == [5]


def test_registry_coverage():
    """The function registry exposes the breadth wave (parity counter)."""
    from starrocks_tpu.exprs.compile import _FUNCTIONS

    must_have = [
        "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "cot",
        "degrees", "radians", "log", "log2", "log10", "sign", "truncate",
        "pmod", "pi", "e", "cbrt", "square",
        "bitand", "bitor", "bitxor", "bitnot", "bit_shift_left",
        "ifnull", "nvl", "nullif",
        "dayofyear", "weekofyear", "hour", "minute", "second", "to_date",
        "last_day", "date_trunc", "date_sub", "adddate", "months_add",
        "years_add", "timestampdiff", "dayname", "monthname", "str_to_date",
        "unix_timestamp", "from_unixtime", "makedate", "to_days", "from_days",
        "reverse", "repeat", "lpad", "rpad", "left", "right", "ascii",
        "concat_ws", "split_part", "locate", "instr", "regexp",
        "regexp_extract", "regexp_replace", "md5", "sha2", "crc32",
        "initcap", "null_or_empty", "space",
    ]
    must_have += [
        # wave 3
        "asinh", "acosh", "atanh", "bit_count", "rand", "pow", "fmod",
        "isnull", "isnotnull", "nvl2", "zeroifnull", "nullifzero",
        "curdate", "now", "current_timestamp", "utc_timestamp", "weekday",
        "dayofweek_iso", "yearweek", "microsecond", "time_to_sec",
        "quarters_add", "milliseconds_add", "microseconds_add",
        "days_diff", "hours_diff", "minutes_diff", "seconds_diff",
        "months_diff", "years_diff", "quarters_diff", "weeks_diff",
        "date_diff", "next_day", "previous_day", "date_floor", "time_slice",
        "add_months", "date_format", "mid", "position", "bit_length",
        "octet_length", "to_base64", "from_base64", "unhex", "sha1",
        "murmur_hash3_32", "fnv_hash", "translate", "url_encode",
        "url_decode", "parse_url", "substring_index", "field", "elt",
        "find_in_set", "soundex", "append_trailing_char_if_absent", "quote",
        "strcmp", "ngram_search", "levenshtein", "get_json_string",
        "get_json_int", "get_json_double", "json_valid", "version",
        "connection_id", "database", "user", "current_user", "typeof",
    ]
    missing = [f for f in must_have if f not in _FUNCTIONS]
    assert not missing, f"registry missing: {missing}"
    assert len(_FUNCTIONS) >= 250


def test_distinct_mixed_with_moment_aggs():
    s = Session()
    s.sql("create table m (k varchar, v double)")
    s.sql("insert into m values ('a', 1.0), ('a', 1.0), ('a', 3.0), "
          "('b', 2.0), ('b', 5.0)")
    rows = s.sql("select k, count(distinct v), stddev_samp(v), var_pop(v) "
                 "from m group by k order by k").rows()
    df = pd.DataFrame({"k": ["a", "a", "a", "b", "b"],
                       "v": [1.0, 1.0, 3.0, 2.0, 5.0]})
    for k, cd, sd, vp in rows:
        sub = df[df.k == k]
        assert cd == sub.v.nunique()
        assert sd == pytest.approx(sub.v.std(ddof=1))
        assert vp == pytest.approx(sub.v.var(ddof=0))


def test_wave3_math_and_null(sess):
    assert rows1(sess, "select bit_count(i) from t order by i") == [
        1, 1, 2, 1, 2]
    r = rows1(sess, "select asinh(x) from t order by i")
    exp = [math.asinh(v) for v in [0.5, -1.25, 9.0, 100.0, 2.0]]
    assert all(abs(a - b) < 1e-12 for a, b in zip(r, exp))
    assert rows1(sess, "select nvl2(y, 1, 0) from t order by i") == [
        1, 1, 1, 1, 0]
    assert rows1(sess, "select zeroifnull(y) from t order by i") == [
        2.0, 4.0, -3.0, 7.5, 0.0]
    assert rows1(sess, "select nullifzero(i - 1) from t order by i") == [
        None, 1, 2, 3, 4]


def test_wave3_dates(sess):
    # pandas oracle for the diff family
    df = pd.DataFrame({
        "d": pd.to_datetime(["2023-01-15", "2024-02-29", "2021-12-31",
                             "2020-06-01", "2023-11-05"]),
    })
    ref = pd.Timestamp("2024-03-15")
    exp_days = [(ref - d).days for d in df.d]
    assert rows1(
        sess,
        "select days_diff(to_date('2024-03-15'), d) from t order by i",
    ) == exp_days
    assert rows1(sess, "select weekday(d) from t order by i") == [
        int(d.weekday()) for d in df.d]
    assert rows1(sess, "select date_format(d, '%Y-%m') from t order by i"
                 ) == [d.strftime("%Y-%m") for d in df.d]
    assert rows1(sess, "select date_diff(month, to_date('2024-03-15'), d) "
                 "from t order by i") == [14, 0, 26, 45, 4]


def test_wave3_strings(sess):
    assert rows1(sess, "select to_base64(s) from t where i = 2") == ["QWJj"]
    assert rows1(sess, "select from_base64(to_base64(s)) from t order by i"
                 ) == ["hello world", "Abc", "", "x,y,z", "Hello"]
    assert rows1(sess, "select substring_index(s, ',', 2) from t where i = 4"
                 ) == ["x,y"]
    assert rows1(sess, "select soundex('Robert')") == ["R163"]
    assert rows1(sess, "select levenshtein(s, 'hello') from t order by i"
                 ) == [6, 5, 5, 5, 1]
    assert rows1(sess, "select field(s, 'Abc', 'Hello') from t order by i"
                 ) == [0, 1, 0, 0, 2]
    assert rows1(sess, "select strcmp(s, 'Hello') from t order by i") == [
        1, -1, -1, 1, 0]


def test_wave3_json(sess):
    s2 = Session()
    s2.sql("create table j (js varchar)")
    s2.sql("""insert into j values ('{"a": 1, "b": {"c": [10, 20]}}'),
           ('not json'), ('{"a": 2.5}')""")
    assert [r[0] for r in s2.sql(
        "select get_json_int(js, '$.a') from j").rows()] == [1, 0, 2]
    assert [r[0] for r in s2.sql(
        "select get_json_string(js, '$.b.c[1]') from j").rows()] == [
        "20", "", ""]
    assert [r[0] for r in s2.sql(
        "select json_valid(js) from j").rows()] == [True, False, True]


def test_group_concat_and_friends(sess):
    s2 = Session()
    s2.sql("create table g (k varchar, v varchar, n bigint)")
    s2.sql("insert into g values ('a','x',1),('a','y',2),('b','z',3),"
           "('b','z',4),('a',null,5)")
    r = s2.sql("select k, group_concat(v) gc, count(*) c from g "
               "group by k order by k").rows()
    assert r == [("a", "x,y", 3), ("b", "z,z", 2)]
    r = s2.sql("select k, group_concat(distinct v, '-') from g "
               "group by k order by k").rows()
    assert r == [("a", "x-y"), ("b", "z")]
    r = s2.sql("select any_value(n), approx_count_distinct(v) from g").rows()
    assert r == [(1, 3)]
    assert s2.sql("select ndv(k) from g").rows() == [(2,)]
    r = s2.sql("select percentile_approx(n, 0.5) from g").rows()
    assert r == [(3.0,)]


def test_group_concat_guard_through_renames():
    """References to the concat column through renames/subquery aliases must
    raise (not silently read the placeholder)."""
    s2 = Session()
    s2.sql("create table gg (k varchar, v varchar)")
    s2.sql("insert into gg values ('a','x'),('a','y')")
    with pytest.raises(Exception, match="group_concat"):
        s2.sql("select gc from (select k, group_concat(v) gc from gg "
               "group by k) x where gc = 'x,y'")
    # plain rename passthrough is fine
    r = s2.sql("select gc as g from (select k, group_concat(v) gc from gg "
               "group by k) x").rows()
    assert r == [("x,y",)]


def test_wave3_fix_regressions(sess):
    # time_slice/date_slice unit-first arg order
    r = rows1(sess, "select time_slice(month, d) from t where i = 1")
    assert str(r[0]) == "2023-01-01"
    # yearweek at an ISO year boundary: 2021-01-01 is ISO week 53 of 2020
    assert rows1(sess, "select yearweek(to_date('2021-01-01'))") == [202053]
    # two rand() occurrences must not correlate
    r = sess.sql("select rand() r1, rand() r2 from t").rows()
    assert any(abs(a - b) > 1e-12 for a, b in r)
    # GROUP BY alias is case-insensitive
    r = sess.sql("select i + 0 as Total from t group by Total "
                 "order by Total").rows()
    assert [x[0] for x in r] == [1, 2, 3, 4, 5]
    # date_format with time tokens on DATETIME refuses loudly
    with pytest.raises(Exception, match="time tokens"):
        sess.sql("select date_format(dt, '%H:%i') from t")


def test_json_arrow_operator(sess):
    # col -> '$.path' is JSON extraction (the reference arrow operator),
    # NOT a lambda — lambdas only parse as higher-order function arguments
    s2 = Session()
    s2.sql("create table ja (js varchar)")
    s2.sql("""insert into ja values ('{"a": {"b": "x"}}'), ('{"a": 2}')""")
    assert [r[0] for r in s2.sql(
        "select js -> '$.a.b' from ja").rows()] == ["x", ""]
    # a non-string rhs outside a higher-order call is a clear parse error
    import pytest as _pytest

    from starrocks_tpu.sql.parser import ParseError

    with _pytest.raises(ParseError, match="JSON path"):
        s2.sql("select js -> 1 from ja")
