"""Next-generation join engine tests (round 13).

Three families:

1. skew-aware hybrid hash join (runtime/batched.py hybrid_partitions /
   execute_hybrid_join): Zipfian/heavy-hitter key distributions vs the
   pandas oracle across INNER/LEFT/SEMI/ANTI, the one-hot-key-never-
   forces-a-full-spill invariant, grace A/B equality, and dict-encoded
   key fallback (string keys can't host-partition — the plan must keep
   the in-HBM path and stay dictionary-aligned);
2. Free-Join-style multiway fusion (sql/physical.multiway_join_chain /
   emit_multiway): star + snowflake shapes vs the oracle, off-A/B
   equality, fallback on non-unique builds, and the plan checker's
   independent re-verification of fused invariants;
3. the Pallas open-addressing hash-table build+probe kernel pair
   (ops/pallas_kernels.hash_build_pallas / hash_probe_pallas) standalone
   and through SQL via SET join_probe_strategy='pallas'.
"""

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime import batched
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


def _counters(session) -> dict:
    out = {}

    def walk(p):
        out.update({k: v for k, (v, _) in p.counters.items()})
        for c in p.children:
            walk(c)

    walk(session.last_profile)
    return out


def _zipf_keys(rng, n, domain, a=1.1):
    """Zipfian keys clipped into [0, domain) — a realistic heavy tail."""
    z = rng.zipf(a, n)
    return np.minimum(z - 1, domain - 1).astype(np.int64)


def _skew_catalog(rng, n_probe=60_000, n_build=24_000, hot_frac=0.5,
                  domain=3_000, probe_domain=None, build_nulls=False):
    """Probe + skewed build: one heavy-hitter key owns `hot_frac` of the
    build side. The build is the SMALLER relation so the optimizer keeps
    it on the build (right) side of the join."""
    bk = rng.integers(0, domain, n_build)
    bk[: int(n_build * hot_frac)] = 7
    rng.shuffle(bk)
    pk = rng.integers(0, probe_domain or int(domain * 1.2), n_probe)
    cat = Catalog()
    cat.register("probe", HostTable.from_pydict({
        "k": list(pk.astype(int)),
        "v": list(rng.integers(0, 100, n_probe).astype(int)),
    }))
    bcols = {"k": list(bk.astype(int)),
             "w": list(rng.integers(0, 100, n_build).astype(int))}
    bt = HostTable.from_pydict(bcols)
    if build_nulls:
        bt.valids["k"] = np.arange(n_build) % 7 != 0
    cat.register("build", bt)
    dp = pd.DataFrame({"k": pk, "v": cat.get_table("probe").table.arrays["v"]})
    db = cat.get_table("build").table.to_pandas()
    return cat, dp, db


@pytest.fixture
def spill_knobs():
    old_t = config.get("batch_rows_threshold")
    old_b = config.get("spill_batch_rows")
    config.set("batch_rows_threshold", 8_192)
    config.set("spill_batch_rows", 8_192)
    yield
    config.set("batch_rows_threshold", old_t)
    config.set("spill_batch_rows", old_b)
    config.set("join_hybrid_strategy", "auto")


# --- 1. skew-aware hybrid hash join ------------------------------------------


def test_hybrid_inner_skewed_vs_oracle_and_grace(spill_knobs):
    rng = np.random.default_rng(11)
    cat, dp, db = _skew_catalog(rng)
    s = Session(cat)
    q = ("SELECT sum(v + w) sv, count(*) c FROM probe, build "
         "WHERE probe.k = build.k")
    got = s.sql(q).rows()
    cs = _counters(s)
    assert cs.get("join_skew_keys", 0) >= 1, cs
    assert "join_spilled_partitions" in cs
    m = dp.merge(db, on="k")
    assert [(int(a), int(b)) for a, b in got] == [
        (int((m.v + m.w).sum()), len(m))]
    # legacy grace agrees bit-for-bit
    config.set("join_hybrid_strategy", "grace")
    assert s.sql(q).rows() == got
    assert batched.SPILL_PARTS_LIVE.value == 0


def test_hybrid_one_hot_key_no_full_spill(spill_knobs):
    """THE skew invariant: with one heavy-hitter key and a cold remainder
    that fits the batch budget, the hybrid join spills NOTHING — the hot
    key rides the broadcast lane and the cold build stays resident. The
    legacy grace path partitioned (and streamed) everything."""
    rng = np.random.default_rng(13)
    # cold build = 6k rows (< 8192 budget); hot key owns another 18k rows
    cat, dp, db = _skew_catalog(rng, n_build=24_000, hot_frac=0.75)
    s = Session(cat)
    q = "SELECT count(*) c, sum(w) sw FROM probe, build WHERE probe.k = build.k"
    got = s.sql(q).rows()
    cs = _counters(s)
    assert cs.get("join_skew_keys", 0) >= 1, cs
    assert cs.get("join_spilled_partitions", -1) == 0, cs
    assert cs.get("join_resident_partitions", 0) >= 1, cs
    m = dp.merge(db, on="k")
    assert [(int(a), int(b)) for a, b in got] == [(len(m), int(m.w.sum()))]
    assert batched.SPILL_PARTS_LIVE.value == 0


def test_hybrid_left_outer_zipf_vs_oracle(spill_knobs):
    """Zipfian PROBE keys against a near-unique build (the FK->dim shape:
    probe skew is absorbed by probe-slice streaming; build dup factor <= 2
    keeps the join output bounded at ~2x probe rows)."""
    rng = np.random.default_rng(17)
    n, m = 30_000, 15_000
    pk = _zipf_keys(rng, n, 20_000)
    bk = np.concatenate([np.arange(10_000), rng.integers(0, 20_000, m - 10_000)])
    cat = Catalog()
    cat.register("probe", HostTable.from_pydict({
        "k": list(pk.astype(int)), "v": list(range(n))}))
    cat.register("build", HostTable.from_pydict({
        "k": list(bk.astype(int)),
        "w": list(rng.integers(0, 50, m).astype(int))}))
    s = Session(cat)
    q = ("SELECT count(*) c, count(w) cw, sum(v) sv, sum(w) sw "
         "FROM probe LEFT JOIN build ON probe.k = build.k")
    got = s.sql(q).rows()
    dfp = pd.DataFrame({"k": pk, "v": np.arange(n)})
    dfb = cat.get_table("build").table.to_pandas()
    mg = dfp.merge(dfb, on="k", how="left")
    exp = [(len(mg), int(mg.w.notna().sum()), int(mg.v.sum()),
            int(mg.w.sum()))]
    assert [(int(a), int(b), int(c), int(d)) for a, b, c, d in got] == exp
    config.set("join_hybrid_strategy", "grace")
    assert s.sql(q).rows() == got


def test_hybrid_semi_anti_vs_oracle(spill_knobs):
    rng = np.random.default_rng(19)
    cat, dp, db = _skew_catalog(rng, n_probe=40_000, n_build=20_000)
    s = Session(cat)
    semi = ("SELECT count(*) c, sum(v) sv FROM probe WHERE k IN "
            "(SELECT k FROM build)")
    anti = ("SELECT count(*) c, sum(v) sv FROM probe WHERE k NOT IN "
            "(SELECT k FROM build) AND k IS NOT NULL")
    got_semi = s.sql(semi).rows()
    got_anti = s.sql(anti).rows()
    member = dp.k.isin(set(db.k))
    exp_semi = [(int(member.sum()), int(dp.v[member].sum()))]
    exp_anti = [(int((~member).sum()), int(dp.v[~member].sum()))]
    assert [(int(a), int(b)) for a, b in got_semi] == exp_semi
    assert [(int(a), int(b)) for a, b in got_anti] == exp_anti
    config.set("join_hybrid_strategy", "grace")
    assert s.sql(semi).rows() == got_semi
    assert s.sql(anti).rows() == got_anti


def test_hybrid_null_build_keys(spill_knobs):
    """NULL join keys never match (SQL equality): routing NULL-carrying
    rows through the lanes must not invent matches."""
    rng = np.random.default_rng(23)
    cat, dp, db = _skew_catalog(rng, n_probe=30_000, n_build=15_000,
                                build_nulls=True)
    s = Session(cat)
    q = "SELECT count(*) c FROM probe, build WHERE probe.k = build.k"
    got = s.sql(q).rows()
    bk = cat.get_table("build").table
    live = pd.DataFrame({"k": np.asarray(bk.arrays["k"])[bk.valids["k"]]})
    exp = [(len(dp.merge(live, on="k")),)]
    assert [(int(a),) for (a,) in got] == exp


def test_hybrid_string_keys_fall_back_dict_aligned(spill_knobs):
    """Dict-encoded string keys can't host-partition (the hybrid/grace
    matcher requires int64-able keys): the plan keeps the in-HBM join,
    whose pack_key_pair aligns the two sides' dictionaries — equal strings
    must match even though their per-table codes differ."""
    rng = np.random.default_rng(29)
    words1 = [f"w{i:04d}" for i in range(400)]
    words2 = [f"w{i:04d}" for i in range(200, 600)]  # shifted code space
    n, m = 30_000, 12_000
    cat = Catalog()
    cat.register("probe", HostTable.from_pydict({
        "k": [words1[i] for i in rng.integers(0, 400, n)],
        "v": list(range(n))}))
    cat.register("build", HostTable.from_pydict({
        "k": [words2[i] for i in rng.integers(0, 400, m)],
        "w": list(rng.integers(0, 9, m).astype(int))}))
    s = Session(cat)
    q = "SELECT count(*) c, sum(v) sv FROM probe, build WHERE probe.k = build.k"
    got = s.sql(q).rows()
    dp = cat.get_table("probe").table.to_pandas()
    db = cat.get_table("build").table.to_pandas()
    mg = dp.merge(db, on="k")
    assert [(int(a), int(b)) for a, b in got] == [
        (len(mg), int(mg.v.sum()))]


# --- 2. Free-Join multiway fusion --------------------------------------------


def _star_catalog(rng, n=25_000):
    cat = Catalog()
    cat.register("fact", HostTable.from_pydict({
        "fk1": list(rng.integers(0, 100, n).astype(int)),
        "fk2": list(rng.integers(0, 50, n).astype(int)),
        "v": list(rng.integers(0, 1000, n).astype(int)),
    }))
    cat.register("d1", HostTable.from_pydict({
        "k1": list(range(100)),
        "a": list(rng.integers(0, 10, 100).astype(int)),
        "snow": list(rng.integers(0, 30, 100).astype(int)),
    }), unique_keys=[("k1",)])
    cat.register("d2", HostTable.from_pydict({
        "k2": list(range(50)),
        "b": list(rng.integers(0, 10, 50).astype(int)),
    }), unique_keys=[("k2",)])
    cat.register("d3", HostTable.from_pydict({
        "k3": list(range(30)),
        "c": list(rng.integers(0, 5, 30).astype(int)),
    }), unique_keys=[("k3",)])
    return cat


STAR_Q = ("SELECT d1.a, sum(v) sv, count(*) c FROM fact, d1, d2, d3 "
          "WHERE fact.fk1 = d1.k1 AND fact.fk2 = d2.k2 AND d1.snow = d3.k3 "
          "AND d2.b < 8 AND d3.c < 4 GROUP BY d1.a ORDER BY d1.a")


def _star_oracle(cat):
    f = cat.get_table("fact").table.to_pandas()
    t1 = cat.get_table("d1").table.to_pandas()
    t2 = cat.get_table("d2").table.to_pandas()
    t3 = cat.get_table("d3").table.to_pandas()
    m = (f.merge(t1, left_on="fk1", right_on="k1")
          .merge(t2, left_on="fk2", right_on="k2")
          .merge(t3, left_on="snow", right_on="k3"))
    m = m[(m.b < 8) & (m.c < 4)]
    g = m.groupby("a").agg(sv=("v", "sum"), c=("v", "size")).reset_index()
    return [(int(r.a), int(r.sv), int(r.c))
            for r in g.sort_values("a").itertuples()]


def test_multiway_star_snowflake_vs_oracle_and_off():
    rng = np.random.default_rng(31)
    cat = _star_catalog(rng)
    s = Session(cat)
    got = s.sql(STAR_Q).rows()
    cs = _counters(s)
    # 3 fused levels: two star arms + one snowflake arm (d1.snow -> d3)
    assert cs.get("join_multiway_hits") == 3, cs
    assert [(int(a), int(sv), int(c)) for a, sv, c in got] == _star_oracle(cat)
    s.sql("SET join_multiway_strategy = 'off'")
    try:
        assert s.sql(STAR_Q).rows() == got
        assert "join_multiway_hits" not in _counters(s)
    finally:
        config.set("join_multiway_strategy", "auto")


def test_multiway_requires_unique_builds():
    """A dimension with DUPLICATE keys is not LUT-eligible: the region
    must fall back to binary joins (which expand duplicates correctly)."""
    rng = np.random.default_rng(37)
    n = 8_000
    cat = Catalog()
    cat.register("fact", HostTable.from_pydict({
        "fk1": list(rng.integers(0, 40, n).astype(int)),
        "fk2": list(rng.integers(0, 20, n).astype(int)),
        "v": list(rng.integers(0, 100, n).astype(int))}))
    # d1 declared unique; dup carries DUPLICATE join keys (2 rows per key)
    cat.register("d1", HostTable.from_pydict({
        "k1": list(range(40)),
        "a": list(rng.integers(0, 5, 40).astype(int))}),
        unique_keys=[("k1",)])
    cat.register("dup", HostTable.from_pydict({
        "k2": [i % 20 for i in range(40)],
        "b": list(rng.integers(0, 5, 40).astype(int))}))
    s = Session(cat)
    q = ("SELECT sum(v) sv, count(*) c, sum(b) sb FROM fact, d1, dup "
         "WHERE fact.fk1 = d1.k1 AND fact.fk2 = dup.k2")
    got = s.sql(q).rows()
    assert "join_multiway_hits" not in _counters(s)
    f = cat.get_table("fact").table.to_pandas()
    t1 = cat.get_table("d1").table.to_pandas()
    t2 = cat.get_table("dup").table.to_pandas()
    m = (f.merge(t1, left_on="fk1", right_on="k1")
          .merge(t2, left_on="fk2", right_on="k2"))
    assert [(int(a), int(b), int(c)) for a, b, c in got] == [
        (int(m.v.sum()), len(m), int(m.b.sum()))]


def test_multiway_plan_checker_flags_relaxed_eligibility(monkeypatch):
    """check_multiway re-verifies fused invariants INDEPENDENTLY: relax
    the compiler-side eligibility (drop the uniqueness proof) and the
    checker must flag the non-unique build the fusion would mis-join."""
    from starrocks_tpu.analysis import plan_check
    from starrocks_tpu.sql import physical
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse
    from starrocks_tpu.sql.analyzer import Analyzer

    rng = np.random.default_rng(41)
    n = 4_000
    cat = Catalog()
    cat.register("fact", HostTable.from_pydict({
        "fk1": list(rng.integers(0, 40, n).astype(int)),
        "fk2": list(rng.integers(0, 20, n).astype(int)),
        "v": list(rng.integers(0, 100, n).astype(int))}))
    cat.register("d1", HostTable.from_pydict({
        "k1": list(range(40)), "a": list(range(40))}),
        unique_keys=[("k1",)])
    cat.register("dup", HostTable.from_pydict({
        "k2": [i % 20 for i in range(40)], "b": list(range(40))}))

    orig = physical.multiway_level

    def relaxed(p, catalog):
        lev = orig(p, catalog)
        if lev is not None:
            return lev
        # the buggy relaxation under test: accept ANY single-key inner
        # join with a bounded range, skipping the uniqueness proof
        from starrocks_tpu.exprs.ir import Col
        from starrocks_tpu.sql.physical import (
            LUT_JOIN_MAX_RANGE, dense_rf_range, join_equi_keys,
        )
        if not isinstance(p, physical.LJoin) or p.kind != "inner" \
                or p.condition is None:
            return None
        pks, bks, residual = join_equi_keys(p)
        if len(pks) != 1 or residual or not all(
                isinstance(k, Col) for k in (pks[0], bks[0])):
            return None
        rng_ = dense_rf_range(p.left, p.right, pks, bks, catalog,
                              max_range=LUT_JOIN_MAX_RANGE)
        return None if rng_ is None else (pks[0], bks[0], *rng_)

    monkeypatch.setattr(physical, "multiway_level", relaxed)
    q = ("SELECT sum(v) FROM fact, d1, dup "
         "WHERE fact.fk1 = d1.k1 AND fact.fk2 = dup.k2")
    plan = optimize(Analyzer(cat).analyze(parse(q)), cat)
    findings = plan_check.check_multiway(plan, cat)
    assert any("not provably unique" in f.message for f in findings), findings


# --- 3. Pallas open-addressing hash table ------------------------------------


def test_hash_kernels_parity_standalone():
    import jax.numpy as jnp

    from starrocks_tpu.ops.pallas_kernels import (
        _EMPTY, hash_build_pallas, hash_probe_pallas,
    )

    rng = np.random.RandomState(7)
    keys = rng.permutation(1 << 20)[:900].astype(np.int64)
    keys[3] = _EMPTY    # NULL/dead build rows carry the sentinel
    keys[77] = _EMPTY
    table = 2048
    tk, tr = hash_build_pallas(jnp.asarray(keys), table, interpret=True)
    probe = np.concatenate([
        keys, rng.randint(-100, 1 << 20, 3196)]).astype(np.int64)[:4096]
    got = np.asarray(hash_probe_pallas(tk, tr, jnp.asarray(probe),
                                       block=1024, interpret=True))
    oracle = {int(k): i for i, k in enumerate(keys) if k != _EMPTY}
    exp = np.array([oracle.get(int(p), -1) for p in probe], np.int32)
    assert (got == exp).all()


def test_hash_kernels_dense_collisions():
    """Adjacent keys hash to clustered slots — the linear-probing worst
    case; every key must still place and probe back to its own row."""
    import jax.numpy as jnp

    from starrocks_tpu.ops.pallas_kernels import (
        hash_build_pallas, hash_probe_pallas,
    )

    keys = np.arange(1000, dtype=np.int64)
    tk, tr = hash_build_pallas(jnp.asarray(keys), 2048, interpret=True)
    got = np.asarray(hash_probe_pallas(
        tk, tr, jnp.asarray(np.arange(2000, dtype=np.int64)),
        block=1000, interpret=True))
    assert (got[:1000] == np.arange(1000)).all()
    assert (got[1000:] == -1).all()


@pytest.mark.parametrize("strategy", ["pallas", "pallas_sorted"])
def test_join_probe_strategies_full_sql(strategy):
    """Both kernel strategies answer INNER/LEFT/SEMI/ANTI unique joins
    identically to the default searchsorted path."""
    rng = np.random.default_rng(43)
    n = 6_000
    cat = Catalog()
    cat.register("f", HostTable.from_pydict({
        "k": list(rng.integers(0, 900, n).astype(int)),
        "v": list(rng.integers(0, 50, n).astype(int))}))
    cat.register("d", HostTable.from_pydict({
        # sparse wide-range keys defeat the LUT path, forcing the
        # sorted/hash unique-join kernels under test
        "k": list((np.arange(600) * 1_000_003 % (1 << 40)).astype(int)),
        "w": list(rng.integers(0, 5, 600).astype(int))}),
        unique_keys=[("k",)])
    # probe keys must overlap the build's sparse domain for real matches
    f = cat.get_table("f").table
    f.arrays["k"] = np.asarray(
        (rng.integers(0, 1200, n) * 1_000_003) % (1 << 40)).astype(np.int64)
    s = Session(cat)
    queries = [
        "SELECT count(*) c, sum(v) sv, sum(w) sw FROM f, d WHERE f.k = d.k",
        "SELECT count(*) c, count(w) cw FROM f LEFT JOIN d ON f.k = d.k",
        "SELECT count(*) c FROM f WHERE k IN (SELECT k FROM d)",
        "SELECT count(*) c FROM f WHERE k NOT IN (SELECT k FROM d)",
    ]
    base = [s.sql(q).rows() for q in queries]
    s.sql(f"SET join_probe_strategy = '{strategy}'")
    try:
        assert [s.sql(q).rows() for q in queries] == base
    finally:
        config.set("join_probe_strategy", "auto")
