"""Lambda (higher-order), MAP and STRUCT builtins — differential tests vs
hand-computed python semantics (exprs/functions_lambda.py; reference:
be/src/exprs/lambda_function.h + map_column.h)."""

import pytest

from starrocks_tpu.runtime.session import Session


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.sql("create table lt (g int, arr array<int>, x int)")
    s.sql("insert into lt values (1, array(1,2,3), 10), (2, array(5), 100),"
          " (3, array(), 7), (4, null, 1)")
    s.sql("create table st (g int, names array<varchar>, nums array<int>)")
    s.sql("insert into st values "
          "(1, array('a','b','c'), array(3,1,2)), "
          "(2, array('z'), array(9))")
    return s


def _py_rows(rows):
    return [tuple(r) for r in rows]


def test_array_map_with_capture(sess):
    got = sess.sql(
        "select g, array_map(e -> e * 2 + x, arr) m from lt order by g"
    ).rows()
    assert got == [(1, [12, 14, 16]), (2, [110]), (3, []), (4, None)]
    # both argument orders parse (reference accepts either)
    got2 = sess.sql(
        "select array_map(arr, e -> e + 1) m from lt where g = 1").rows()
    assert got2 == [([2, 3, 4],)]


def test_array_map_two_arrays_and_strings(sess):
    got = sess.sql(
        "select array_map((a, b) -> a * b, arr, arr) m from lt order by g"
    ).rows()
    assert got == [([1, 4, 9],), ([25],), ([],), (None,)]
    # string LUT ops work inside lambda bodies (flattened-lane design)
    got2 = sess.sql(
        "select array_map(s -> length(s) + g, names) m from st order by g"
    ).rows()
    assert got2 == [([2, 2, 2],), ([3],)]


def test_array_filter_and_matches(sess):
    assert sess.sql(
        "select g, array_filter(arr, e -> e % 2 = 1) f from lt order by g"
    ).rows() == [(1, [1, 3]), (2, [5]), (3, []), (4, None)]
    assert sess.sql(
        "select g, all_match(arr, e -> e > 0) a, any_match(arr, e -> e > 2) y"
        " from lt order by g"
    ).rows() == [(1, True, True), (2, True, True), (3, True, False),
                 (4, None, None)]


def test_array_sortby(sess):
    assert sess.sql(
        "select array_sortby(names, s -> length(s)) s from st where g = 1"
    ).rows() == [(["a", "b", "c"],)]
    assert sess.sql(
        "select array_sortby(arr, e -> -e) s from lt where g = 1"
    ).rows() == [([3, 2, 1],)]
    # sort one array by ANOTHER's values via a two-param lambda over zip
    assert sess.sql(
        "select array_sortby((s, n) -> n, names, nums) s "
        "from st where g = 1"
    ).rows() == [(["b", "c", "a"],)]


def test_map_family(sess):
    q = "map_from_arrays(arr, array_map(e -> e * 10, arr))"
    assert sess.sql(
        f"select g, map_size({q}) z from lt where g <= 3 order by g"
    ).rows() == [(1, 3), (2, 1), (3, 0)]
    assert sess.sql(
        f"select element_at({q}, 2) v, map_contains_key({q}, 5) c "
        "from lt where g <= 2 order by g"
    ).rows() == [(20, False), (None, True)]
    assert sess.sql(
        f"select map_keys({q}) k, map_values({q}) v from lt where g = 1"
    ).rows() == [([1, 2, 3], [10, 20, 30])]
    assert sess.sql(
        f"select cardinality({q}) c from lt where g = 1"
    ).rows() == [(3,)]


def test_lambda_string_literal_body(sess):
    # `x -> 'abc'` inside a call argument list is a lambda with a constant
    # string body, NOT JSON extraction — only '$'-prefixed path literals
    # take the arrow route (get_json_string)
    got = sess.sql(
        "select g, array_map(e -> 'k', arr) m from lt where g <= 2 order by g"
    ).rows()
    assert got == [(1, ["k", "k", "k"]), (2, ["k"])]


def test_map_lambdas(sess):
    q = "map_from_arrays(arr, array_map(e -> e * 10, arr))"
    assert sess.sql(
        f"select map_values(transform_values({q}, (k, v) -> v + k)) tv "
        "from lt where g = 1"
    ).rows() == [([11, 22, 33],)]
    assert sess.sql(
        f"select map_keys(transform_keys({q}, (k, v) -> k * 100)) tk "
        "from lt where g = 1"
    ).rows() == [([100, 200, 300],)]
    assert sess.sql(
        f"select map_keys(map_filter({q}, (k, v) -> v >= 20)) mk "
        "from lt where g = 1"
    ).rows() == [([2, 3],)]


def test_map_concat_last_wins(sess):
    m = ("map_concat(map_from_arrays(array(1, 2), array(10, 20)), "
         "map_from_arrays(array(2, 3), array(200, 300)))")
    assert sess.sql(
        f"select element_at({m}, 2) v, map_size({m}) z from lt where g = 1"
    ).rows() == [(200, 3)]
    # dedup is consistent across every introspection surface
    assert sess.sql(
        f"select map_keys({m}) k, map_values({m}) v from lt where g = 1"
    ).rows() == [([1, 2, 3], [10, 200, 300])]


def test_grouped_lambda_and_nested(sess):
    # lambdas in grouped projections (the _build_aggregate replace() path):
    # the lambda's captured refs resolve through group keys
    assert sess.sql(
        "select g, array_map(e -> e + g, array(g, g * 2)) m, count(*) c "
        "from lt where g <= 2 group by g order by g"
    ).rows() == [(1, [2, 3], 1), (2, [4, 6], 1)]
    assert sess.sql(
        "select g from lt where g <= 3 group by g "
        "having any_match(array(g, g * 2), e -> e > 3) order by g"
    ).rows() == [(2,), (3,)]
    # nested lambda capturing the outer param AND an outer array column
    assert sess.sql(
        "select array_map(e -> cardinality(array_filter(arr, f -> f > e)),"
        " arr) m from lt where g = 1"
    ).rows() == [([2, 1, 0],)]


def test_multi_array_zip_semantics(sess):
    # DEVIATION (documented in eval_lambda): mismatched per-row lengths
    # zip to the SHORTER length instead of raising like the reference
    sess.sql("create table zz (a array<int>, b array<int>)")
    sess.sql("insert into zz values (array(1,2,3), array(7))")
    assert sess.sql(
        "select array_map((x, y) -> x + y, a, b) m from zz"
    ).rows() == [([8],)]
    with pytest.raises(Exception, match="params"):
        sess.sql("select array_filter(a, b, x -> x > 1) m from zz")


def test_struct_family(sess):
    assert sess.sql(
        "select named_struct('a', x, 'b', g * 2).a sa, "
        "named_struct('a', x, 'b', g * 2).b sb from lt where g = 2"
    ).rows() == [(100, 4)]
    assert sess.sql(
        "select struct_field(row(x, g), 'col2') c2 from lt where g = 1"
    ).rows() == [(1,)]
    with pytest.raises(Exception, match="no struct field"):
        sess.sql("select named_struct('a', 1).zz from lt where g = 1")


def test_lambda_in_where_and_agg(sess):
    # lambdas compose with the rest of the engine: filters and aggregates
    assert sess.sql(
        "select g from lt where any_match(arr, e -> e >= 5) order by g"
    ).rows() == [(2,)]
    assert sess.sql(
        "select sum(cardinality(array_filter(arr, e -> e > 1))) s "
        "from lt where g <= 3"
    ).rows() == [(3,)]


def test_lambda_shadowing_and_nesting(sess):
    # the param shadows a real column name (x); inner lambda shadows outer
    assert sess.sql(
        "select array_map(x -> x + 1, arr) m from lt where g = 1"
    ).rows() == [([2, 3, 4],)]


def test_map_duplicate_keys_last_wins(sess):
    # all map builtins agree on last-occurrence-wins: maps dedupe at
    # construction, and element_at picks the LAST hit either way
    got = sess.sql(
        "select element_at(map_from_arrays(array(1, 1), array(10, 20)), 1) v,"
        " map_size(map_from_arrays(array(1, 1), array(10, 20))) z "
        "from lt where g = 1").rows()
    assert got == [(20, 1)]


def test_element_at_column_key(sess):
    # per-row COLUMN key: each row looks up its own g (1..4) in {g: g*10}
    got = sess.sql(
        "select g, element_at(map_from_arrays(array(g, 7), "
        "array(g * 10, 70)), g) v from lt order by g").rows()
    assert got == [(1, 10), (2, 20), (3, 30), (4, 40)]
    # a missing per-row key is NULL, not a broadcast artifact
    got2 = sess.sql(
        "select g, element_at(map_from_arrays(array(7), array(70)), g) v "
        "from lt order by g").rows()
    assert got2 == [(1, None), (2, None), (3, None), (4, None)]
