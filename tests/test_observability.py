"""Profile / config / metrics / failpoint tests (reference analog:
RuntimeProfile + configbase + metrics + failpoint behaviors, SURVEY §5)."""

import pytest

from starrocks_tpu.runtime import failpoint
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.metrics import QUERIES_TOTAL, metrics
from starrocks_tpu.runtime.profile import RuntimeProfile
from starrocks_tpu.runtime.session import Session


def _sess():
    s = Session()
    s.sql("create table t (a int, b double)")
    s.sql("insert into t values (1, 2.0), (2, 3.0), (1, 4.0)")
    return s


def test_profile_collected():
    s = _sess()
    r = s.sql("select a, sum(b) from t group by a")
    prof = r.profile
    assert prof is not None
    assert "analyze" in prof.counters
    assert prof.find("attempt_0") is not None
    rendered = prof.render()
    assert "compile_and_run" in rendered


def test_explain_analyze():
    s = _sess()
    out = s.sql("explain analyze select a, sum(b) s from t group by a")
    assert "Agg[" in out and "compile_and_run" in out


def test_config_registry():
    assert config.get("default_agg_groups") == 1024
    config.set("max_recompiles", 4)
    assert config.get("max_recompiles") == 4
    config.set("max_recompiles", 6)
    with pytest.raises(KeyError):
        config.set("no_such_option", 1)
    with pytest.raises(PermissionError):
        config.set("chunk_align", 512)
    items = dict((n, v) for n, v, *_ in config.items())
    assert "enable_zonemap_pruning" in items


def test_metrics_prometheus():
    before = QUERIES_TOTAL.value
    s = _sess()
    s.sql("select count(*) c from t group by a > 0")
    assert QUERIES_TOTAL.value > before
    text = metrics.render_prometheus()
    assert "# TYPE sr_tpu_queries_total counter" in text


def test_failpoint_injection():
    s = _sess()
    with failpoint.scoped("executor::before_run"):
        with pytest.raises(failpoint.FailPointError):
            s.sql("select count(*) c from t group by a > 0")
    # disarmed: works again
    r = s.sql("select count(*) c from t group by a > 0")
    assert r.rows() == [(3,)]
    assert failpoint._registry.hits("executor::before_run") >= 2


def test_failpoint_action_and_times():
    calls = []
    with failpoint.scoped("executor::before_run", action=lambda: calls.append(1), times=1):
        s = _sess()
        s.sql("select count(*) c from t group by a > 0")
        s.sql("select count(*) c from t group by a > 0")
    assert calls == [1]  # times=1 limited the injection


def test_program_cache_and_cap_adoption():
    s = _sess()
    q = "select a, sum(b) s from t group by a order by a"
    import time
    t0 = time.time(); r1 = s.sql(q).rows(); first = time.time() - t0
    t0 = time.time(); r2 = s.sql(q).rows(); second = time.time() - t0
    assert r1 == r2
    assert second < first  # cached program, no re-trace
    # learned capacities: an overflowing query runs 1 attempt the second time
    s.sql("insert into t values (3, 1.0), (4, 1.0), (5, 1.0)")
    qq = "select a, count(*) c from t group by a order by a"
    s.sql(qq)
    s.sql(qq)
    attempts = sum(1 for c in s.last_profile.children if c.name.startswith("attempt"))
    assert attempts == 1


def test_program_cache_retrace_safe_after_dict_change():
    # regression: cached programs must retrace cleanly when a string
    # dictionary (jit-static schema metadata) changes after DML
    s = Session()
    s.sql("create table rc (g int, s varchar)")
    s.sql("insert into rc values (1, 'a'), (2, 'b')")
    q = "select s, count(*) c from rc group by s order by s"
    assert s.sql(q).rows() == [("a", 1), ("b", 1)]
    s.sql("insert into rc values (3, 'zzz')")
    assert s.sql(q).rows() == [("a", 1), ("b", 1), ("zzz", 1)]


def test_batched_aggregation_spill_path():
    # host-offload streaming (spill analog): results identical to one-shot
    from starrocks_tpu.storage.catalog import tpch_catalog

    cat = tpch_catalog(sf=0.005)
    q = """select l_returnflag, sum(l_quantity) q, count(*) c,
           avg(l_discount) a, min(l_extendedprice) mn
           from lineitem where l_shipdate <= date '1998-09-02'
           group by l_returnflag order by l_returnflag"""
    ref = Session(cat).sql(q).rows()
    config.set("batch_rows_threshold", 4000)
    try:
        s = Session(cat)
        got = s.sql(q).rows()
        assert got == ref
        info = s.last_profile.find("attempt_0").infos
        assert info["batches"] >= 2
        # high-cardinality group-by: overflow-recompile inside the batched path
        q2 = "select l_orderkey, sum(l_quantity) s from lineitem group by l_orderkey"
        config.set("batch_rows_threshold", 0)
        ref2 = sorted(Session(cat).sql(q2).rows())  # one-shot oracle
        config.set("batch_rows_threshold", 4000)
        got2 = sorted(Session(cat).sql(q2).rows())
        assert got2 == ref2
    finally:
        config.set("batch_rows_threshold", 0)


def test_show_profile_statement():
    from starrocks_tpu.runtime.session import Session

    s = Session()
    s.sql("CREATE TABLE t (a BIGINT)")
    s.sql("INSERT INTO t VALUES (1), (2)")
    assert s.sql("SHOW PROFILE") == "no queries yet"
    s.sql("SELECT sum(a) FROM t")
    out = s.sql("SHOW PROFILE")
    assert "attempt_0" in out or "query" in out
