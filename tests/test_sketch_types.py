"""HLL + BITMAP sketch types: accuracy fuzz, SQL surface, storage
round-trip, distributed-vs-single-chip agreement (VERDICT r4 item 5).

Reference behavior: be/src/types/hll.h (HLL_UNION_AGG / HLL_CARDINALITY),
be/src/types/bitmap_value.h + be/src/exprs/bitmap_functions.cpp
(BITMAP_UNION_COUNT / INTERSECT_COUNT), re-designed as dense fixed-width
device columns (ops/sketch.py)."""

import numpy as np
import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


def _sess(tables: dict) -> Session:
    cat = Catalog()
    for name, data in tables.items():
        if isinstance(data, HostTable):
            cat.register(name, data)
        else:
            cat.register(name, HostTable.from_pydict(data))
    return Session(cat)


def test_hll_estimate_accuracy_1m_fuzz():
    """approx_count_distinct within ~2% of exact on 1M rows (p=12 ->
    theoretical rel. error 1.04/sqrt(4096) = 1.6%)."""
    rng = np.random.default_rng(5)
    true_ndv = 137_813
    vals = rng.integers(0, true_ndv, 1_000_000)
    vals[:true_ndv] = np.arange(true_ndv)  # every value present
    s = _sess({"t": {"v": vals}})
    est = s.sql("select approx_count_distinct(v) from t").rows()[0][0]
    assert abs(est - true_ndv) / true_ndv < 0.02, (est, true_ndv)
    exact = s.sql("select ndv(v) from t").rows()[0][0]
    assert exact == true_ndv  # ndv stays exact


def test_hll_grouped_and_strings():
    rng = np.random.default_rng(6)
    n = 200_000
    g = rng.integers(0, 4, n)
    v = rng.integers(0, 50_000, n)
    s = _sess({"t": {"g": g, "s": [f"u{x}" for x in v]}})
    got = s.sql("select g, approx_count_distinct(s) from t "
                "group by g order by g").rows()
    import pandas as pd

    df = pd.DataFrame({"g": g, "s": [f"u{x}" for x in v]})
    exact = df.groupby("g").s.nunique()
    assert len(got) == 4
    for gid, est in got:
        assert abs(est - exact[gid]) / exact[gid] < 0.05, (gid, est)


def test_hll_sketch_column_union_roundtrip(tmp_path):
    """Sketches materialize into a table, survive parquet storage, and
    hll_union / hll_cardinality work over the stored column."""
    rng = np.random.default_rng(7)
    n = 100_000
    part = rng.integers(0, 8, n)
    user = rng.integers(0, 20_000, n)
    s = _sess({"raw": {"p": part, "u": user}})
    s.store_root = None  # in-memory catalog; storage tested below
    s.sql("create table daily as select p, hll_sketch(u) as users "
          "from raw group by p")
    # per-partition sketches re-merge to the global estimate
    est = s.sql("select hll_union_agg(users) from daily").rows()[0][0]
    true_ndv = len(np.unique(user))
    assert abs(est - true_ndv) / true_ndv < 0.03, (est, true_ndv)
    merged = s.sql(
        "select hll_cardinality(hll_union(users)) from daily").rows()[0][0]
    assert merged == est


def test_hll_storage_roundtrip(tmp_path):
    rng = np.random.default_rng(8)
    s = Session(data_dir=str(tmp_path))
    cat = s.catalog
    s.sql("create table agg_t (k int, users hll(12))")
    raw = _sess({"raw": {"k": rng.integers(0, 3, 50_000),
                         "u": rng.integers(0, 9_000, 50_000)}})
    sk = raw.sql("select k, hll_sketch(u) as users from raw group by k")
    rows = sk.rows()
    # insert the sketch rows (binary planes) through the normal write path
    from starrocks_tpu import types as T

    ht = HostTable.from_pydict(
        {"k": [r[0] for r in rows], "users": [r[1] for r in rows]},
        types={"k": T.INT, "users": T.HLL(12)})
    s._append(cat.get_table("agg_t"), ht)
    est = s.sql("select hll_union_agg(users) from agg_t").rows()[0][0]
    true_ndv = len(np.unique(raw.catalog.get_table(
        "raw").table.arrays["u"]))
    assert abs(est - true_ndv) / true_ndv < 0.03, (est, true_ndv)


def test_bitmap_agg_exact_counts():
    rng = np.random.default_rng(9)
    n = 300_000
    g = rng.integers(0, 5, n)
    v = rng.integers(0, 3_000, n)
    s = _sess({"t": {"g": g, "v": v}})
    got = s.sql("select g, bitmap_union_count(to_bitmap(v)) from t "
                "group by g order by g").rows()
    import pandas as pd

    exact = pd.DataFrame({"g": g, "v": v}).groupby("g").v.nunique()
    assert got == [(int(k), int(exact[k])) for k in sorted(exact.index)]


def test_bitmap_union_count_composes_over_stored_bitmaps():
    rng = np.random.default_rng(10)
    n = 120_000
    day = rng.integers(0, 10, n)
    site = rng.integers(0, 2, n)
    user = rng.integers(0, 2_500, n)
    s = _sess({"t": {"dy": day, "site": site, "u": user}})
    s.sql("create table daily as select dy, site, "
          "bitmap_agg(u) as users from t group by dy, site")
    got = s.sql("select site, bitmap_union_count(users) from daily "
                "group by site order by site").rows()
    import pandas as pd

    exact = pd.DataFrame({"site": site, "u": user}).groupby(
        "site").u.nunique()
    assert got == [(int(k), int(exact[k])) for k in sorted(exact.index)]


def test_intersect_count_and_scalar_bitmap_fns():
    rng = np.random.default_rng(11)
    n = 80_000
    dim = rng.integers(1, 4, n)  # 1, 2, 3
    user = rng.integers(0, 1_500, n)
    s = _sess({"t": {"dim": dim, "u": user}})
    s.sql("create table by_dim as select dim, bitmap_agg(u) as users "
          "from t group by dim")
    got = s.sql("select intersect_count(users, dim, 1, 2) from by_dim"
                ).rows()[0][0]
    u1 = set(user[dim == 1])
    u2 = set(user[dim == 2])
    assert got == len(u1 & u2)
    # scalar and/or/count/contains over two bitmap values
    r = s.sql("""select bitmap_count(bitmap_and(a.users, b.users)),
                        bitmap_count(bitmap_or(a.users, b.users)),
                        bitmap_contains(a.users, 0)
                 from by_dim a, by_dim b
                 where a.dim = 1 and b.dim = 2""").rows()[0]
    assert r[0] == len(u1 & u2)
    assert r[1] == len(u1 | u2)
    assert r[2] == (0 in u1)


def test_bitmap_storage_roundtrip(tmp_path):
    rng = np.random.default_rng(12)
    s = Session(data_dir=str(tmp_path))
    cat = s.catalog
    s.sql("create table bm (k int, users bitmap(4096))")
    from starrocks_tpu import types as T

    vals = [sorted(set(rng.integers(0, 4096, 50).tolist())) for _ in range(3)]
    def planes(vs):
        b = np.zeros(512, dtype=np.uint8)
        for x in vs:
            b[x >> 3] |= 1 << (x & 7)
        return b.astype(np.int8).tobytes()
    ht = HostTable.from_pydict(
        {"k": [0, 1, 2], "users": [planes(v) for v in vals]},
        types={"k": T.INT, "users": T.BITMAP(4096)})
    s._append(cat.get_table("bm"), ht)
    got = s.sql("select k, bitmap_count(users) from bm order by k").rows()
    assert got == [(i, len(vals[i])) for i in range(3)]
    tot = s.sql("select bitmap_union_count(users) from bm").rows()[0][0]
    assert tot == len(set().union(*map(set, vals)))


def test_sketch_aggs_distributed_match_single_chip(eight_devices):
    """The distributed planner gathers rows for holistic sketch aggs — the
    result must be bit-identical to single-chip."""
    rng = np.random.default_rng(13)
    n = 100_000
    g = rng.integers(0, 6, n)
    v = rng.integers(0, 20_000, n)
    cat = Catalog()
    cat.register("t", HostTable.from_pydict({"g": g, "v": v}))
    single = Session(cat)
    q = ("select g, approx_count_distinct(v), "
         "bitmap_union_count(v) from t group by g order by g")
    want = single.sql(q).rows()
    dist = Session(cat, dist_shards=8)
    got = dist.sql(q).rows()
    assert got == want


def test_hll_sketches_merge_across_dictionaries():
    """Sketches over the SAME strings from independently built dictionaries
    must merge to the single-population estimate (value-hash stability)."""
    names = [f"user{i}" for i in range(4000)]
    s = _sess({
        "t1": HostTable.from_pydict({"u": names}),
        "t2": HostTable.from_pydict({"u": list(reversed(names))}),
    })
    s.sql("create table sk1 as select hll_sketch(u) as h from t1")
    s.sql("create table sk2 as select hll_sketch(u) as h from t2")
    est = s.sql("select hll_union_agg(h) from "
                "(select h from sk1 union all select h from sk2) x"
                ).rows()[0][0]
    assert abs(est - 4000) / 4000 < 0.05, est


def test_bitmap_binary_widens_domains():
    s = _sess({"t": {"a": [1, 2, 3], "b": [100, 200, 300]}})
    # different stats-derived domains must still combine
    r = s.sql("select bitmap_count(bitmap_or(to_bitmap(a), to_bitmap(b))) "
              "from t where a = 1").rows()
    assert r == [(2,)]
