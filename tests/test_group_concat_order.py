"""group_concat ORDER BY / SEPARATOR (round-3 leftover; reference:
be/src/exprs/agg/group_concat.h ORDER BY support)."""

import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog


@pytest.fixture()
def sess():
    cat = Catalog()
    cat.register("t", HostTable.from_pydict({
        "g": [1, 1, 1, 2, 2],
        "name": ["bob", "amy", "cid", "zed", "ann"],
        "rank": [2, 1, None, 5, 4],
    }))
    return Session(cat)


def test_order_by_expr(sess):
    r = sess.sql("select g, group_concat(name order by rank) from t "
                 "group by g order by g").rows()
    # NULL rank sorts last within the group
    assert r == [(1, "amy,bob,cid"), (2, "ann,zed")]
    r = sess.sql("select g, group_concat(name order by rank desc) from t "
                 "group by g order by g").rows()
    # NULL placement follows the engine's ORDER BY default: first on DESC
    assert r == [(1, "cid,bob,amy"), (2, "zed,ann")]


def test_double_separator_rejected(sess):
    import pytest as _pt

    with _pt.raises(Exception, match="not both"):
        sess.sql("select group_concat(name, ';' separator '|') from t")


def test_separator_and_self_order(sess):
    r = sess.sql("select g, group_concat(name order by name separator '|') "
                 "from t group by g order by g").rows()
    assert r == [(1, "amy|bob|cid"), (2, "ann|zed")]
    # legacy positional separator still works, default ordering unchanged
    r = sess.sql("select g, group_concat(name, ';') from t "
                 "group by g order by g").rows()
    assert r == [(1, "amy;bob;cid"), (2, "ann;zed")]
