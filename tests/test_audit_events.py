"""Observability-plane tests: structured audit log, system event
journal, metrics time-series ring, and the ADMIN DIAGNOSE bundle
(reference analog: FE plugin/AuditEvent + fe.audit.log, SHOW PROC-style
event views, and the BE metrics webpage — SURVEY §1/§5).

The contracts under test:

- every top-level statement — success, error, point-lane — leaves
  exactly ONE audit record with its terminal state, via both surfaces
  (AUDIT.snapshot and information_schema.audit_log);
- every ring is hard-bounded (audit, pending included; events; metrics
  history) and the JSONL sink never exceeds ~2x its rotation threshold;
- the event taxonomy is closed (off-taxonomy emission raises);
- heartbeat loss/reconnect transitions journal exactly once per outage;
- ADMIN DIAGNOSE returns one parseable JSON document with every
  flight-recorder section present.
"""

import json
import os

import pytest

from starrocks_tpu.runtime import events
from starrocks_tpu.runtime.audit import AUDIT, diagnostic_bundle
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.events import EVENTS, TAXONOMY
from starrocks_tpu.runtime.events import emit as emit_event
from starrocks_tpu.runtime.metrics import HISTORY
from starrocks_tpu.runtime.session import Session


@pytest.fixture(autouse=True)
def _restore_obs_knobs():
    yield
    config.set("enable_audit_log", True)
    config.set("audit_log_ring", 1024)
    config.set("audit_log_path", "")
    config.set("audit_log_rotate_mb", 8)
    config.set("events_ring_size", 512)
    config.set("metrics_history_capacity", 120)


def _sess():
    s = Session()
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1, 2), (2, 3), (1, 4)")
    return s


# --- audit log ---------------------------------------------------------------


def test_audit_exactly_one_record_per_statement():
    s = _sess()  # 2 records already: create + insert
    n0 = AUDIT.stats()["registered"]
    s.sql("select b, sum(a) sa from t group by b")
    with pytest.raises(Exception):
        s.sql("select no_such_col from t")
    recs = AUDIT.snapshot()
    assert AUDIT.stats()["registered"] - n0 == 2
    ok, bad = recs[-2], recs[-1]
    assert ok["state"] == "done" and ok["stmt_class"] == "read"
    assert ok["tables"] == "t" and ok["rows"] == 3
    assert ok["mem_peak_bytes"] > 0  # the accountant's high-water mark
    assert bad["state"] == "error"
    # the same two records through the SQL surface
    got = s.sql("select state from information_schema.audit_log "
                "order by seq").rows()
    assert [r[0] for r in got[-2:]] == ["done", "error"]


def test_audit_point_lane_records(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table kv (k bigint, v varchar, primary key(k))")
    s.sql("insert into kv values (1, 'a'), (2, 'b')")
    n0 = AUDIT.stats()["registered"]
    assert s.sql("select v from kv where k = 2").rows() == [("b",)]
    recs = AUDIT.snapshot()
    assert AUDIT.stats()["registered"] - n0 == 1
    assert recs[-1]["stmt_class"] == "point"
    assert recs[-1]["state"] == "done" and recs[-1]["tables"] == "kv"


def test_audit_ring_hard_bounded():
    s = _sess()
    config.set("audit_log_ring", 4)
    for _ in range(10):
        s.sql("select count(*) from t")
    st = AUDIT.stats()
    assert st["retained"] == 4
    assert len(AUDIT.snapshot()) == 4
    assert st["dropped"] > 0


class _FakeCtx:
    """Terminal-shaped context for driving the audit sink directly
    (rotation needs megabytes of records; real queries would dominate
    the test's runtime)."""

    def __init__(self, i):
        self.qid = i
        self.profile = None
        self.stmt_class = "read"
        self.sql = "select /* pad */ " + "x" * 600
        self.user = "root"
        self.tables = ("t",)
        self.state = "done"
        self.last_stage = "fetch_results"
        self.queue_wait_ms = 0
        self.rows = 1
        self.mem_peak = 0
        self.degraded = False

    def elapsed_ms(self):
        return 1

    def cancel_reason(self):
        return None


def test_audit_jsonl_sink_rotates_and_stays_bounded(tmp_path):
    path = str(tmp_path / "audit.jsonl")
    config.set("audit_log_rotate_mb", 1)
    config.set("audit_log_path", path)
    rotate_bytes = 1 << 20
    try:
        # ~700B/line x 4000 crosses the 1MB threshold twice over
        for i in range(4000):
            AUDIT.record_query(_FakeCtx(i))
        AUDIT.flush()
        assert os.path.exists(path + ".1"), "sink never rotated"
        sizes = [os.path.getsize(p) for p in (path, path + ".1")]
        slack = 4096  # one record of overshoot headroom
        assert all(sz <= rotate_bytes + slack for sz in sizes), sizes
        assert sum(sizes) <= 2 * rotate_bytes + slack, sizes
        with open(path) as f:
            last = json.loads(f.readlines()[-1])
        assert last["stmt_class"] == "read" and len(last["stmt"]) == 512
    finally:
        config.set("audit_log_path", "")
        config.set("audit_log_rotate_mb", 8)


# --- event journal -----------------------------------------------------------


def test_event_ring_bounded_and_counts_survive_eviction():
    EVENTS.clear()
    config.set("events_ring_size", 3)
    for _ in range(8):
        emit_event("compaction", table="t", rows=1, rowsets_merged=2)
    assert len(EVENTS.snapshot()) == 3
    assert EVENTS.stats()["compaction"] == 8  # lifetime, not ring
    ev = EVENTS.snapshot()[-1]
    assert ev["name"] == "compaction" and ev["detail"]["table"] == "t"
    assert ev["seq"] == 8


def test_event_off_taxonomy_raises():
    with pytest.raises(ValueError, match="closed taxonomy"):
        emit_event("made_up_event", x=1)
    assert "made_up_event" not in EVENTS.stats()


def test_events_sql_surface():
    emit_event("checkpoint", seq=7, tail_ops=0)
    got = Session().sql(
        "select name, detail from information_schema.events "
        "order by seq").rows()
    assert got and got[-1][0] == "checkpoint"
    assert json.loads(got[-1][1])["seq"] == 7
    assert all(name in TAXONOMY for name, _d in got)


def test_soft_mem_degrade_emits_event():
    n0 = EVENTS.stats().get("soft_mem_degrade", 0)
    s = _sess()
    config.set("query_mem_soft_limit_bytes", 1)
    try:
        s.sql("select b, sum(a) from t group by b")
    finally:
        config.set("query_mem_soft_limit_bytes", 0)
    assert EVENTS.stats().get("soft_mem_degrade", 0) > n0


# --- heartbeat loss / reconnect ----------------------------------------------


def test_heartbeat_loss_and_reconnect_journal_once_per_outage():
    from starrocks_tpu.runtime.cluster import Heartbeater

    hb = Heartbeater("127.0.0.1", 1, "w1", autostart=False)
    base_loss = EVENTS.stats().get("heartbeat_loss", 0)
    base_rec = EVENTS.stats().get("heartbeat_reconnect", 0)
    hb._observe(False)   # outage starts: journaled
    hb._observe(False)   # still down: silent (once per outage)
    hb._observe(False)
    hb._observe(True)    # back: reconnect with the failure count
    hb._observe(True)    # healthy steady-state: silent
    assert EVENTS.stats().get("heartbeat_loss", 0) == base_loss + 1
    assert EVENTS.stats().get("heartbeat_reconnect", 0) == base_rec + 1
    rec = [e for e in EVENTS.snapshot()
           if e["name"] == "heartbeat_reconnect"][-1]
    assert rec["detail"] == {"worker": "w1", "after_failures": 3}


# --- metrics history ---------------------------------------------------------


def test_metrics_history_ring_bounded_and_sample_shape():
    HISTORY.clear()
    config.set("metrics_history_capacity", 5)
    for _ in range(12):
        HISTORY.sample()
    samples = HISTORY.snapshot()
    assert len(samples) == 5
    s = samples[-1]
    assert set(s) == {"ts", "counters", "gauges", "histograms"}
    # counter entries are deltas: an idle process samples no movement
    assert all(v > 0 for v in s["counters"].values())


def test_metrics_history_counter_deltas():
    s = _sess()
    HISTORY.clear()
    HISTORY.sample()
    s.sql("select count(*) from t")
    HISTORY.sample()
    last = HISTORY.snapshot()[-1]
    assert last["counters"].get("sr_tpu_queries_total", 0) >= 1


def test_metrics_history_sql_surface():
    HISTORY.sample()
    got = Session().sql(
        "select name, kind from information_schema.metrics_history "
        "where kind = 'gauge'").rows()
    assert got  # gauges are always present (memory/cache gauges)


# --- ADMIN DIAGNOSE ----------------------------------------------------------


def test_admin_diagnose_bundle():
    s = _sess()
    s.sql("select b, sum(a) from t group by b")
    out = s.sql("admin diagnose")
    bundle = json.loads(out)
    for section in ("generated_ts", "running", "memory", "profiles",
                    "audit_tail", "audit_stats", "events_tail",
                    "event_counts", "metrics_history", "lock_witness",
                    "failpoints", "config_non_default", "cache"):
        assert section in bundle, section
    assert bundle["audit_tail"], "bundle carries no audit tail"
    assert bundle["audit_tail"][-1]["stmt_class"] == "read"
    assert isinstance(bundle["lock_witness"]["cycles"], int)
    # direct-call parity (the /api/debug/bundle handler calls this)
    assert set(diagnostic_bundle(s)) == set(bundle)


def test_admin_diagnose_requires_admin():
    s = _sess()
    s.sql("create user 'bob' identified by 'pw'")
    s2 = Session(catalog=s.catalog, cache=s.cache)
    s2.current_user = "bob"
    with pytest.raises(PermissionError):
        s2.sql("admin diagnose")
