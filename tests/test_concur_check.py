"""Static concurrency-contract analyzer tests (ISSUE 6).

Golden BAD fixtures prove each checker rejects what it exists to reject —
a seeded lock-order inversion, an unguarded access to a `guarded_by`
field, a forbidden/undeclared import — and twin GOOD fixtures prove the
escape hatches (`with self._lock`, `# lint: holds`, `# lint:
unguarded-ok`, manifest allow prefixes) pass clean. Then the real
package: `starrocks_tpu/` must be strict-clean (zero errors) under both
analyzers — the same gate tools/concur_lint.py runs ahead of pytest.
"""

from __future__ import annotations

from starrocks_tpu.analysis import astwalk, boundary_check, concur_check


def _rules(rep, severity=None):
    fs = rep.findings if hasattr(rep, "findings") else rep
    return [f.rule for f in fs if severity in (None, f.severity)]


# --- lock-order graph ----------------------------------------------------------

INVERSION = '''
import threading

class A:
    def __init__(self):
        self._la = threading.Lock()

    def m(self):
        with self._la:
            b.n()

    def locked_leaf(self):
        with self._la:
            pass

class B:
    def __init__(self):
        self._lb = threading.Lock()

    def n(self):
        with self._lb:
            a.locked_leaf()

a = A()
b = B()
'''


def test_lock_order_inversion_rejected():
    rep = concur_check.check_fixture(INVERSION)
    cycles = [f for f in rep.findings if f.rule == "lock-order-cycle"]
    assert len(cycles) == 1 and cycles[0].severity == "error"
    # the finding names both locks and both witnessing sites
    assert "fixture.A._la" in cycles[0].message
    assert "fixture.B._lb" in cycles[0].message
    assert "fixture.py:" in cycles[0].message


def test_one_way_ordering_clean():
    # same shape, but B.n does NOT call back into A: a DAG, no finding
    src = INVERSION.replace("            a.locked_leaf()\n", "            pass\n")
    rep = concur_check.check_fixture(src)
    assert "lock-order-cycle" not in _rules(rep)
    assert rep.stats["edges"] == 1  # A._la -> B._lb recorded


def test_cross_object_instance_resolution():
    # the MemoryAccountant.charge shape: a module FUNCTION calls a
    # module-level instance's method; holding another lock around that
    # function must produce the cross-object edge
    src = '''
import threading

class Accountant:
    def __init__(self):
        self._lock = threading.Lock()

    def charge(self):
        with self._lock:
            pass

ACC = Accountant()

def account():
    ACC.charge()

class Exec:
    def __init__(self):
        self._mu = threading.Lock()

    def step(self):
        with self._mu:
            account()
'''
    rep = concur_check.check_fixture(src)
    assert rep.stats["edges"] == 1
    assert not rep.errors


def test_factory_bound_local_resolution():
    # round-12 extension: a LOCAL bound from a known factory
    # (`c = reg.counter(...)`) resolves to the factory's return class, so
    # calling its locking method while holding another lock records the
    # cross-object edge — previously locals were invisible to the graph
    src = '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()

    def inc(self):
        with self._lock:
            pass

class MetricRegistry:
    def counter(self, name):
        return Counter()

reg = MetricRegistry()

class Exec:
    def __init__(self):
        self._mu = threading.Lock()

    def step(self):
        c = reg.counter("x")
        with self._mu:
            c.inc()
'''
    rep = concur_check.check_fixture(src)
    assert not rep.errors
    assert rep.stats["edges"] == 1  # Exec._mu -> Counter._lock witnessed


def test_factory_local_chain_through_constructor():
    # two-hop fixpoint: local registry constructed locally, then a local
    # counter minted from it — still resolves
    src = '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()

    def inc(self):
        with self._lock:
            pass

class MetricRegistry:
    def counter(self, name):
        return Counter()

class Exec:
    def __init__(self):
        self._mu = threading.Lock()

    def step(self):
        reg = MetricRegistry()
        c = reg.counter("x")
        with self._mu:
            with c._lock:
                pass
'''
    rep = concur_check.check_fixture(src)
    assert not rep.errors
    assert rep.stats["edges"] == 1


def test_direct_self_nest_nonreentrant_rejected():
    src = '''
import threading

class C:
    def __init__(self):
        self._mu = threading.Lock()

    def bad(self):
        with self._mu:
            with self._mu:
                pass
'''
    rep = concur_check.check_fixture(src)
    assert "self-deadlock" in _rules(rep, "error")
    # RLock twin is legal
    rep2 = concur_check.check_fixture(src.replace("Lock()", "RLock()"))
    assert "self-deadlock" not in _rules(rep2)


# --- guarded_by discipline -----------------------------------------------------

GUARDED = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}  # guarded_by: _lock

    def good(self):
        with self._lock:
            self.state["k"] = 1

    def helper(self):  # lint: holds _lock
        return len(self.state)

    def bad(self):
        return self.state.get("k")

    def closure_trap(self):
        with self._lock:
            def later():
                return self.state
            return later

    def reviewed(self):
        return self.state  # lint: unguarded-ok
'''


def test_guarded_by_violations():
    rep = concur_check.check_fixture(GUARDED)
    errs = [f for f in rep.errors if f.rule == "guarded-by"]
    # exactly two: `bad` (no lock) and the closure body (runs after the
    # with-block exits — lexical nesting does not mean held-at-call-time)
    assert len(errs) == 2
    lines = sorted(int(f.where.rsplit(":", 1)[1]) for f in errs)
    assert "bad" in GUARDED.splitlines()[lines[0] - 2]  # def line above
    # good/helper/reviewed produce nothing
    assert all("good" not in f.message and "helper" not in f.message
               and "reviewed" not in f.message for f in errs)


def test_guarded_by_unknown_lock_rejected():
    src = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.x = 0  # guarded_by: _nope
'''
    rep = concur_check.check_fixture(src)
    assert "guarded-by-unknown-lock" in _rules(rep, "error")


def test_unannotated_mutable_attr_warns_and_suppression():
    src = '''
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.table = {}
        self.reviewed = {}  # lint: unguarded-ok
        self.scalar_set_once = 0
'''
    rep = concur_check.check_fixture(src)
    warns = [f for f in rep.warnings
             if f.rule == "unannotated-mutable-attr"]
    assert len(warns) == 1 and "C.table" in warns[0].message
    # scalar assigned only in __init__ with an immutable RHS: not flagged


def test_lockdep_factories_inventoried():
    src = '''
from starrocks_tpu import lockdep

class C:
    def __init__(self):
        self._lock = lockdep.rlock("C._lock")
        self.x = 0  # guarded_by: _lock

    def bad(self):
        self.x += 1
'''
    rep = concur_check.check_fixture(src)
    assert rep.stats["locks"] == 1
    assert "guarded-by" in _rules(rep, "error")


def test_inherited_lock_and_guard():
    # the Counter/Gauge shape: subclass methods touch base-guarded state
    src = '''
import threading

class Base:
    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0  # guarded_by: _lock

class Sub(Base):
    def good(self):
        with self._lock:
            self._v = 2

    def bad(self):
        self._v = 3
'''
    rep = concur_check.check_fixture(src)
    errs = [f for f in rep.errors if f.rule == "guarded-by"]
    assert len(errs) == 1 and "Sub.bad" in errs[0].message


# --- module-boundary manifest --------------------------------------------------

_MANIFEST = {
    "units": {
        "ops": {"allow": ["ops", "column", "runtime.config"],
                "forbid": ["runtime"]},
        "column": {"allow": ["column"]},
        "runtime": {"allow": ["*"]},
    },
}


def _fixture_sources(*pairs):
    # target stubs must exist as modules for `from ..x import y` to
    # resolve as a submodule import
    stubs = [astwalk.parse_fixture("", rel) for rel in (
        "starrocks_tpu/runtime/__init__.py",
        "starrocks_tpu/runtime/config.py",
        "starrocks_tpu/runtime/lifecycle.py",
        "starrocks_tpu/column/__init__.py",
        "starrocks_tpu/ops/__init__.py",
    )]
    return stubs + [astwalk.parse_fixture(src, rel) for rel, src in pairs]


def test_forbidden_import_rejected():
    srcs = _fixture_sources(
        ("starrocks_tpu/ops/bad.py",
         "from ..runtime import lifecycle\n"))
    fs = boundary_check.check_imports(_MANIFEST, srcs)
    assert any(f.rule == "forbidden-import" and "runtime.lifecycle"
               in f.message for f in fs)


def test_allow_exception_beats_forbid_prefix():
    # ops may import runtime.config even though runtime/ is forbidden:
    # longest prefix wins — the ISSUE-6 contract shape
    srcs = _fixture_sources(
        ("starrocks_tpu/ops/good.py",
         "from ..runtime.config import config\nfrom ..column import x\n"))
    fs = boundary_check.check_imports(_MANIFEST, srcs)
    assert [str(f) for f in fs if f.severity == "error"] == []


def test_undeclared_import_rejected():
    manifest = {"units": {"column": {"allow": ["column"]},
                          "ops": {"allow": ["ops"]},
                          "runtime": {"allow": ["*"]}}}
    srcs = _fixture_sources(
        ("starrocks_tpu/column/sneaky.py", "from ..ops import x\n"))
    fs = boundary_check.check_imports(manifest, srcs)
    assert any(f.rule == "undeclared-import" for f in fs)


def test_unit_missing_from_manifest_rejected():
    srcs = _fixture_sources(
        ("starrocks_tpu/newpkg/mod.py", "import os\n"))
    fs = boundary_check.check_imports(_MANIFEST, srcs)
    assert any(f.rule == "unit-missing" for f in fs)


def test_module_rule_override_tighter_than_unit():
    manifest = {
        "units": {"ops": {"allow": ["ops", "column"]},
                  "column": {"allow": ["column"]},
                  "runtime": {"allow": ["*"]}},
        "module_rules": {"ops/pinned.py": {"allow": []}},
    }
    srcs = _fixture_sources(
        ("starrocks_tpu/ops/pinned.py", "from ..column import x\n"))
    fs = boundary_check.check_imports(manifest, srcs)
    assert any(f.rule == "undeclared-import" for f in fs)


def test_governed_external_rejected_outside_allow_list():
    # sockets are service-layer-only; a storage module opening one fails
    manifest = {
        "external_governed": ["jax", "socket"],
        "units": {"ops": {"allow": ["ops"], "external": ["jax"]},
                  "column": {"allow": ["column"], "external": ["jax"]},
                  "runtime": {"allow": ["*"], "external": ["jax", "socket"]}},
    }
    srcs = _fixture_sources(
        ("starrocks_tpu/ops/leaky.py",
         "def f():\n    import socket\n    return socket.gethostname()\n"))
    fs = boundary_check.check_imports(manifest, srcs)
    assert any(f.rule == "external-import" and "'socket'" in f.message
               for f in fs), fs
    # jax is allow-listed for ops: no finding
    srcs = _fixture_sources(
        ("starrocks_tpu/ops/fine.py", "from jax.sharding import Mesh\n"))
    assert not boundary_check.check_imports(manifest, srcs)


def test_real_manifest_governs_externals():
    m = boundary_check.load_manifest()
    assert "socket" in m["external_governed"]
    assert "jax" in m["external_governed"]
    # sockets are granted ONLY via service-module pins, never unit-wide
    for unit, rule in m["units"].items():
        assert "socket" not in rule.get("external", []), unit
    assert "socket" in m["module_rules"]["runtime/mysql_service.py"][
        "external"]
    # the static gates stay stdlib-only, externally too
    assert m["module_rules"]["analysis/boundary_check.py"]["external"] == []


# --- the real package must hold its own contract -------------------------------

def test_package_concur_strict_clean():
    rep = concur_check.check_package()
    assert rep.errors == [], "\n".join(str(f) for f in rep.errors)
    # the coverage ratchet may carry warns, but they are bounded and
    # tracked (bench.py concur_findings) — a jump means new unreviewed
    # shared state landed on a lock-owning class
    assert len(rep.warnings) <= 6, "\n".join(str(f) for f in rep.warnings)
    # sanity: the inventory actually sees the engine's locks and the
    # cross-object edges (QueryCache/Workgroup -> metrics, journal ->
    # failpoint registry)
    assert rep.stats["locks"] >= 10
    assert rep.stats["guarded_attrs"] >= 15
    assert rep.stats["edges"] >= 3


def test_package_boundary_manifest_clean():
    fs = boundary_check.check_package()
    assert [str(f) for f in fs] == []


def test_manifest_pins_static_analyzers_to_zero_deps():
    m = boundary_check.load_manifest()
    for mod in ("analysis/astwalk.py", "analysis/concur_check.py",
                "analysis/boundary_check.py"):
        rule = m["module_rules"][mod]
        assert set(rule["allow"]) <= {"analysis.astwalk"}
