"""SSB-flat 13-query differential suite vs a pandas oracle."""

import numpy as np
import pandas as pd
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.datagen.ssb import ssb_catalog

from ssb_queries import FLAT_QUERIES


@pytest.fixture(scope="module")
def sess():
    s = Session(ssb_catalog(sf=0.005))
    s._flat = s.catalog.get_table("lineorder_flat").table.to_pandas()
    return s


def _oracle(df, qid):
    y = "lo_orderdate_year"
    if qid == "q1.1":
        x = df[(df[y] == 1993) & df.lo_discount.between(1, 3) & (df.lo_quantity < 25)]
        return [[(x.lo_extendedprice * x.lo_discount).sum()]]
    if qid == "q1.2":
        x = df[(df.lo_orderdate_yearmonthnum == 199401)
               & df.lo_discount.between(4, 6) & df.lo_quantity.between(26, 35)]
        return [[(x.lo_extendedprice * x.lo_discount).sum()]]
    if qid == "q1.3":
        x = df[(df.lo_orderdate_weeknuminyear == 6) & (df[y] == 1994)
               & df.lo_discount.between(5, 7) & df.lo_quantity.between(26, 35)]
        return [[(x.lo_extendedprice * x.lo_discount).sum()]]
    if qid in ("q2.1", "q2.2", "q2.3"):
        if qid == "q2.1":
            x = df[(df.p_category == "MFGR#12") & (df.s_region == "AMERICA")]
        elif qid == "q2.2":
            x = df[(df.p_brand >= "MFGR#2221") & (df.p_brand <= "MFGR#2228")
                   & (df.s_region == "ASIA")]
        else:
            x = df[(df.p_brand == "MFGR#2239") & (df.s_region == "EUROPE")]
        g = x.groupby([y, "p_brand"], as_index=False).agg(r=("lo_revenue", "sum"))
        g = g.sort_values([y, "p_brand"])
        return [[r.r, getattr(r, y), r.p_brand] for r in g.itertuples(index=False)]
    if qid in ("q3.1", "q3.2", "q3.3", "q3.4"):
        if qid == "q3.1":
            x = df[(df.c_region == "ASIA") & (df.s_region == "ASIA") & df[y].between(1992, 1997)]
            keys = ["c_nation", "s_nation"]
        elif qid == "q3.2":
            x = df[(df.c_nation == "UNITED STATES") & (df.s_nation == "UNITED STATES")
                   & df[y].between(1992, 1997)]
            keys = ["c_city", "s_city"]
        elif qid == "q3.3":
            x = df[df.c_city.isin(["UNITED KI1", "UNITED KI5"])
                   & df.s_city.isin(["UNITED KI1", "UNITED KI5"])
                   & df[y].between(1992, 1997)]
            keys = ["c_city", "s_city"]
        else:
            x = df[df.c_city.isin(["UNITED KI1", "UNITED KI5"])
                   & df.s_city.isin(["UNITED KI1", "UNITED KI5"])
                   & (df.lo_orderdate_yearmonth == "Dec1997")]
            keys = ["c_city", "s_city"]
        g = x.groupby(keys + [y], as_index=False).agg(r=("lo_revenue", "sum"))
        g = g.sort_values([y, "r"], ascending=[True, False])
        return [[*(getattr(r, k) for k in keys), getattr(r, y), r.r]
                for r in g.itertuples(index=False)]
    # q4.x
    if qid == "q4.1":
        x = df[(df.c_region == "AMERICA") & (df.s_region == "AMERICA")
               & df.p_mfgr.isin(["MFGR#1", "MFGR#2"])]
        keys = [y, "c_nation"]
    elif qid == "q4.2":
        x = df[(df.c_region == "AMERICA") & (df.s_region == "AMERICA")
               & df[y].isin([1997, 1998]) & df.p_mfgr.isin(["MFGR#1", "MFGR#2"])]
        keys = [y, "s_nation", "p_category"]
    else:
        x = df[(df.s_nation == "UNITED STATES") & df[y].isin([1997, 1998])
               & (df.p_category == "MFGR#14")]
        keys = [y, "s_city", "p_brand"]
    g = x.assign(p=x.lo_revenue - x.lo_supplycost).groupby(keys, as_index=False).agg(
        profit=("p", "sum"))
    g = g.sort_values(keys)
    return [[*(getattr(r, k.replace(".", "_")) for k in keys), r.profit]
            for r in g.itertuples(index=False)]


@pytest.mark.parametrize("qid", sorted(FLAT_QUERIES))
def test_ssb_flat(sess, qid):
    got = sess.sql(FLAT_QUERIES[qid]).rows()
    exp = _oracle(sess._flat, qid)
    assert len(got) == len(exp), f"{qid}: {len(got)} vs {len(exp)} rows"
    for i, (g, e) in enumerate(zip(got, exp)):
        for gv, ev in zip(g, e):
            if isinstance(ev, (int, float, np.floating, np.integer)):
                ok = (gv is None and (ev != ev)) or abs(float(gv) - float(ev)) <= max(abs(float(ev)), 1) * 1e-9
                assert ok, f"{qid} row {i}: {gv} vs {ev}"
            else:
                assert str(gv) == str(ev), f"{qid} row {i}: {gv!r} vs {ev!r}"
