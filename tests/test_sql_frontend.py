"""Parser / analyzer / session unit tests + golden plans
(reference analog: fe sql/plan/PlanTestBase golden-plan tests)."""

import numpy as np
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.sql import ast
from starrocks_tpu.sql.parser import ParseError, parse
from starrocks_tpu.storage.catalog import Catalog, tpch_catalog
from starrocks_tpu.column import HostTable


def test_parse_select_basics():
    s = parse("select a, b + 1 as c from t where a > 2 group by a, b having count(*) > 1 order by c desc limit 5")
    assert isinstance(s, ast.Select)
    assert len(s.items) == 2
    assert s.items[1].alias == "c"
    assert s.limit == 5
    assert not s.order_by[0].asc


def test_parse_joins_and_subqueries():
    s = parse("""select * from a left outer join b on a.x = b.y, c
                 where exists (select 1 from d where d.k = a.x)
                 and a.z in (select z from e)""")
    assert isinstance(s.from_, ast.JoinRef)


def test_parse_case_in_like_between():
    s = parse("""select case when x > 1 then 'hi' else 'lo' end,
                 y between 1 and 2, z like 'ab%', w in (1,2,3), v not in (4)
                 from t""")
    assert len(s.items) == 5


def test_parse_interval_date():
    s = parse("select 1 from t where d >= date '1994-01-01' + interval '3' month")
    assert "date_add_months" in repr(s.where)


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("select from t")
    with pytest.raises(ParseError):
        parse("selec 1")
    with pytest.raises(ParseError):
        parse("select a from t where")


def test_explain_golden_q3():
    s = Session(tpch_catalog(sf=0.001))
    plan = s.sql("""explain select l_orderkey, sum(l_extendedprice) rev
        from customer, orders, lineitem
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and c_mktsegment = 'BUILDING'
        group by l_orderkey order by rev desc limit 10""")
    # shape assertions, not byte equality: sort-topn over agg over 2 joins,
    # with lineitem (largest) as probe root and filters pushed to scans
    assert plan.index("Sort") < plan.index("Agg")
    assert plan.count("Join[inner") == 2
    assert "Scan[lineitem" in plan and "Scan[customer" in plan
    filter_line = next(l for l in plan.splitlines() if "Filter" in l)
    assert "c_mktsegment" in filter_line  # pushed onto the customer side


def test_session_ddl_insert_select():
    s = Session()
    s.sql("create table t (a int not null, b varchar, c decimal(10,2))")
    s.sql("insert into t values (1, 'x', 1.50), (2, 'y', 2.25), (3, 'x', 0.75)")
    r = s.sql("select b, sum(c) sc, count(*) n from t group by b order by b")
    assert r.rows() == [("x", 2.25, 2), ("y", 2.25, 1)]
    s.sql("insert into t values (4, null, null)")
    r = s.sql("select count(*) n, count(b) nb, count(c) nc from t group by a > 0")
    assert r.rows() == [(4, 3, 3)]
    s.sql("drop table t")
    with pytest.raises(Exception):
        s.sql("select * from t")


def test_insert_select():
    s = Session()
    s.sql("create table src (a int, b double)")
    s.sql("insert into src values (1, 1.5), (2, 2.5), (3, 3.5)")
    s.sql("create table dst (a int, b double)")
    s.sql("insert into dst select a, b from src where a >= 2")
    r = s.sql("select count(*) c, sum(b) s from dst group by a > 0")
    assert r.rows() == [(2, 6.0)]


def test_distinct_and_order_alias():
    s = Session()
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1, 10), (1, 10), (2, 20)")
    r = s.sql("select distinct a, b from t order by a")
    assert r.rows() == [(1, 10), (2, 20)]


def test_cte():
    s = Session()
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1, 1), (2, 2), (3, 3)")
    r = s.sql("with big as (select a, b from t where a >= 2) select sum(b) s from big group by a > 0")
    assert r.rows() == [(5,)]


def test_self_join_aliases():
    s = Session()
    s.sql("create table t (k int, v int)")
    s.sql("insert into t values (1, 10), (2, 20), (3, 30)")
    r = s.sql("""select t1.v, t2.v from t t1, t t2
                 where t1.k = t2.k - 1 order by t1.v""")
    assert r.rows() == [(10, 20), (20, 30)]


def test_no_filter_pushdown_through_topn():
    # regression: filtering must not happen before a fused ORDER BY+LIMIT
    s = Session()
    s.sql("create table t (a int)")
    s.sql("insert into t values (1), (2), (30), (40), (50)")
    r = s.sql("select a from (select a from t order by a limit 2) s where a > 10")
    assert r.rows() == []
    r2 = s.sql("select a from (select a from t order by a desc limit 2) s where a > 10")
    assert sorted(r2.rows()) == [(40,), (50,)]


def test_distinct_aggregates():
    # regression: DISTINCT aggs were silently ignored pre-rewrite
    s = Session()
    s.sql("create table da (g int, x int, y double)")
    s.sql("insert into da values (1,5,1.0),(1,5,2.0),(1,7,3.0),(2,9,4.0),(1,null,5.0)")
    r = s.sql("""select g, count(distinct x) cd, sum(distinct x) sd,
                 count(*) c, sum(y) sy, avg(y) ay, min(y) mn
                 from da group by g order by g""")
    assert r.rows() == [(1, 2, 12, 4, 11.0, 2.75, 1.0), (2, 1, 9, 1, 4.0, 4.0, 4.0)]


def test_union_all_and_distinct():
    s = Session()
    s.sql("create table ua (x int, s varchar)")
    s.sql("create table ub (x int, s varchar)")
    s.sql("insert into ua values (1, 'p'), (2, 'q')")
    s.sql("insert into ub values (2, 'q'), (3, 'r')")
    assert s.sql("select x, s from ua union all select x, s from ub order by x").rows() == [
        (1, "p"), (2, "q"), (2, "q"), (3, "r")]
    assert s.sql("select x, s from ua union select x, s from ub order by x").rows() == [
        (1, "p"), (2, "q"), (3, "r")]
    r = s.sql("select s, count(*) c from (select x, s from ua union all select x, s from ub) u group by s order by s")
    assert r.rows() == [("p", 1), ("q", 2), ("r", 1)]


def test_show_describe_information_schema():
    s = Session()
    s.sql("create table meta1 (a int not null, b varchar)")
    s.sql("insert into meta1 values (1, 'x')")
    assert s.sql("show tables") == ["meta1"]
    assert s.sql("describe meta1") == [("a", "INT", "NO"), ("b", "VARCHAR", "YES")]
    rows = s.sql("select table_name, table_rows from information_schema.tables").rows()
    assert ("meta1", 1) in rows
    cols = s.sql(
        "select column_name from information_schema.columns where table_name = 'meta1' order by column_name"
    ).rows()
    assert cols == [("a",), ("b",)]


def test_distinct_in_correlated_subquery_and_union_in_subquery():
    # regressions: distinct rewrite must reach marker subplans; IN-subquery
    # may contain a UNION
    s = Session()
    s.sql("create table rt (a int)")
    s.sql("create table ru (k int, x int)")
    s.sql("insert into rt values (1), (2)")
    s.sql("insert into ru values (1, 5), (1, 5), (1, 7), (2, 9)")
    r = s.sql("select a from rt where a <= (select count(distinct x) from ru where ru.k = rt.a) order by a")
    assert r.rows() == [(1,)]
    r2 = s.sql("select a from rt where a in (select a from rt union select x from ru) order by a")
    assert r2.rows() == [(1,), (2,)]


def test_intersect_except_null_semantics():
    s = Session()
    s.sql("create table ia (x int, s varchar)")
    s.sql("create table ib (x int, s varchar)")
    s.sql("insert into ia values (1,'p'),(2,'q'),(2,'q'),(null,'n')")
    s.sql("insert into ib values (2,'q'),(3,'r'),(null,'n')")
    # set-op semantics: distinct; NULLs compare equal
    assert s.sql("select x, s from ia intersect select x, s from ib order by x nulls last").rows() == [
        (2, "q"), (None, "n")]
    assert s.sql("select x, s from ia except select x, s from ib order by x nulls last").rows() == [
        (1, "p")]


def test_intersect_except_all_multiplicity():
    # bag semantics: INTERSECT ALL keeps min(cl, cr) copies, EXCEPT ALL
    # keeps max(cl - cr, 0); NULLs compare equal (window-partition rewrite,
    # reference: be/src/exec/intersect_node.h hash-counting semantics)
    s = Session()
    s.sql("create table ba (x int, s varchar)")
    s.sql("create table bb (x int, s varchar)")
    s.sql("insert into ba values (1,'a'),(1,'a'),(1,'a'),(2,'b'),"
          "(3,null),(3,null),(null,null)")
    s.sql("insert into bb values (1,'a'),(1,'a'),(3,null),(null,null),"
          "(null,null),(9,'z')")
    assert s.sql(
        "select x, s from ba intersect all select x, s from bb "
        "order by x nulls last, s"
    ).rows() == [(1, "a"), (1, "a"), (3, None), (None, None)]
    assert s.sql(
        "select x, s from ba except all select x, s from bb "
        "order by x nulls last, s"
    ).rows() == [(1, "a"), (2, "b"), (3, None)]
    # n-ary chain folds left-associatively
    assert s.sql(
        "select x, s from ba intersect all select x, s from bb "
        "intersect all select x, s from ba order by x nulls last, s"
    ).rows() == [(1, "a"), (1, "a"), (3, None), (None, None)]
    with pytest.raises(Exception, match="mixing"):
        s.sql("select x, s from ba intersect all select x, s from bb "
              "intersect select x, s from ba")


def test_explain_group_concat_distinct_order_by():
    # EXPLAIN must never raise on executable SQL: the group_concat two-plan
    # orchestration is mirrored into EXPLAIN (regression: the DISTINCT
    # rewrite refused ORDER BY extras and EXPLAIN crashed)
    s = Session()
    s.sql("create table gct (g int, v varchar)")
    s.sql("insert into gct values (1,'b'),(1,'a'),(1,'a'),(2,'c')")
    q = "select g, group_concat(distinct v order by v) gc from gct group by g"
    txt = s.sql("explain " + q)
    assert "group_concat" in txt and "Agg" in txt
    assert s.sql(q + " order by g").rows() == [(1, "a,b"), (2, "c")]
    txt2 = s.sql("explain analyze " + q)
    assert "Agg" in txt2


def test_views_and_materialized_views():
    s = Session()
    s.sql("create table vb (g varchar, v int)")
    s.sql("insert into vb values ('a', 1), ('a', 2), ('b', 5)")
    s.sql("create view vv as select g, sum(v) s from vb group by g")
    # logical view inlines at reference (always fresh) and composes
    assert s.sql("select g, s from vv where s > 2 order by g").rows() == [("a", 3), ("b", 5)]
    assert s.sql("select count(*) c from vv").rows() == [(2,)]
    # MV materializes; stale until refreshed
    s.sql("create materialized view mv as select g, count(*) c from vb group by g")
    assert s.sql("select g, c from mv order by g").rows() == [("a", 2), ("b", 1)]
    s.sql("insert into vb values ('b', 6)")
    assert s.sql("select g, c from mv order by g").rows() == [("a", 2), ("b", 1)]
    assert s.sql("refresh materialized view mv") == 2
    assert s.sql("select g, c from mv order by g").rows() == [("a", 2), ("b", 2)]
    # views join with base tables
    assert s.sql(
        "select vb.g, vv.s from vb, vv where vb.g = vv.g group by vb.g, vv.s order by 1"
    ).rows() == [("a", 3), ("b", 11)]
    # drop
    s.sql("drop table vv")
    with pytest.raises(Exception):
        s.sql("select * from vv")


def test_view_scoping_and_conflicts():
    s = Session()
    s.sql("create table bt (a int)")
    s.sql("create table bu (a int)")
    s.sql("insert into bt values (1)")
    s.sql("insert into bu values (99)")
    s.sql("create view bv as select a from bt;")
    # caller CTEs must NOT leak into view bodies
    assert s.sql("with bt as (select a from bu) select a from bv").rows() == [(1,)]
    with pytest.raises(ValueError):
        s.sql("create materialized view bt as select a from bu")
    # failed MV creation leaves nothing behind
    with pytest.raises(Exception):
        s.sql("create materialized view bad as select zzz from bt")
    assert "bad" not in s.catalog.mv_defs
    # cycle guard
    s.sql("create view c1 as select a from bt")
    s.catalog.views["c1"] = "select a from c2"
    s.catalog.views["c2"] = "select a from c1"
    with pytest.raises(Exception, match="cyclic"):
        s.sql("select * from c1")


def test_right_and_full_outer_joins():
    s = Session()
    s.sql("create table fl (k int, a varchar)")
    s.sql("create table fr (k int, b varchar)")
    s.sql("insert into fl values (1, 'x'), (2, 'y')")
    s.sql("insert into fr values (2, 'q'), (3, 'z')")
    assert sorted(
        s.sql("select fl.a, fr.b from fl right join fr on fl.k = fr.k").rows(),
        key=str,
    ) == [("y", "q"), (None, "z")]
    rows = sorted(
        s.sql("select fl.k, fl.a, fr.k, fr.b from fl full outer join fr on fl.k = fr.k").rows(),
        key=str,
    )
    assert rows == [(1, "x", None, None), (2, "y", 2, "q"), (None, None, 3, "z")]
    # aggregates over a full join
    r = s.sql("""select count(*) c, count(fl.k) cl, count(fr.k) cr
                 from fl full outer join fr on fl.k = fr.k""")
    assert r.rows() == [(3, 2, 2)]


def test_full_join_extras_and_subquery():
    s = Session()
    s.sql("create table el (k int, a varchar)")
    s.sql("create table er (k int, b varchar)")
    s.sql("insert into el values (1,'x'),(2,'y')")
    s.sql("insert into er values (2,'q'),(3,'z')")
    # one-side extra ON conjunct: failed rows stay, as unmatched
    rows = sorted(s.sql(
        "select el.k, er.k from el full outer join er on el.k = er.k and er.b = 'q'"
    ).rows(), key=str)
    assert rows == [(1, None), (2, 2), (None, 3)]
    # full join inside a correlated EXISTS
    r = s.sql("""select el.k from el where exists (
      select 1 one from el e2 full outer join er on e2.k = er.k
      where e2.k = el.k) order by 1""")
    assert r.rows() == [(1,), (2,)]


def test_or_factoring_enables_join_keys():
    # regression: TPC-H-Q19-style OR of bundles repeating the join predicate
    # must factor the common equi conjunct out (else cartesian blowup)
    s = Session(tpch_catalog(sf=0.001))
    plan = s.sql("""explain select sum(l_extendedprice) r from lineitem, part
        where (p_partkey = l_partkey and p_size < 10)
           or (p_partkey = l_partkey and p_size > 40)""")
    assert "Join[inner" in plan and "Join[cross" not in plan


def test_show_create_table():
    s = Session()
    s.sql("create table sct (a int not null, b varchar, primary key(a)) distributed by hash(a) buckets 4")
    ddl = s.sql("show create table sct")
    assert "a INT NOT NULL" in ddl and "PRIMARY KEY(a)" in ddl
    assert "DISTRIBUTED BY HASH(a)" in ddl
    s.sql("create view scv as select a from sct")
    assert s.sql("show create table scv").startswith("CREATE VIEW scv AS")
    with pytest.raises(ValueError):
        s.sql("show create table nosuch")


def test_distribution_survives_dml():
    # regression: INSERT/DELETE must not drop distribution metadata (it feeds
    # colocate placement and SHOW CREATE)
    s = Session()
    s.sql("create table dt (a int) distributed by hash(a)")
    s.sql("insert into dt values (1), (2)")
    assert "DISTRIBUTED BY HASH(a)" in s.sql("show create table dt")
    s.sql("delete from dt where a = 1")
    assert "DISTRIBUTED BY HASH(a)" in s.sql("show create table dt")
    assert s.catalog.get_table("dt").distribution == ("a",)
