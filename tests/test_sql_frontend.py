"""Parser / analyzer / session unit tests + golden plans
(reference analog: fe sql/plan/PlanTestBase golden-plan tests)."""

import numpy as np
import pytest

from starrocks_tpu.runtime.session import Session
from starrocks_tpu.sql import ast
from starrocks_tpu.sql.parser import ParseError, parse
from starrocks_tpu.storage.catalog import Catalog, tpch_catalog
from starrocks_tpu.column import HostTable


def test_parse_select_basics():
    s = parse("select a, b + 1 as c from t where a > 2 group by a, b having count(*) > 1 order by c desc limit 5")
    assert isinstance(s, ast.Select)
    assert len(s.items) == 2
    assert s.items[1].alias == "c"
    assert s.limit == 5
    assert not s.order_by[0].asc


def test_parse_joins_and_subqueries():
    s = parse("""select * from a left outer join b on a.x = b.y, c
                 where exists (select 1 from d where d.k = a.x)
                 and a.z in (select z from e)""")
    assert isinstance(s.from_, ast.JoinRef)


def test_parse_case_in_like_between():
    s = parse("""select case when x > 1 then 'hi' else 'lo' end,
                 y between 1 and 2, z like 'ab%', w in (1,2,3), v not in (4)
                 from t""")
    assert len(s.items) == 5


def test_parse_interval_date():
    s = parse("select 1 from t where d >= date '1994-01-01' + interval '3' month")
    assert "date_add_months" in repr(s.where)


def test_parse_errors():
    with pytest.raises(ParseError):
        parse("select from t")
    with pytest.raises(ParseError):
        parse("selec 1")
    with pytest.raises(ParseError):
        parse("select a from t where")


def test_explain_golden_q3():
    s = Session(tpch_catalog(sf=0.001))
    plan = s.sql("""explain select l_orderkey, sum(l_extendedprice) rev
        from customer, orders, lineitem
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and c_mktsegment = 'BUILDING'
        group by l_orderkey order by rev desc limit 10""")
    # shape assertions, not byte equality: sort-topn over agg over 2 joins,
    # with lineitem (largest) as probe root and filters pushed to scans
    assert plan.index("Sort") < plan.index("Agg")
    assert plan.count("Join[inner") == 2
    assert "Scan[lineitem" in plan and "Scan[customer" in plan
    filter_line = next(l for l in plan.splitlines() if "Filter" in l)
    assert "c_mktsegment" in filter_line  # pushed onto the customer side


def test_session_ddl_insert_select():
    s = Session()
    s.sql("create table t (a int not null, b varchar, c decimal(10,2))")
    s.sql("insert into t values (1, 'x', 1.50), (2, 'y', 2.25), (3, 'x', 0.75)")
    r = s.sql("select b, sum(c) sc, count(*) n from t group by b order by b")
    assert r.rows() == [("x", 2.25, 2), ("y", 2.25, 1)]
    s.sql("insert into t values (4, null, null)")
    r = s.sql("select count(*) n, count(b) nb, count(c) nc from t group by a > 0")
    assert r.rows() == [(4, 3, 3)]
    s.sql("drop table t")
    with pytest.raises(Exception):
        s.sql("select * from t")


def test_insert_select():
    s = Session()
    s.sql("create table src (a int, b double)")
    s.sql("insert into src values (1, 1.5), (2, 2.5), (3, 3.5)")
    s.sql("create table dst (a int, b double)")
    s.sql("insert into dst select a, b from src where a >= 2")
    r = s.sql("select count(*) c, sum(b) s from dst group by a > 0")
    assert r.rows() == [(2, 6.0)]


def test_distinct_and_order_alias():
    s = Session()
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1, 10), (1, 10), (2, 20)")
    r = s.sql("select distinct a, b from t order by a")
    assert r.rows() == [(1, 10), (2, 20)]


def test_cte():
    s = Session()
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values (1, 1), (2, 2), (3, 3)")
    r = s.sql("with big as (select a, b from t where a >= 2) select sum(b) s from big group by a > 0")
    assert r.rows() == [(5,)]


def test_self_join_aliases():
    s = Session()
    s.sql("create table t (k int, v int)")
    s.sql("insert into t values (1, 10), (2, 20), (3, 30)")
    r = s.sql("""select t1.v, t2.v from t t1, t t2
                 where t1.k = t2.k - 1 order by t1.v""")
    assert r.rows() == [(10, 20), (20, 30)]


def test_no_filter_pushdown_through_topn():
    # regression: filtering must not happen before a fused ORDER BY+LIMIT
    s = Session()
    s.sql("create table t (a int)")
    s.sql("insert into t values (1), (2), (30), (40), (50)")
    r = s.sql("select a from (select a from t order by a limit 2) s where a > 10")
    assert r.rows() == []
    r2 = s.sql("select a from (select a from t order by a desc limit 2) s where a > 10")
    assert sorted(r2.rows()) == [(40,), (50,)]
