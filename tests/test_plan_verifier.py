"""Static-verifier tests: golden bad-plan fixtures that MUST fail strict,
and the clean-corpus guarantee (every TPC-H/SSB/TPC-DS query passes).

Each fixture reproduces one invariant class a past round shipped a bug in:
- schema mismatch (operator references a column its child never produces);
- replicated-operand join without an exchange (distribution pass);
- a profile counter on a sharded stage that is not psum-shaped (the host
  max-merge would report ONE shard's count — round-6 review bug);
- a knob read during tracing but missing from the compiled-program cache
  key (a SET could serve a stale trace — round-7 bug).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from starrocks_tpu.analysis import Finding, VerifyError, report
from starrocks_tpu.analysis import key_check, plan_check, trace_check
from starrocks_tpu.exprs.ir import AggExpr, Call, Col, Lit
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.sql.logical import (
    LAggregate, LFilter, LJoin, LProject, LScan, LSort,
)
from starrocks_tpu.storage.catalog import tpch_catalog


@pytest.fixture(scope="module")
def catalog():
    return tpch_catalog(sf=0.001)


def _errors(findings):
    return [f for f in findings if f.severity == "error"]


# --- golden fixture 1: schema mismatch ---------------------------------------


def test_schema_mismatch_rejected(catalog):
    scan = LScan("nation", "nation", ("n_nationkey", "n_name"))
    bad = LFilter(scan, Call("eq", Col("nation.n_regionkey"), Lit(1)))
    findings = plan_check.check_plan(bad, catalog)
    errs = _errors(findings)
    assert errs, "schema mismatch must be an error finding"
    f = errs[0]
    assert f.invariant == "schema-agreement"
    assert "n_regionkey" in f.message
    assert "Filter" in f.node  # names the offending op
    with pytest.raises(VerifyError):
        report(findings, level="strict")


def test_schema_join_condition_and_duplicates(catalog):
    l = LScan("nation", "n1", ("n_nationkey",))
    r = LScan("region", "r1", ("r_regionkey",))
    bad = LJoin(l, r, "inner",
                Call("eq", Col("n1.n_nationkey"), Col("r1.r_name")))
    errs = _errors(plan_check.check_plan(bad, catalog))
    assert any(f.invariant == "schema-agreement" and "r_name" in f.message
               for f in errs)
    # ambiguous outputs: same alias+column from both sides
    dup = LJoin(LScan("nation", "n1", ("n_nationkey",)),
                LScan("nation", "n1", ("n_nationkey",)), "inner",
                Call("eq", Col("n1.n_nationkey"), Col("n1.n_nationkey")))
    errs = _errors(plan_check.check_plan(dup, catalog))
    assert any("ambiguous" in f.message or "duplicate" in f.message
               for f in errs)


def test_dtype_mismatch_rejected(catalog):
    # joining an int key against a dict-coded string column compares codes
    # to values
    l = LScan("nation", "n1", ("n_nationkey",))
    r = LScan("region", "r1", ("r_name",))
    bad = LJoin(l, r, "inner",
                Call("eq", Col("n1.n_nationkey"), Col("r1.r_name")))
    findings = plan_check.check_dtypes(bad, catalog)
    assert any(f.invariant == "dtype-agreement" for f in _errors(findings))


# --- golden fixture 2: replicated-operand join without exchange --------------


def test_replicated_join_without_exchange_rejected(catalog):
    from starrocks_tpu.sql.distributed import REPLICATED, SHARDED

    probe = LScan("nation", "n1", ("n_nationkey", "n_name"))
    build = LScan("customer", "c1", ("c_custkey", "c_nationkey"))
    join = LJoin(probe, build, "inner",
                 Call("eq", Col("n1.n_nationkey"), Col("c1.c_nationkey")))
    # declared physical plan: replicated probe x partitioned build, NO
    # exchange — each shard would join the whole probe against one build
    # fragment and the "result" is per-shard garbage
    modes = {id(probe): REPLICATED, id(build): SHARDED}
    findings = plan_check.check_distribution(
        join, catalog, scan_modes=modes, managed_exchanges=False)
    errs = _errors(findings)
    assert any(f.invariant == "distribution"
               and "replicated probe" in f.message
               and "Join" in f.node for f in errs)
    with pytest.raises(VerifyError):
        report(findings, level="strict")
    # same operands WITH compiler-managed exchanges: legal
    clean = plan_check.check_distribution(
        join, catalog, scan_modes=modes, managed_exchanges=True)
    assert not _errors(clean)


def test_uncolocated_sharded_join_needs_exchange(catalog):
    from starrocks_tpu.sql.distributed import SHARDED

    a = LScan("orders", "o", ("o_orderkey", "o_custkey"))
    b = LScan("lineitem", "l", ("l_orderkey",))
    join = LJoin(a, b, "inner",
                 Call("eq", Col("o.o_orderkey"), Col("l.l_orderkey")))
    modes = {id(a): SHARDED, id(b): SHARDED}
    errs = _errors(plan_check.check_distribution(
        join, catalog, scan_modes=modes, managed_exchanges=False))
    assert any("not colocated" in f.message for f in errs)
    # hash-colocated on the join keys: no exchange needed even undeclared
    modes = {id(a): ("hash", "o.o_orderkey"), id(b): ("hash", "l.l_orderkey")}
    plan2 = LAggregate(join, (("k", Col("o.o_orderkey")),),
                       (("n", AggExpr("count", None)),))
    findings = plan_check.check_distribution(
        plan2, catalog, scan_modes=modes, managed_exchanges=False)
    assert not [f for f in _errors(findings) if "Join" in f.node]


# --- golden fixture 3: non-psum profile counter on a sharded stage -----------


def _counter_program(use_psum: bool):
    from starrocks_tpu.parallel.mesh import make_mesh, shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(8)

    def step(x):
        local = jnp.sum(x)  # per-shard count
        ctr = jax.lax.psum(local, "d") if use_psum else local
        return {"~ctr_rows_pruned@0": ctr[None]}

    return shard_map(step, mesh=mesh, in_specs=(P("d"),), out_specs=P("d")), \
        jnp.ones((64,), jnp.int64)


def test_non_psum_counter_rejected(eight_devices):
    raw, x = _counter_program(use_psum=False)
    findings = trace_check.audit_program(raw, x)
    errs = _errors(findings)
    assert any(f.invariant == "non-psum-counter" for f in errs), findings
    with pytest.raises(VerifyError):
        report(findings, level="strict")


def test_psum_counter_clean(eight_devices):
    raw, x = _counter_program(use_psum=True)
    findings = trace_check.audit_program(raw, x)
    assert not [f for f in findings if f.invariant == "non-psum-counter"], \
        findings


# --- golden fixture 3b: counter merged only within axis subgroups ------------


def _grouped_counter_program():
    """A counter psum'd with axis_index_groups: merged WITHIN each 4-device
    subgroup only. On a 2-process mesh the subgroups are the per-process
    slices, so the host merge across processes keeps one group's partial —
    the cross-process merge invariant violation."""
    from starrocks_tpu.parallel.mesh import make_mesh, shard_map
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(8)
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]

    def step(x):
        local = jnp.sum(x)
        ctr = jax.lax.psum(local, "d", axis_index_groups=groups)
        return {"~ctr_rows_pruned@0": ctr[None]}

    return shard_map(step, mesh=mesh, in_specs=(P("d"),), out_specs=P("d")), \
        jnp.ones((64,), jnp.int64)


def test_subgroup_psum_counter_rejected(eight_devices):
    raw, x = _grouped_counter_program()
    findings = trace_check.audit_program(raw, x)
    errs = _errors(findings)
    assert any(f.invariant == "subgroup-psum-counter" for f in errs), findings
    # the grouped merge must NOT also satisfy the plain psum check
    assert not [f for f in findings if f.invariant == "non-psum-counter"], \
        findings
    with pytest.raises(VerifyError):
        report(findings, level="strict")


def test_distributed_corpus_counters_clean(eight_devices, catalog):
    """The REAL distributed compiler's counters must audit clean (they
    psum on sharded stages by construction)."""
    config.set("plan_verify_level", "strict")
    try:
        s = Session(tpch_catalog(sf=0.01), dist_shards=8)
        res = s.sql("select l_returnflag, count(*) n, sum(l_quantity) q "
                    "from lineitem group by l_returnflag order by n desc "
                    "limit 3")
        assert res.table.num_rows == 3
    finally:
        config.set("plan_verify_level", "off")


# --- golden fixture 4: knob read during trace but outside the cache key ------


def test_knob_outside_key_rejected():
    config.define("_test_rogue_knob", 7, True, "verifier fixture knob")
    try:
        with config.record_reads() as reads:
            config.get("_test_rogue_knob")
        findings = key_check.check_trace_reads(reads)
        errs = _errors(findings)
        assert any(f.invariant == "knob-outside-key"
                   and "_test_rogue_knob" in f.node for f in errs)
        with pytest.raises(VerifyError):
            report(findings, level="strict")
    finally:
        config._fields.pop("_test_rogue_knob", None)


def test_declared_trace_knob_clean():
    config.define("_test_keyed_knob", 7, True, "verifier fixture knob",
                  trace=True)
    try:
        with config.record_reads() as reads:
            config.get("_test_keyed_knob")
        assert key_check.check_trace_reads(reads) == []
        # and the declaration alone puts it in the program cache key
        assert ("_test_keyed_knob", 7) in config.trace_key()
    finally:
        config._fields.pop("_test_keyed_knob", None)


def test_engine_trace_reads_are_keyed(catalog):
    """End-to-end round-7 regression: trace a real program, record every
    knob read, and require the read-set to be covered by the key."""
    from starrocks_tpu.sql.analyzer import Analyzer
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse
    from starrocks_tpu.sql.physical import Caps, compile_plan

    plan = optimize(Analyzer(catalog).analyze(parse(
        "select n_name, count(*) c from nation, customer "
        "where n_nationkey = c_nationkey group by n_name")), catalog)
    caps = Caps({})
    with config.record_reads() as reads:
        compiled = compile_plan(plan, catalog, caps)
        # force the actual trace (knob reads inside ops happen here)
        from starrocks_tpu.runtime.executor import DeviceCache

        cache = DeviceCache()
        inputs = tuple(
            cache.chunk_for(catalog.get_table(t), a, cols)
            for t, a, cols in compiled.scans)
        jax.make_jaxpr(compiled.fn)(inputs)
    assert key_check.check_trace_reads(reads) == [], reads


def test_opt_key_covers_optimizer_reads(catalog):
    from starrocks_tpu.sql.analyzer import Analyzer
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse

    plan = Analyzer(catalog).analyze(parse(
        "select * from (select n_name, rank() over (order by n_nationkey) r "
        "from nation) t where r <= 3"))
    with config.record_reads() as reads:
        optimize(plan, catalog)
    assert key_check.check_opt_reads(reads) == [], reads


# --- capacity monotonicity + null semantics ----------------------------------


def test_capacity_monotonicity_flags_non_monotone_estimate(catalog):
    # a Sort claiming more output rows than its limit allows is only
    # constructible by corrupting the estimate: emulate with a bound probe
    scan = LScan("customer", "c", ("c_custkey",))
    sort = LSort(scan, ((Col("c.c_custkey"), True, False),), limit=10)
    assert plan_check._row_bound(sort, catalog) == 10.0
    clean = plan_check.check_capacities(sort, catalog)
    assert not _errors(clean)


def test_null_comparison_warned(catalog):
    scan = LScan("nation", "n", ("n_nationkey",))
    bad = LFilter(scan, Call("eq", Col("n.n_nationkey"), Lit(None)))
    findings = plan_check.check_null_semantics(bad, catalog)
    assert any(f.invariant == "null-semantics" for f in findings)
    assert all(f.severity == "warn" for f in findings)  # advisory only


# --- strict end-to-end through the Session -----------------------------------


def test_strict_mode_executes_clean_queries():
    config.set("plan_verify_level", "strict")
    try:
        s = Session(tpch_catalog(sf=0.001))
        res = s.sql("select n_name from nation order by n_name limit 5")
        assert res.table.num_rows == 5
    finally:
        config.set("plan_verify_level", "off")


# --- the whole corpus verifies clean -----------------------------------------


def _corpus_plans():
    import sys

    sys.path.insert(0, "tests")
    from tests.tpch_queries import QUERIES as TPCH
    from tests.ssb_queries import FLAT_QUERIES as SSB
    from tests.tpcds_queries import QUERIES as TPCDS

    return [("tpch", f"q{k}", v) for k, v in sorted(TPCH.items())] + \
        [("ssb", k, v) for k, v in sorted(SSB.items())] + \
        [("tpcds", k, v) for k, v in sorted(TPCDS.items())]


@pytest.fixture(scope="module")
def corpus_sessions():
    from starrocks_tpu.storage.datagen.ssb import ssb_catalog
    from starrocks_tpu.storage.datagen.tpcds import tpcds_catalog

    return {
        "tpch": Session(tpch_catalog(sf=0.001)),
        "ssb": Session(ssb_catalog(sf=0.002)),
        "tpcds": Session(tpcds_catalog(sf=0.002)),
    }


@pytest.mark.parametrize("suite,name,text", _corpus_plans())
def test_corpus_plan_clean(corpus_sessions, suite, name, text):
    """Every corpus query's optimized plan passes the structural passes
    with zero error findings (warn-severity advisories allowed), and its
    distributed lowering is legal under managed exchanges."""
    from starrocks_tpu.sql.analyzer import Analyzer
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse

    sess = corpus_sessions[suite]
    plan = optimize(Analyzer(sess.catalog).analyze(parse(text)),
                    sess.catalog)
    findings = plan_check.check_plan(plan, sess.catalog)
    findings += plan_check.check_distribution(plan, sess.catalog)
    errs = _errors(findings)
    assert not errs, f"{suite}/{name}: {[str(f) for f in errs]}"
