"""Mesh parallelism tests on the 8-device virtual CPU mesh
(PseudoCluster analog — SURVEY §4)."""

from functools import partial

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from starrocks_tpu.parallel.mesh import shard_map

from starrocks_tpu.column import HostTable
from starrocks_tpu.exprs import AggExpr, col, gt, lit
from starrocks_tpu.ops import filter_chunk
from starrocks_tpu.parallel import (
    BROADCAST, SHUFFLE, broadcast_join, chunk_pspec, dist_aggregate,
    make_mesh, shard_host_table,
)


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh(8)


def _table(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    return HostTable.from_pydict(
        {
            "k": rng.integers(0, 37, n),
            "v": rng.normal(size=n),
        }
    )


def test_shard_host_table(mesh):
    ht = _table()
    g = shard_host_table(ht, mesh)
    assert g.capacity % 8 == 0
    assert int(g.num_rows()) == 4000


@pytest.mark.parametrize("via", [BROADCAST, SHUFFLE])
def test_dist_aggregate_vs_pandas(mesh, via):
    ht = _table()
    g = shard_host_table(ht, mesh)
    specs = chunk_pspec(g)

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=(specs,),
        out_specs=(P("d") if via == SHUFFLE else P(), P("d")),
        check_vma=False,
    )
    def run(local):
        out, ng, _mb, _png = dist_aggregate(
            local,
            group_by=(("k", col("k")),),
            aggs=(("s", AggExpr("sum", col("v"))), ("c", AggExpr("count", None)),
                  ("a", AggExpr("avg", col("v")))),
            axis="d", n_shards=8,
            partial_groups=64, final_groups=64,
            via=via, bucket_capacity=64,
        )
        return out, ng[None]

    out, ng = run(g)
    ng = int(np.asarray(ng)[0]) if via == BROADCAST else int(np.asarray(ng).sum())
    rows = HostTable.from_chunk(out).to_pylist()
    got = pd.DataFrame(rows, columns=["k", "s", "c", "a"]).sort_values("k").reset_index(drop=True)
    df = ht.to_pandas()
    exp = df.groupby("k", as_index=False).agg(
        s=("v", "sum"), c=("v", "size"), a=("v", "mean")
    ).sort_values("k").reset_index(drop=True)
    assert ng == len(exp)
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_allclose(got["s"], exp["s"], rtol=1e-9)
    np.testing.assert_array_equal(got["c"], exp["c"])
    np.testing.assert_allclose(got["a"], exp["a"], rtol=1e-9)


def test_broadcast_join_vs_pandas(mesh):
    rng = np.random.default_rng(5)
    fact = HostTable.from_pydict(
        {"fk": rng.integers(1, 51, 3000), "fv": np.arange(3000)}
    )
    dim = HostTable.from_pydict(
        {"dk": np.arange(1, 51), "dv": rng.normal(size=50)}
    )
    gf = shard_host_table(fact, mesh)
    gd = shard_host_table(dim, mesh)

    run = jax.jit(
        shard_map(
            lambda f_local, d_local: broadcast_join(
                f_local, d_local, (col("fk"),), (col("dk"),), axis="d",
                payload=["dv"],
            )[0],
            mesh=mesh,
            in_specs=(chunk_pspec(gf), chunk_pspec(gd)),
            out_specs=P("d"),
            check_vma=False,
        )
    )
    out = run(gf, gd)
    got = pd.DataFrame(
        HostTable.from_chunk(out).to_pylist(), columns=["fk", "fv", "dv"]
    ).sort_values("fv").reset_index(drop=True)
    exp = fact.to_pandas().merge(
        dim.to_pandas(), left_on="fk", right_on="dk"
    )[["fk", "fv", "dv"]].sort_values("fv").reset_index(drop=True)
    np.testing.assert_array_equal(got["fk"], exp["fk"])
    np.testing.assert_allclose(got["dv"], exp["dv"], rtol=1e-12)


def test_shuffle_exact_full_bucket_no_collision(mesh):
    # regression: dead padding rows must not clobber slots of an exactly-full
    # bucket (they are routed out-of-bounds and dropped)
    from starrocks_tpu.parallel import shuffle_chunk

    ht = HostTable.from_pydict({"k": [7] * 48, "v": list(range(48))})
    g = shard_host_table(ht, mesh)  # 48 live rows + dead padding per shard

    # per-shard scalars need a shard dim: wrap
    run = jax.jit(
        shard_map(
            lambda local: (lambda c, m: (c, m[None]))(
                *shuffle_chunk(local, (col("k"),), "d", 8, 64)
            ),
            mesh=mesh, in_specs=(chunk_pspec(g),),
            out_specs=(P("d"), P("d")), check_vma=False,
        )
    )
    out, mx = run(g)
    assert int(out.num_rows()) == 48  # no rows lost
    vs = sorted(r[1] for r in HostTable.from_chunk(out).to_pylist())
    assert vs == list(range(48))
