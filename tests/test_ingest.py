"""Continuous ingest plane: HTTP stream load, routine-load poller,
micro-batch group commit, txn-label exactly-once, gate footprints,
compaction hygiene, and the enable_ingest_plane kill switch."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from starrocks_tpu.ingest import (
    IngestBackpressure,
    IngestError,
    parse_csv,
    parse_json,
)
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.http_service import SqlHttpServer
from starrocks_tpu.runtime.serving import StatementGate, _read_footprint
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.runtime.workload import WORKLOAD


@pytest.fixture(autouse=True)
def _reset_ingest_knobs():
    yield
    for knob, dflt in (
        ("enable_ingest_plane", True),
        ("ingest_batch_rows", 4096),
        ("ingest_batch_age_ms", 200),
        ("ingest_staging_limit_bytes", 64 << 20),
        ("ingest_compact_commits", 32),
        ("ingest_compact_bytes", 64 << 20),
        ("ingest_poll_interval_s", 0.5),
        ("enable_query_cache", False),
        ("enable_plan_cache", True),
    ):
        try:
            config.set(knob, dflt)
        except KeyError:
            pass


def _mk(s=None, table="ti"):
    """Session + fast-commit plane + a PK table to load into."""
    s = s or Session()
    s.sql(f"create table {table} (k int, v int, primary key (k))")
    plane = s.ingest_plane()
    config.set("ingest_batch_age_ms", 5)
    return s, plane


# --- direct plane API --------------------------------------------------------

def test_load_commits_and_label_replays():
    s, plane = _mk()
    r1 = plane.load(s, "ti", [{"k": 1, "v": 10}, {"k": 2, "v": 20}],
                    label="L1")
    assert r1["rows"] == 2 and r1["table"] == "ti"
    assert not r1.get("replayed")
    assert s.sql("select k, v from ti order by k").rows() == [
        (1, 10), (2, 20)]
    # exactly-once: the same label is a durable no-op answering with the
    # ORIGINAL receipt, and no rows are re-applied
    r2 = plane.load(s, "ti", [{"k": 1, "v": 999}], label="L1")
    assert r2["replayed"] and r2["commit_seq"] == r1["commit_seq"]
    assert s.sql("select v from ti where k = 1").rows() == [(10,)]


def test_load_upserts_on_pk():
    s, plane = _mk()
    plane.load(s, "ti", [{"k": 1, "v": 1}], label="a")
    plane.load(s, "ti", [{"k": 1, "v": 2}], label="b")
    assert s.sql("select v from ti where k = 1").rows() == [(2,)]
    assert s.sql("select count(*) from ti").rows() == [(1,)]


def test_load_rejects_bad_targets_and_rows():
    s, plane = _mk()
    s.sql("create view vw as select k from ti")
    with pytest.raises(IngestError, match="unknown table"):
        plane.load(s, "nope", [{"k": 1}])
    with pytest.raises(IngestError, match="view"):
        plane.load(s, "vw", [{"k": 1}])
    with pytest.raises(IngestError, match="empty load"):
        plane.load(s, "ti", [])
    with pytest.raises(IngestError, match="unknown column"):
        plane.load(s, "ti", [{"k": 1, "zzz": 2}])
    with pytest.raises(IngestError, match="PRIMARY KEY"):
        plane.load(s, "ti", [{"k": None, "v": 2}])
    # nothing staged after the rejections
    assert plane.stats()["staged_bytes"] == 0


def test_group_commit_folds_concurrent_loads():
    s, plane = _mk()
    config.set("ingest_batch_age_ms", 150)
    config.set("ingest_batch_rows", 1_000_000)
    receipts = []

    def one(i):
        receipts.append(plane.load(
            s, "ti", [{"k": i, "v": i}], label=f"g{i}"))

    ts = [threading.Thread(target=one, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # all three requests folded into ONE micro-batch commit
    assert len({r["commit_seq"] for r in receipts}) == 1
    assert all(r["batch_rows"] == 3 for r in receipts)
    assert s.sql("select count(*) from ti").rows() == [(3,)]


def test_backpressure_rejects_before_staging():
    s, plane = _mk()
    config.set("ingest_staging_limit_bytes", 1)
    with pytest.raises(IngestBackpressure):
        plane.load(s, "ti", [{"k": 1, "v": 1}], label="bp")
    assert plane.stats()["staged_bytes"] == 0
    # retry with the SAME label succeeds once the budget recovers
    config.set("ingest_staging_limit_bytes", 64 << 20)
    r = plane.load(s, "ti", [{"k": 1, "v": 1}], label="bp")
    assert not r.get("replayed") and r["rows"] == 1


def test_load_classifies_as_load_workload():
    s, plane = _mk()
    def loads():
        return sum(row["count"] for row in WORKLOAD.snapshot()
                   if row["stmt_class"] == "load")

    before = loads()
    plane.load(s, "ti", [{"k": 7, "v": 7}], label="wl")
    assert loads() > before


# --- body parsing ------------------------------------------------------------

def test_parse_csv_mapping_separator_and_nulls():
    s, _plane = _mk()
    h = s.catalog.get_table("ti")
    assert parse_csv(h, "1,10\n2,20\n") == [
        {"k": 1, "v": 10}, {"k": 2, "v": 20}]
    # explicit column mapping, custom separator, '' and \N as NULL
    assert parse_csv(h, "5|\n6|\\N\n", columns=["k", "v"], sep="|") == [
        {"k": 5, "v": None}, {"k": 6, "v": None}]
    assert parse_csv(h, "9", columns=["k"]) == [{"k": 9}]
    with pytest.raises(IngestError, match="arity"):
        parse_csv(h, "1,2,3")
    with pytest.raises(IngestError, match="unknown column"):
        parse_csv(h, "1", columns=["zzz"])


def test_parse_json_shapes():
    s, _plane = _mk()
    h = s.catalog.get_table("ti")
    assert parse_json(h, '{"k": 1, "v": 2}') == [{"k": 1, "v": 2}]
    assert parse_json(h, '[{"k": 1}, {"K": 2}]') == [{"k": 1}, {"k": 2}]
    assert parse_json(h, '{"rows": [{"k": 3}]}') == [{"k": 3}]
    # NDJSON: one object per line
    assert parse_json(h, '{"k": 1}\n{"k": 2}\n') == [{"k": 1}, {"k": 2}]
    with pytest.raises(IngestError, match="unknown column"):
        parse_json(h, '{"zzz": 1}')
    with pytest.raises(IngestError):
        parse_json(h, '"scalar"')


# --- HTTP stream load --------------------------------------------------------

def _put(port, path, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body.encode(),
        headers=headers or {}, method="PUT")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else {}


def test_http_stream_load_end_to_end():
    srv = SqlHttpServer(Session()).start()
    try:
        sess = srv.tier.template
        sess.sql("create table web (k int, v varchar, primary key (k))")
        sess.ingest_plane()
        config.set("ingest_batch_age_ms", 5)
        # CSV with a label
        code, body = _put(srv.port, "/api/load/web?label=h1", "1,aa\n2,bb\n")
        assert code == 200 and body["status"] == "ok"
        assert body["rows"] == 2 and "ms" in body
        # JSON format
        code, body = _put(srv.port, "/api/load/web?format=json&label=h2",
                          '[{"k": 3, "v": "cc"}]')
        assert code == 200 and body["rows"] == 1
        # column mapping: only k, v fills NULL
        code, body = _put(srv.port, "/api/load/web?columns=k", "4\n")
        assert code == 200
        r = sess.sql("select k, v from web order by k").rows()
        assert r == [(1, "aa"), (2, "bb"), (3, "cc"), (4, None)]
        # label replay answers the ORIGINAL receipt, applies nothing
        code, body = _put(srv.port, "/api/load/web?label=h1", "1,zz\n")
        assert code == 200 and body["replayed"]
        assert sess.sql("select v from web where k = 1").rows() == [("aa",)]
        # parse errors are 400s, unknown table too
        code, body = _put(srv.port, "/api/load/web", "1,2,3\n")
        assert code == 400 and "arity" in body["error"]
        code, _ = _put(srv.port, "/api/load/missing", "1\n")
        assert code == 400
        # backpressure maps to 429
        config.set("ingest_staging_limit_bytes", 1)
        code, body = _put(srv.port, "/api/load/web", "9,x\n")
        assert code == 429 and body["status"] == "backpressure"
        config.set("ingest_staging_limit_bytes", 64 << 20)
        # GET /api/ingest: plane stats + job rows
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/api/ingest") as r:
            doc = json.loads(r.read())
        assert doc["ingest"]["commits"] >= 3
        assert doc["ingest"]["staged_bytes"] == 0
        assert doc["jobs"] == []
    finally:
        srv.stop()


# --- durability: labels and jobs survive restart -----------------------------

def test_label_replay_survives_restart_via_tail_and_image(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s, plane = _mk(s)
    r1 = plane.load(s, "ti", [{"k": 1, "v": 1}], label="dur")
    # journal-tail replay: a fresh process sees the label without any
    # image having been cut
    s2 = Session(data_dir=str(tmp_path / "db"))
    r2 = s2.ingest_plane().load(s2, "ti", [{"k": 1, "v": 99}], label="dur")
    assert r2["replayed"] and r2["commit_seq"] == r1["commit_seq"]
    assert s2.sql("select v from ti where k = 1").rows() == [(1,)]
    # image replay: checkpoint folds the ledger into the image, the tail
    # resets, and the label STILL replays
    s2.checkpoint_metadata()
    s3 = Session(data_dir=str(tmp_path / "db"))
    r3 = s3.ingest_plane().load(s3, "ti", [{"k": 1, "v": 98}], label="dur")
    assert r3["replayed"]
    assert s3.sql("select v from ti where k = 1").rows() == [(1,)]


# --- routine-load poller -----------------------------------------------------

def _wait_until(pred, timeout=8.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_routine_load_job_tails_file_and_persists_offsets(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s, plane = _mk(s)
    config.set("ingest_poll_interval_s", 0.05)
    src = tmp_path / "feed.csv"
    src.write_text("1,10\n2,20\n")
    spec = {"table": "ti", "path": str(src), "format": "csv"}
    s.sql(f"admin set ingest_job 'j1' = '{json.dumps(spec)}'")
    assert _wait_until(
        lambda: s.sql("select count(*) from ti").rows() == [(2,)])
    # appended bytes load incrementally; a HALF-WRITTEN tail line (no
    # newline) must wait for the next tick, not load garbage
    with open(src, "a") as f:
        f.write("3,30\n4,4")
    assert _wait_until(
        lambda: s.sql("select count(*) from ti").rows() == [(3,)])
    time.sleep(0.2)  # extra ticks must NOT load the partial line
    assert s.sql("select count(*) from ti").rows() == [(3,)]
    with open(src, "a") as f:
        f.write("0\n")
    assert _wait_until(
        lambda: s.sql("select v from ti where k = 4").rows() == [(40,)])
    # information_schema.ingest_jobs surfaces the job row
    rows = s.sql(
        "select name, table_name, state, rows_loaded from "
        "information_schema.ingest_jobs").rows()
    assert rows == [("j1", "ti", "RUNNING", 4)]
    # restart: the job + offsets replay, nothing double-loads
    s.checkpoint_metadata()
    plane.poller.stop()  # first incarnation "exits"
    s2 = Session(data_dir=str(tmp_path / "db"))
    plane2 = s2.ingest_plane()
    assert _wait_until(lambda: plane2.poller.stats()["running"])
    time.sleep(0.2)
    assert s2.sql("select count(*) from ti").rows() == [(4,)]
    snap = plane2.poller.snapshot()
    assert snap[0]["offsets"] == {str(src): len(src.read_bytes())}
    # drop stops the (last) poll thread entirely
    s2.sql("admin set ingest_job 'j1' = 'drop'")
    assert plane2.poller.stats() == {"jobs": 0, "running": False}
    assert not any(t.name == "sr-tpu-ingest-poll" and t.is_alive()
                   for t in threading.enumerate())


def test_ingest_job_spec_validation():
    s, plane = _mk()
    with pytest.raises(IngestError, match="table and path"):
        s.sql("admin set ingest_job 'bad' = '{\"path\": \"/tmp/x\"}'")
    with pytest.raises(IngestError, match="unknown table"):
        s.sql('admin set ingest_job \'bad\' = '
              '\'{"table": "nope", "path": "/tmp/x"}\'')
    assert plane.poller.stats() == {"jobs": 0, "running": False}


# --- statement-gate footprints -----------------------------------------------

def test_gate_matrix_table_exclusive_vs_readers():
    g = StatementGate()
    with g.exclusive("x"):
        # ingest commit on x: reads of OTHER tables flow freely...
        assert g.try_shared(frozenset({"y"}))
        g.release_shared(frozenset({"y"}))
        # ...reads of x stall, and so do strong (unknown-footprint) readers
        assert not g.try_shared(frozenset({"x"}))
        assert not g.try_shared(None)
    # commit done: both admit again
    assert g.try_shared(frozenset({"x"}))
    g.release_shared(frozenset({"x"}))
    assert g.try_shared(None)
    g.release_shared(None)


def test_read_footprint_upgrades_via_plan_cache():
    s = Session()
    s.sql("create table base (a int)")
    s.sql("create table other (b int)")
    s.sql("create view v as select a from base")
    cat, cache = s.catalog, s.cache
    # plain table read: token scan already proves the footprint
    assert _read_footprint("select a from base", cat, cache) == \
        frozenset({"base"})
    # view read COLD: not provable by tokens -> strong reader (None)
    assert _read_footprint("select a from v", cat, cache) is None
    # after one execution the analyzed plan is cached and the SAME text
    # upgrades to an exact per-table claim THROUGH the view
    s.sql("select a from v")
    assert _read_footprint("select a from v", cat, cache) == \
        frozenset({"base"})
    # catalog-only reads claim no base table at all (weakest reader)
    s.sql("select 1")
    assert _read_footprint("select 1", cat, cache) == frozenset()
    # non-reads never claim
    assert _read_footprint("insert into base values (1)", cat, cache) \
        is None


# --- kill switch -------------------------------------------------------------

def test_enable_ingest_plane_off_rejects_and_stays_threadless():
    s, plane = _mk()
    config.set("enable_ingest_plane", False)
    with pytest.raises(IngestError, match="disabled"):
        plane.load(s, "ti", [{"k": 1, "v": 1}])
    with pytest.raises(IngestError, match="disabled"):
        s.sql("admin set ingest_job 'j' = '{\"table\":\"ti\","
              "\"path\":\"/tmp/x\"}'")
    plane.poller.ensure_started()
    assert plane.poller.stats()["running"] is False
    assert not any(t.name == "sr-tpu-ingest-poll" and t.is_alive()
                   for t in threading.enumerate())
    # existing statement paths are untouched by the disabled plane
    s.sql("insert into ti values (5, 50)")
    assert s.sql("select v from ti where k = 5").rows() == [(50,)]


# --- small-segment hygiene ---------------------------------------------------

def test_micro_batch_commits_trigger_compaction(tmp_path):
    s = Session(data_dir=str(tmp_path / "db"))
    s, plane = _mk(s)
    config.set("ingest_compact_commits", 3)
    for i in range(3):
        plane.load(s, "ti", [{"k": i, "v": i}], label=f"c{i}")
    # 3 micro-batch commits tripped the trigger: rowsets merged to one
    m = s.store.read_manifest("ti")
    assert len(m["rowsets"]) == 1
    assert s.sql("select count(*) from ti").rows() == [(3,)]
    # debt reset: the next load does NOT immediately re-compact
    plane.load(s, "ti", [{"k": 9, "v": 9}], label="c9")
    assert len(s.store.read_manifest("ti")["rowsets"]) == 2


def test_partial_agg_cache_survives_micro_batches_and_compaction(tmp_path):
    config.set("enable_query_cache", True)
    s = Session(data_dir=str(tmp_path / "db"))
    s.sql("create table agg (k int, v double, primary key (k))")
    plane = s.ingest_plane()
    config.set("ingest_batch_age_ms", 5)
    vals = ",".join(f"({i}, {float(i)})" for i in range(2000))
    s.sql(f"insert into agg values {vals}")
    q = "select k % 5 g, sum(v) sv, count(*) c from agg group by g order by g"
    s.sql(q)  # cold: states cached per segment

    def counters():
        return {k: v for k, (v, _) in s.last_profile.counters.items()}

    # a micro-batch commit lands a NEW segment: the partial tier reuses
    # the cached state for the old one and scans only the delta
    plane.load(s, "agg", [{"k": 2000 + i, "v": float(2000 + i)}
                          for i in range(100)], label="seg2")
    r = s.sql(q)
    c = counters()
    assert c.get("qcache_partial_hits", 0) >= 1
    assert c.get("qcache_rows_saved", 0) >= 2000
    got = {row[0]: (row[1], row[2]) for row in r.rows()}
    for g in range(5):
        vs = [float(i) for i in range(2100) if i % 5 == g]
        assert abs(got[g][0] - sum(vs)) < 1e-3 and got[g][1] == len(vs)
    # force the ingest-side compaction trigger; results must stay exact
    # (the rewritten segment invalidates its states via the store listener)
    config.set("ingest_compact_commits", 1)
    plane.load(s, "agg", [{"k": 5000, "v": 5000.0}], label="seg3")
    assert len(s.store.read_manifest("agg")["rowsets"]) == 1
    got = {row[0]: (row[1], row[2]) for row in s.sql(q).rows()}
    vals = [float(i) for i in range(2100)] + [5000.0]
    for g in range(5):
        vs = [v for v in vals if int(v) % 5 == g]
        assert abs(got[g][0] - sum(vs)) < 1e-3 and got[g][1] == len(vs)
