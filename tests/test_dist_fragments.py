"""Fragment IR: golden plan shapes, declared-placement verification,
byte-identity with the monolithic SPMD path, and a REAL two-process mesh
running a hash-partition exchange through SQL.

The tentpole contract: physical plans split at repartition boundaries
into fragments whose edges are explicit Exchange nodes (the reference's
PlanFragment/ExchangeNode pair); each fragment compiles as its own
program with a DECLARED placement that analysis/plan_check.py verifies
(managed_exchanges=False) instead of re-simulating the compiler; and with
`SET dist_fragments = false` the pre-IR monolithic program remains the
byte-identity anchor.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import starrocks_tpu.sql.distributed as D
from starrocks_tpu.analysis import plan_check
from starrocks_tpu.runtime.config import config
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.sql.distributed import REPLICATED, SHARDED
from starrocks_tpu.sql.logical import LExchange, walk_plan
from starrocks_tpu.storage.catalog import tpch_catalog

from tpch_queries import QUERIES


@pytest.fixture(scope="module")
def sess(eight_devices):
    old = D.SHARD_THRESHOLD_ROWS
    old_sh = D.SHUFFLE_AGG_MIN_GROUPS
    D.SHARD_THRESHOLD_ROWS = 10_000  # SF0.01: lineitem+orders(>=15k) shard
    D.SHUFFLE_AGG_MIN_GROUPS = 4_000
    yield Session(tpch_catalog(sf=0.01), dist_shards=8)
    D.SHARD_THRESHOLD_ROWS = old
    D.SHUFFLE_AGG_MIN_GROUPS = old_sh


def _ir_of(sess, sql):
    """Run `sql` in fragment mode and return its FragmentIR."""
    if sess.__dict__.get("_dist_executor"):
        sess._dist_executor._frag_ir_memo.clear()
    config.set("dist_fragments", True)
    sess.sql(sql)
    de = sess._dist_executor
    new = list(de._frag_ir_memo.values())
    assert len(new) == 1, "expected exactly one new fragment IR"
    ir, _scans = new[0]
    return ir


def _kinds(ir):
    return [(ev.kind, ev.payload) for ev in ir.events]


# --- golden plan shapes -------------------------------------------------------


def test_scan_only_fragments(sess):
    """Sharded filter-scan: one interior fragment + the coordinator-gather
    sink — the minimal two-fragment plan."""
    ir = _ir_of(sess, "select l_orderkey, l_quantity from lineitem "
                      "where l_quantity < 3")
    assert len(ir.fragments) == 2
    assert _kinds(ir) == [("gather", "rows")]
    interior, sink = ir.fragments
    assert not interior.sink and interior.deps == ()
    assert sink.sink and sink.deps == (interior.fid,)
    assert sink.out_mode == REPLICATED
    assert ir.events[0].out_mode == REPLICATED


def test_hash_join_fragments(sess):
    """Q18: the semi-join's build side hash-repartitions ROWS onto the
    probe's placement — the shuffle-join exchange — then the TopN gathers."""
    ir = _ir_of(sess, QUERIES[18])
    assert len(ir.fragments) == 3
    assert _kinds(ir) == [("hash", "rows"), ("gather", "topn")]
    shuffle = ir.events[0]
    assert shuffle.out_mode == ("hash", "orders.o_orderkey")
    assert shuffle.keys, "hash exchange must declare its partition keys"
    assert ir.fragments[-1].sink


def test_broadcast_join_fragments(sess):
    """Q10: the smaller sharded build side broadcasts (all-gather) to
    every shard instead of repartitioning both sides."""
    ir = _ir_of(sess, QUERIES[10])
    assert len(ir.fragments) == 3
    assert _kinds(ir) == [("broadcast", "rows"), ("gather", "partial")]
    assert ir.events[0].out_mode == REPLICATED


def test_shuffle_agg_fragments(sess):
    """Multi-key high-cardinality group-by: partial agg states hash-
    partition by the group keys (shuffle-final aggregation)."""
    old = D.SHUFFLE_AGG_MIN_GROUPS
    D.SHUFFLE_AGG_MIN_GROUPS = 100
    try:
        ir = _ir_of(
            sess,
            "select l_suppkey, l_linestatus, sum(l_quantity) q "
            "from lineitem group by l_suppkey, l_linestatus "
            "order by q desc, l_suppkey limit 5")
    finally:
        D.SHUFFLE_AGG_MIN_GROUPS = old
    assert _kinds(ir) == [("hash", "partial"), ("gather", "topn")]
    assert ir.events[0].out_mode == SHARDED  # multi-key: no single token
    assert len(ir.events[0].keys) == 2


def test_annotated_plan_passes_declared_check(sess):
    """The annotated plan (explicit LExchange edges) must verify in
    DECLARED mode: plan_check checks the declarations instead of
    re-simulating the compiler's exchange decisions."""
    for sql in (QUERIES[10], QUERIES[18]):
        ir = _ir_of(sess, sql)
        n_ex = len({id(n) for n in walk_plan(ir.annotated)
                    if isinstance(n, LExchange)})
        assert n_ex == len(ir.events)
        findings = plan_check.check_distribution(
            ir.annotated, sess.catalog, managed_exchanges=False)
        errs = [f for f in findings if f.severity == "error"]
        assert errs == [], [str(f) for f in errs]


# --- byte identity with the monolithic pre-IR path ----------------------------


@pytest.mark.parametrize("qid", [1, 3, 10, 18])
def test_fragment_rows_byte_identical_to_monolithic(sess, qid):
    config.set("dist_fragments", True)
    rf = sess.sql(QUERIES[qid]).rows()
    try:
        config.set("dist_fragments", False)
        rm = sess.sql(QUERIES[qid]).rows()
    finally:
        config.set("dist_fragments", True)
    assert len(rf) == len(rm)
    for a, b in zip(rf, rm):
        va = list(a.values()) if isinstance(a, dict) else list(a)
        vb = list(b.values()) if isinstance(b, dict) else list(b)
        assert va == vb  # exact, not approx: same ops in the same order


def test_fragment_stats_on_profile(sess):
    config.set("dist_fragments", True)
    sess.sql(QUERIES[10])
    prof = sess.last_profile
    assert prof.infos.get("fragments", 0) >= 3
    assert prof.infos.get("exchanges", 0) >= 2
    assert prof.counters.get("exchange_rows", (0,))[0] > 0
    assert prof.counters.get("exchange_bytes", (0,))[0] > 0


# --- two REAL processes: hash exchange over the global mesh -------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_fragment_sql():
    """Spawns two processes that join one global mesh (jax.distributed
    over gloo — the CPU stand-in for DCN) and run the SAME SQL through
    the fragment executor: per-process table slices placed with
    make_array_from_callback, a hash-partition exchange and the counter
    psums crossing the process boundary in-program."""
    coord = f"127.0.0.1:{_free_port()}"
    worker = os.path.join(os.path.dirname(__file__), "dist_sql_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for pid in (0, 1)
    ]
    outs, rcs = [], []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
        rcs.append(p.returncode)
    joined = "\n".join(outs)
    if any(rc != 0 for rc in rcs) and (
        "Multiprocess computations aren't implemented" in joined
        or "multiprocess computations" in joined.lower()
    ):
        # this jaxlib build ships without the gloo CPU collective backend
        # (an environment property, not a code regression)
        pytest.skip("jaxlib lacks CPU multiprocess (gloo) collectives")
    for out, rc in zip(outs, rcs):
        assert rc == 0, out[-2000:]
    assert "sql ok=True" in joined
    assert "spans_processes=True" in joined


def test_two_process_fragment_sql_host_exchange():
    """The gloo-free two-process run: the in-mesh variant above needs CPU
    multiprocess collectives this image ships without, so here the SAME
    contract — one SQL query whose hash exchange crosses a REAL process
    boundary — rides the host exchange plane instead: a coordinator
    process schedules every fragment onto one spawned worker process
    (runtime/cluster_exec.py), boundary payloads crossing as columnar
    batches over sockets."""
    from starrocks_tpu.runtime.cluster_exec import ClusterRuntime

    old, old_sh = D.SHARD_THRESHOLD_ROWS, D.SHUFFLE_AGG_MIN_GROUPS
    old_dist = config.get("dist_fragments")
    D.SHARD_THRESHOLD_ROWS = 100
    D.SHUFFLE_AGG_MIN_GROUPS = 10
    try:
        s = Session(dist_shards=2)
        s.sql("create table t (a int, b int)")
        s.sql("insert into t values "
              + ", ".join(f"({i % 97}, {i % 7})" for i in range(400)))
        s.sql("create table d (k int, v int)")
        s.sql("insert into d values "
              + ", ".join(f"({i}, {i * 10})" for i in range(97)))
        config.set("dist_fragments", True)
        sql = ("select d.v, sum(t.b) s from t join d on t.a = d.k "
               "group by d.v order by s desc, d.v limit 5")
        oracle = s.sql(sql).rows()
        # the fragment IR really carries a hash-partition exchange
        irs = list(s._dist_executor._frag_ir_memo.values())
        assert any(ev.kind == "hash"
                   for ir, _scans in irs for ev in ir.events)
        cr = ClusterRuntime(n_workers=1, shards=2).start(s)
        try:
            cr.attach(s)
            got = s.sql(sql + " ").rows()  # pad: dodge the query cache
            assert got == oracle
            # every fragment (incl. both sides of the hash exchange)
            # executed in the OTHER process
            assert cr.stats()["fragments_total"] >= 3
        finally:
            s.catalog.cluster_runtime = None
            cr.stop()
    finally:
        config.set("dist_fragments", old_dist)
        D.SHARD_THRESHOLD_ROWS = old
        D.SHUFFLE_AGG_MIN_GROUPS = old_sh
