"""DECIMAL(19..38) beyond SUM: compares, WHERE, ORDER BY, min/max, join
keys, multiply, and wide columns through the distributed exchange
(VERDICT r4 item 8; reference: be/src/runtime/decimalv3.h int128 paths)."""

import decimal

import pytest

from starrocks_tpu.column import HostTable
from starrocks_tpu.runtime.session import Session
from starrocks_tpu.storage.catalog import Catalog

decimal.getcontext().prec = 60  # test arithmetic must not round at 28 digits
D = decimal.Decimal

BIG = [D("123456789012345678901234567.89"), D("-9876543210987654321.01"),
       D("0.01"), D("-0.01"), D("99999999999999999999999999999999.99"),
       None]


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.sql("CREATE TABLE d (id BIGINT, v DECIMAL(30, 2))")
    vals = ", ".join(
        f"({i}, {v})" if v is not None else f"({i}, NULL)"
        for i, v in enumerate(BIG))
    s.sql(f"INSERT INTO d VALUES {vals}")
    return s


def test_where_and_compare(sess):
    r = sess.sql("SELECT id FROM d WHERE v > 0 ORDER BY id").rows()
    assert r == [(0,), (2,), (4,)]
    r = sess.sql("SELECT id FROM d WHERE v <= -0.01 ORDER BY id").rows()
    assert r == [(1,), (3,)]
    r = sess.sql("SELECT id FROM d "
                 "WHERE v = 123456789012345678901234567.89").rows()
    assert r == [(0,)]
    r = sess.sql("SELECT id FROM d WHERE v BETWEEN -1 AND 1 "
                 "ORDER BY id").rows()
    assert r == [(2,), (3,)]


def test_order_by_dec128(sess):
    r = sess.sql("SELECT id FROM d WHERE v IS NOT NULL "
                 "ORDER BY v").rows()
    assert [x[0] for x in r] == [1, 3, 2, 0, 4]
    r = sess.sql("SELECT id FROM d WHERE v IS NOT NULL "
                 "ORDER BY v DESC").rows()
    assert [x[0] for x in r] == [4, 0, 2, 3, 1]


def test_min_max_group(sess):
    r = sess.sql("SELECT min(v), max(v) FROM d").rows()[0]
    assert r[0] == min(v for v in BIG if v is not None)
    assert r[1] == max(v for v in BIG if v is not None)
    r = sess.sql("SELECT id % 2 AS g, min(v), max(v) FROM d "
                 "WHERE v IS NOT NULL GROUP BY g ORDER BY g").rows()
    evens = [BIG[i] for i in (0, 2, 4)]
    odds = [BIG[i] for i in (1, 3)]
    assert r == [(0, min(evens), max(evens)), (1, min(odds), max(odds))]


def test_add_sub_multiply(sess):
    r = sess.sql("SELECT v + v, v - v, v * 2 FROM d WHERE id = 0").rows()[0]
    assert r[0] == BIG[0] * 2
    assert r[1] == D("0.00")
    assert r[2] == BIG[0] * 2
    # dec64 * dec64 overflowing scale 18 now promotes to DECIMAL128
    s2 = Session()
    s2.sql("CREATE TABLE m (a DECIMAL(18, 10), b DECIMAL(18, 10))")
    s2.sql("INSERT INTO m VALUES (12345678.9876543210, 2.5)")
    got = s2.sql("SELECT a * b FROM m").rows()[0][0]
    assert got == D("12345678.9876543210") * D("2.5000000000")


def test_divide_via_double(sess):
    r = sess.sql("SELECT v / 2 FROM d WHERE id = 1").rows()[0][0]
    assert r == pytest.approx(float(BIG[1]) / 2, rel=1e-12)


def test_dec128_join_key(sess):
    s = Session()
    s.sql("CREATE TABLE l (k DECIMAL(28, 2), tag VARCHAR)")
    s.sql("CREATE TABLE r (k DECIMAL(28, 2), v BIGINT)")
    s.sql("INSERT INTO l VALUES (12345678901234567890.12, 'a'), "
          "(-5.50, 'b'), (7.00, 'c')")
    s.sql("INSERT INTO r VALUES (12345678901234567890.12, 1), "
          "(-5.50, 2), (8.00, 3)")
    rows = s.sql("SELECT l.tag, r.v FROM l JOIN r ON l.k = r.k "
                 "ORDER BY l.tag").rows()
    assert rows == [("a", 1), ("b", 2)]


def test_wide_columns_cross_distributed_exchange(eight_devices):
    """ARRAY and DECIMAL128 columns survive the all_to_all shuffle: a
    sharded group-by whose output carries wide columns matches single-chip."""
    cat = Catalog()
    n = 4000
    cat.register("w", HostTable.from_pydict({
        "g": [i % 37 for i in range(n)],
        "v": [D(f"{(i * 7919) % 100000}.{i % 100:02d}") * D(10) ** 15
              for i in range(n)],
        "arr": [[i % 5, i % 3] for i in range(n)],
    }, types={"v": __import__("starrocks_tpu.types", fromlist=["DECIMAL"]
                              ).DECIMAL(30, 2)}))
    q = ("SELECT g, sum(v), min(v), max(v), sum(array_sum(arr)) FROM w "
         "GROUP BY g ORDER BY g")
    single = Session(cat).sql(q).rows()
    dist = Session(cat, dist_shards=8).sql(q).rows()
    assert dist == single


def test_dec128_in_list(sess):
    r = sess.sql("SELECT id FROM d WHERE v IN (0.01, -0.01, 5) "
                 "ORDER BY id").rows()
    assert r == [(2,), (3,)]
    r = sess.sql("SELECT id FROM d WHERE v IN "
                 "(123456789012345678901234567.89)").rows()
    assert r == [(0,)]
