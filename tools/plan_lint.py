#!/usr/bin/env python
"""Plan lint CLI: run the static verifier over the full query corpus.

For every TPC-H / SSB / TPC-DS corpus query this executes the query at a
tiny scale factor with `plan_verify_level=strict`, which exercises all
three analysis passes through the production wiring (plan verifier on the
optimized plan, trace auditor + cache-key completeness on every fresh
compile), plus the distribution pass statically per plan. Any error-
severity finding fails the run (exit 1) with the op and the violated
invariant named.

Usage:
  python tools/plan_lint.py --corpus           # all three corpora
  python tools/plan_lint.py --corpus --suite tpch
  python tools/plan_lint.py --corpus --qcache  # + query cache on, 2 runs/query
  python tools/plan_lint.py --sql "select ..." # ad-hoc statement (TPC-H cat)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _suites(which):
    if which in ("tpch", "all"):
        from starrocks_tpu.storage.catalog import tpch_catalog
        from tpch_queries import QUERIES as TPCH

        yield ("tpch", tpch_catalog(sf=0.01),
               {f"q{k}": v for k, v in sorted(TPCH.items())})
    if which in ("ssb", "all"):
        from starrocks_tpu.storage.datagen.ssb import ssb_catalog
        from ssb_queries import FLAT_QUERIES

        yield ("ssb", ssb_catalog(sf=0.005), dict(sorted(FLAT_QUERIES.items())))
    if which in ("tpcds", "all"):
        from starrocks_tpu.storage.datagen.tpcds import tpcds_catalog
        from tests.tpcds_queries import QUERIES as TPCDS

        yield ("tpcds", tpcds_catalog(sf=0.01), dict(sorted(TPCDS.items())))


def lint_corpus(which: str = "all", verbose: bool = False,
                qcache: bool = False) -> int:
    import logging

    from starrocks_tpu import analysis
    from starrocks_tpu.analysis import VerifyError
    from starrocks_tpu.analysis.plan_check import check_distribution
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session

    handler = logging.StreamHandler(sys.stderr)
    analysis.logger.addHandler(handler)
    analysis.logger.setLevel(logging.WARNING)

    config.set("plan_verify_level", "strict")
    if qcache:
        # query cache on: run every query TWICE so both the store path
        # (result-key completeness audit of the real knob read-set) and
        # the validated-hit path run under strict
        config.set("enable_query_cache", True)
    if not config.get("compilation_cache_dir"):
        # share the tier-1 suite's persistent XLA cache: lint re-traces
        # every program (that is the point) but compiles stay warm
        config.set("compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".xla_cache"), force=True)

    t0 = time.time()
    n_queries = errors = 0
    findings_before = analysis.findings_total()
    for suite, catalog, queries in _suites(which):
        sess = Session(catalog)
        for name, text in queries.items():
            n_queries += 1
            tq = time.time()
            status = "ok"
            try:
                res = sess.sql(text)
                if qcache:
                    res = sess.sql(text)  # the validated-hit path
                # distribution pass, statically (the single-process corpus
                # run never enters the distributed executor)
                analysis.report(
                    check_distribution(res.plan, sess.catalog),
                    res.profile, level="strict", where=f"{suite}/{name}")
            except VerifyError as e:
                errors += 1
                status = "VERIFY-FAIL"
                print(f"{suite}/{name}: {e}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — lint shouldn't die mid-run
                errors += 1
                status = f"ERROR {type(e).__name__}: {str(e)[:200]}"
                print(f"{suite}/{name}: {status}", file=sys.stderr)
            if verbose or status != "ok":
                print(f"  {suite}/{name}: {status} "
                      f"({time.time() - tq:.1f}s)", file=sys.stderr)
    summary = {
        "metric": "plan_lint_corpus",
        **({"qcache": True} if qcache else {}),
        "queries": n_queries,
        "strict_failures": errors,
        "findings": analysis.findings_total() - findings_before,
        "seconds": round(time.time() - t0, 1),
    }
    print(json.dumps(summary))
    return 1 if errors else 0


def _rows_equal(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        va = list(ra.values()) if isinstance(ra, dict) else list(ra)
        vb = list(rb.values()) if isinstance(rb, dict) else list(rb)
        if va != vb:
            return False
    return True


def lint_fragments(which: str = "all", verbose: bool = False) -> int:
    """Fragment-IR corpus pass: every query runs on the 8-shard mesh in
    fragment mode under strict verification (declared-placement check of
    the annotated plan + trace audit of every fragment program), then
    again through the monolithic pre-IR program — rows must be
    byte-identical (same ops in the same order, not approximately equal).
    """
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                               + os.environ.get("XLA_FLAGS", ""))
    import logging

    from starrocks_tpu import analysis
    from starrocks_tpu.analysis import VerifyError
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    import starrocks_tpu.sql.distributed as D

    handler = logging.StreamHandler(sys.stderr)
    analysis.logger.addHandler(handler)
    analysis.logger.setLevel(logging.WARNING)

    # corpus scale factors are tiny; force the distributed path anyway
    D.SHARD_THRESHOLD_ROWS = 10_000
    D.SHUFFLE_AGG_MIN_GROUPS = 4_000
    config.set("plan_verify_level", "strict")
    if not config.get("compilation_cache_dir"):
        config.set("compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".xla_cache"), force=True)

    t0 = time.time()
    n_queries = errors = mismatches = 0
    tot_frags = tot_exchanges = 0
    for suite, catalog, queries in _suites(which):
        sess = Session(catalog, dist_shards=8)
        for name, text in queries.items():
            n_queries += 1
            status = "ok"
            try:
                config.set("dist_fragments", True)
                rf = sess.sql(text).rows()
                config.set("dist_fragments", False)
                rm = sess.sql(text).rows()
                if not _rows_equal(rf, rm):
                    mismatches += 1
                    status = "ROW-MISMATCH vs monolithic"
                    print(f"{suite}/{name}: {status}", file=sys.stderr)
            except VerifyError as e:
                errors += 1
                status = "VERIFY-FAIL"
                print(f"{suite}/{name}: {e}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — lint shouldn't die mid-run
                errors += 1
                status = f"ERROR {type(e).__name__}: {str(e)[:200]}"
                print(f"{suite}/{name}: {status}", file=sys.stderr)
            finally:
                config.set("dist_fragments", True)
            if verbose or status != "ok":
                print(f"  {suite}/{name}: {status}", file=sys.stderr)
        de = sess.__dict__.get("_dist_executor")
        if de is not None:
            for ir, _scans in de._frag_ir_memo.values():
                tot_frags += len(ir.fragments)
                tot_exchanges += len(ir.events)
    summary = {
        "metric": "plan_lint_fragments",
        "queries": n_queries,
        "strict_failures": errors,
        "row_mismatches": mismatches,
        "fragments": tot_frags,
        "exchanges": tot_exchanges,
        "seconds": round(time.time() - t0, 1),
    }
    print(json.dumps(summary))
    return 1 if errors or mismatches else 0


def lint_sql(text: str) -> int:
    from starrocks_tpu.analysis import VerifyError
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.storage.catalog import tpch_catalog

    config.set("plan_verify_level", "strict")
    sess = Session(tpch_catalog(sf=0.01))
    try:
        sess.sql(text)
    except VerifyError as e:
        print(e, file=sys.stderr)
        return 1
    print("clean")
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--corpus", action="store_true",
                    help="lint every corpus query")
    ap.add_argument("--suite", default="all",
                    choices=["all", "tpch", "ssb", "tpcds"])
    ap.add_argument("--sql", default=None, help="lint one ad-hoc statement")
    ap.add_argument("--qcache", action="store_true",
                    help="enable the query cache and run each corpus query "
                         "twice: strict-audits the result cache key (store "
                         "path) and the validated-hit path")
    ap.add_argument("--fragments", action="store_true",
                    help="fragment-IR corpus pass on the 8-shard mesh: "
                         "strict declared-placement verification plus "
                         "byte-identity against the monolithic pre-IR "
                         "program")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    if args.sql:
        return lint_sql(args.sql)
    if args.fragments:
        return lint_fragments(args.suite, args.verbose)
    if args.corpus:
        return lint_corpus(args.suite, args.verbose, qcache=args.qcache)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
