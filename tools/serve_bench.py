#!/usr/bin/env python
"""Sustained mixed-workload serving benchmark (ROADMAP item 2).

Drives the serving tier (runtime/serving.py) the way a dashboard fleet
does: N client threads over REAL MySQL-wire and HTTP connections, firing
a Zipfian-weighted mix of TPC-H(+SSB-flat) statements against one shared
tier, and reports client-observed latency percentiles, sustained QPS,
admission/pool queue wait, and cache-hit rates — the first concurrency
numbers in the bench trajectory.

Phases:
  1. **setup/warmup** — build the in-memory TPC-H (and optionally SSB
     flat) catalog, start one MySQL + one HTTP front door over a shared
     ServingTier, run every template once so trace+compile costs are paid
     up front (the engine compiles per distinct plan; a serving mix keys
     the same programs afterwards).
  2. **cold** — `enable_query_cache=off`: every statement executes for
     real (planning + device dispatch) through the priority pool. Run
     twice: pool=1 (forced single-thread serialization — the pre-round-12
     behavior) and pool=N, same duration; their QPS ratio is the
     concurrency speedup on THIS box.
  3. **warm** — `enable_query_cache=on`: statements repeat Zipfian-hot,
     so most answers ride the plan-cache + result-cache inline fast path;
     reports warm p50/p99 and fast-path/cache hit rates.
  4. optional **--chaos** — arms a handful of failpoints (times-bounded)
     mid-run; the run must finish with zero leaked slots/bytes/registry
     entries and an acyclic lock-witness graph.
  5. **feedback** — in-process A/B of the plan-feedback loop (ISSUE 11):
     learn/repeat/steady passes with `plan_feedback` off vs on; the on
     arm must pre-tighten the restart-analog repeat pass to zero
     adaptive recompiles and hold steady-state fresh compiles at zero.
  6. **obs** — observability-plane overhead A/B (audit log +
     metrics-history sampler on vs off, interleaved rounds): warm
     fast-path p50 and point-lane p50 must regress <5% with the
     defaults ON (`--obs` runs just this phase; `--no-obs` skips it).
  7. **--ingest** — continuous-ingest phase: sustained HTTP stream-load
     lanes into one PK table under live analytic + point serving of a
     DIFFERENT table, reporting ingest_rows_s, staged->visible
     freshness p50, serving p99 under ingest vs baseline, and the idle
     cost of the enabled-but-unused plane.

Summary JSON prints on the last line (the driver's bench contract);
--detail merges a "serve" section into BENCH_DETAIL.json.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


# --- query mix ----------------------------------------------------------------

# parameterized dashboard-style templates; each (template, param) combo is
# one distinct statement text. Plans key compiled programs by literal
# values, so the warmup pays one compile per combo — keep the cross
# product modest and the Zipf head hot.
TPCH_TEMPLATES = [
    ("returns_by_flag",
     "select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
     "from lineitem where l_shipdate <= date '{d}' "
     "group by l_returnflag, l_linestatus order by l_returnflag, "
     "l_linestatus",
     [{"d": d} for d in ("1998-09-02", "1998-06-30", "1998-03-31")]),
    ("revenue_window",
     "select sum(l_extendedprice * l_discount) from lineitem "
     "where l_discount between {lo} and {hi} and l_quantity < {q}",
     [{"lo": 0.05, "hi": 0.07, "q": 24},
      {"lo": 0.03, "hi": 0.05, "q": 25},
      {"lo": 0.06, "hi": 0.08, "q": 24}]),
    ("orders_by_priority",
     "select o_orderpriority, count(*) from orders "
     "where o_orderdate >= date '{d}' group by o_orderpriority "
     "order by o_orderpriority",
     [{"d": d} for d in ("1995-01-01", "1996-01-01", "1997-01-01")]),
    ("top_customers",
     "select c_name, sum(o_totalprice) as spend from customer "
     "join orders on c_custkey = o_custkey group by c_name "
     "order by spend desc limit {k}",
     [{"k": 10}, {"k": 20}]),
    ("nation_mix",
     "select n_name, count(*) from customer "
     "join nation on c_nationkey = n_nationkey group by n_name "
     "order by n_name",
     [{}]),
]

SSB_TEMPLATES = [
    ("ssb_q11",
     "select sum(lo_extendedprice * lo_discount) as revenue "
     "from lineorder_flat where lo_discount between {lo} and {hi} "
     "and lo_quantity < {q}",
     [{"lo": 1, "hi": 3, "q": 25}, {"lo": 4, "hi": 6, "q": 35}]),
]


def build_statements(include_ssb: bool) -> list:
    out = []
    for name, tpl, params in TPCH_TEMPLATES:
        for i, p in enumerate(params):
            out.append((f"{name}#{i}", tpl.format(**p)))
    if include_ssb:
        for name, tpl, params in SSB_TEMPLATES:
            for i, p in enumerate(params):
                out.append((f"{name}#{i}", tpl.format(**p)))
    return out


def zipf_weights(n: int, s: float = 1.1) -> list:
    w = [1.0 / (i + 1) ** s for i in range(n)]
    total = sum(w)
    return [x / total for x in w]


# --- clients ------------------------------------------------------------------


class HttpClient:
    """Keep-alive HTTP /query client (one per thread)."""

    def __init__(self, port: int):
        self.conn = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=120)

    def query(self, sql: str):
        body = json.dumps({"sql": sql})
        self.conn.request("POST", "/query", body,
                          {"Content-Type": "application/json"})
        resp = self.conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"http {resp.status}: {data[:200]!r}")
        return json.loads(data)

    def close(self):
        self.conn.close()


def _drain_metrics():
    from starrocks_tpu.cache.query_cache import QCACHE_HITS
    from starrocks_tpu.runtime.serving import (
        SERVE_FAST_PATH, SERVE_QUEUE_WAIT_MS, SERVE_STATEMENTS)
    from starrocks_tpu.runtime.workgroup import (
        ADMISSION_ADMITTED, ADMISSION_QUEUE_WAIT_MS)

    return {
        "fast_path": SERVE_FAST_PATH.value,
        "statements": SERVE_STATEMENTS.value,
        "pool_wait_ms": SERVE_QUEUE_WAIT_MS.value,
        "qcache_hits": QCACHE_HITS.value,
        "admitted": ADMISSION_ADMITTED.value,
        "admission_wait_ms": ADMISSION_QUEUE_WAIT_MS.value,
    }


def run_phase(mysql_port: int, http_port: int, statements, weights,
              threads: int, seconds: float, http_frac: float,
              seed: int = 7) -> dict:
    """One timed phase: `threads` clients (a `http_frac` fraction over
    HTTP, the rest MySQL wire), each firing Zipfian-weighted statements
    until the deadline. Returns client-observed latency stats + metric
    deltas."""
    from test_mysql_protocol import MiniMySQLClient

    m0 = _drain_metrics()
    latencies: list = []
    errors: list = []
    lat_lock = threading.Lock()
    stop_at = [0.0]
    # two-phase start: (1) every client connected, (2) deadline armed —
    # the measured window must not start while connects are in flight
    barrier_conn = threading.Barrier(threads + 1)
    barrier_go = threading.Barrier(threads + 1)

    def client_loop(i: int):
        rng = random.Random(seed * 1000 + i)
        is_http = i < threads * http_frac
        cli = None
        try:
            time.sleep((i % 8) * 0.01)  # stagger the connect burst
            cli = (HttpClient(http_port) if is_http
                   else MiniMySQLClient("127.0.0.1", mysql_port))
        except Exception as e:  # noqa: BLE001
            errors.append(f"connect[{i}]: {e!r}")
        my: list = []
        barrier_conn.wait()
        barrier_go.wait()
        if cli is None:
            return
        while time.monotonic() < stop_at[0]:
            sql = rng.choices(statements, weights=weights, k=1)[0][1]
            t0 = time.perf_counter()
            try:
                cli.query(sql)
            except Exception as e:  # noqa: BLE001
                errors.append(f"{type(e).__name__}: {e}"[:200])
                continue
            my.append((time.perf_counter() - t0) * 1000.0)
        with lat_lock:
            latencies.extend(my)
        try:
            (cli.close if is_http else cli.quit)()
        except Exception:  # noqa: BLE001
            pass

    ts = [threading.Thread(target=client_loop, args=(i,), daemon=True)
          for i in range(threads)]
    for t in ts:
        t.start()
    barrier_conn.wait()  # every client finished connecting (or gave up)
    stop_at[0] = time.monotonic() + seconds
    t_start = time.monotonic()
    barrier_go.wait()    # clock armed: release the fleet
    for t in ts:
        t.join(timeout=seconds + 120)
    wall = time.monotonic() - t_start
    m1 = _drain_metrics()
    latencies.sort()

    def pct(p: float) -> float:
        if not latencies:
            return 0.0
        return latencies[min(int(len(latencies) * p), len(latencies) - 1)]

    n = len(latencies)
    stmts = max(m1["statements"] - m0["statements"], 1)
    return {
        "requests": n,
        "wall_s": round(wall, 2),
        "qps": round(n / wall, 1) if wall > 0 else 0.0,
        "p50_ms": round(pct(0.50), 3),
        "p95_ms": round(pct(0.95), 3),
        "p99_ms": round(pct(0.99), 3),
        "queue_wait_ms": round(
            (m1["pool_wait_ms"] - m0["pool_wait_ms"]
             + m1["admission_wait_ms"] - m0["admission_wait_ms"])
            / stmts, 3),
        "fast_path_rate": round(
            (m1["fast_path"] - m0["fast_path"]) / stmts, 3),
        "cache_hit_rate": round(
            (m1["qcache_hits"] - m0["qcache_hits"]) / stmts, 3),
        "errors": len(errors),
        "error_sample": errors[:3],
    }


def _pct(sorted_ms: list, p: float) -> float:
    if not sorted_ms:
        return 0.0
    return sorted_ms[min(int(len(sorted_ms) * p), len(sorted_ms) - 1)]


def _run_mixed_lane_phase(s, nrows: int, seconds: float) -> dict:
    """Mixed serving: analytic scans + point lookups + a per-second DML
    pulse against ONE tier over the SAME store-backed table, reporting
    per-lane latency. The per-table statement gate is what keeps the
    point lane inline here; the analytic lane and the DML pulse
    serialize against each other exactly as the correctness contract
    demands."""
    from starrocks_tpu.runtime.serving import ServingTier

    tier = ServingTier(s, pool_size=2)
    try:
        warm = tier.new_session()
        aq = "select count(*) c, sum(n) s from point_kv where n >= 0"
        tier.execute(warm, aq)  # pay the analytic compile up front
        buckets: dict = {"point": [], "analytic": [], "dml": []}
        lock = threading.Lock()
        stop_at = time.monotonic() + seconds

        def loop(lane: str, mk):
            sess = tier.new_session()
            my: list = []
            while time.monotonic() < stop_at:
                sql = mk()
                t0 = time.perf_counter()
                try:
                    tier.execute(sess, sql)
                except Exception:  # noqa: BLE001
                    continue
                my.append((time.perf_counter() - t0) * 1000.0)
                if lane == "dml":
                    time.sleep(0.5)  # per-second DML pulse, not a flood
            with lock:
                buckets[lane].extend(my)

        rp1, rp2, rd = (random.Random(101), random.Random(102),
                        random.Random(103))
        ts = [
            threading.Thread(target=loop, args=("analytic", lambda: aq),
                             daemon=True),
            threading.Thread(target=loop, args=(
                "point", lambda: "select v, n from point_kv where k = "
                f"{rp1.randrange(nrows)}"), daemon=True),
            threading.Thread(target=loop, args=(
                "point", lambda: "select v, n from point_kv where k = "
                f"{rp2.randrange(nrows)}"), daemon=True),
            threading.Thread(target=loop, args=(
                "dml", lambda: f"update point_kv set n = "
                f"{rd.randrange(10 ** 6)} where k = {rd.randrange(nrows)}"),
                daemon=True),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=seconds + 120)
        out: dict = {}
        for lane, lat in buckets.items():
            lat.sort()
            out[f"{lane}_requests"] = len(lat)
            if lat:
                out[f"{lane}_p50_ms"] = round(_pct(lat, 0.50), 3)
                out[f"{lane}_p99_ms"] = round(_pct(lat, 0.99), 3)
        return out
    finally:
        tier.shutdown()


def run_point_phase(seconds: float = 4.0, nrows: int = 20000,
                    mixed: bool = True) -> dict:
    """Short-circuit point-query lane benchmark (the wire-speed PK-lookup
    plane). tpch_catalog is in-memory, so this phase builds its own
    TabletStore-backed PK table — the lane only exists over the stored
    primary index. Reports sustained in-proc point QPS/percentiles, the
    cold-analytic anchor for the same statement (lane off, fresh plans),
    and mixed-workload per-lane latency under a per-second DML pulse."""
    import shutil
    import tempfile

    from starrocks_tpu.cache import plan_cache  # noqa: F401 — knob define
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session

    d = tempfile.mkdtemp(prefix="sr_pointbench_")
    out: dict = {"rows": nrows}
    prev_plan = config.get("enable_plan_cache")
    try:
        s = Session(data_dir=os.path.join(d, "db"))
        s.sql("create table point_kv (k bigint, v varchar, n bigint, "
              "primary key(k))")
        for base in range(0, nrows, 2000):
            rows = ",".join(f"({i}, 'v{i}', {i * 7})"
                            for i in range(base, min(base + 2000, nrows)))
            s.sql(f"insert into point_kv values {rows}")
        rng = random.Random(11)

        # cold analytic anchor: the SAME statement with the lane off and
        # plan caching off — what every lookup would cost through the
        # full planner/compiler path
        config.set("enable_short_circuit", False)
        config.set("enable_plan_cache", False)
        lat: list = []
        for _ in range(12):
            k = rng.randrange(nrows)
            t0 = time.perf_counter()
            s.sql(f"select v, n from point_kv where k = {k}")
            lat.append((time.perf_counter() - t0) * 1000.0)
        lat.sort()
        out["analytic_cold_p50_ms"] = round(_pct(lat, 0.50), 3)
        config.set("enable_plan_cache", prev_plan)
        config.set("enable_short_circuit", True)

        # sustained in-proc point loop (single client; the wire adds its
        # own per-protocol cost on top of the engine answer path)
        lat = []
        deadline = time.monotonic() + seconds
        t_all = time.monotonic()
        while time.monotonic() < deadline:
            k = rng.randrange(int(nrows * 1.02))  # ~2% misses in the mix
            t0 = time.perf_counter()
            s.sql(f"select v, n from point_kv where k = {k}")
            lat.append((time.perf_counter() - t0) * 1000.0)
        wall = time.monotonic() - t_all
        lat.sort()
        out.update({
            "point_requests": len(lat),
            "point_qps": round(len(lat) / wall, 1) if wall else 0.0,
            "point_p50_ms": round(_pct(lat, 0.50), 3),
            "point_p99_ms": round(_pct(lat, 0.99), 3),
        })
        if out["point_p50_ms"]:
            out["point_vs_analytic_cold"] = round(
                out["analytic_cold_p50_ms"] / out["point_p50_ms"], 1)

        if mixed:
            out["mixed"] = _run_mixed_lane_phase(s, nrows, seconds)
    finally:
        config.set("enable_plan_cache", prev_plan)
        config.set("enable_short_circuit", True)
        shutil.rmtree(d, ignore_errors=True)
    return out


def run_feedback_phase(cat, statements) -> dict:
    """A/B of the plan-feedback loop (ISSUE 11) over the serve mix plus a
    guaranteed-overflow expansion join. Three passes per arm, in process:

      learn  — fresh session, cold everything: pays compiles AND the
               adaptive overflow retries that teach the store;
      repeat — NEW session (cold program/opt caches, the restart analog)
               with the feedback store carried over: feedback-on must
               pre-tighten to ZERO adaptive recompiles;
      steady — same session again: second executions must ride the
               program cache end to end (zero fresh compiles — the
               consult-token fixpoint keeping the opt-plan key warm).
    """
    import numpy as np

    from starrocks_tpu.column import HostTable
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.feedback import (
        FEEDBACK_EST_ERRSUM, FEEDBACK_EST_JOINS, FEEDBACK_HITS,
        FEEDBACK_RETRIES_AVOIDED)
    from starrocks_tpu.runtime.metrics import PROGRAM_COMPILES, RECOMPILES
    from starrocks_tpu.runtime.session import Session

    rng = np.random.default_rng(29)
    cat.register("fb_fact", HostTable.from_pydict({
        "k": [int(x) for x in rng.integers(0, 20, 2000)],
        "v": list(range(2000))}))
    cat.register("fb_dim", HostTable.from_pydict({
        "k": [int(x) for x in rng.integers(0, 20, 2000)],
        "w": list(range(2000))}))
    mix = [sql for _, sql in statements] + [
        "select count(*) c, sum(f.v + d.w) s from fb_fact f "
        "join fb_dim d on f.k = d.k"]

    def run_pass(sess) -> dict:
        c0, r0 = PROGRAM_COMPILES.value, RECOMPILES.value
        for sql in mix:
            sess.sql(sql)
        return {"compiles": PROGRAM_COMPILES.value - c0,
                "recompiles": RECOMPILES.value - r0}

    out: dict = {"mix_statements": len(mix)}
    try:
        for mode in ("off", "on"):
            config.set("plan_feedback", mode == "on")
            h0, a0 = FEEDBACK_HITS.value, FEEDBACK_RETRIES_AVOIDED.value
            e0, j0 = FEEDBACK_EST_ERRSUM.value, FEEDBACK_EST_JOINS.value
            s1 = Session(cat)
            res = {"learn": run_pass(s1)}
            s2 = Session(cat)  # restart analog: cold caches, same catalog
            s2.cache.feedback = s1.cache.feedback
            res["repeat"] = run_pass(s2)
            res["steady"] = run_pass(s2)
            res["feedback_hits"] = FEEDBACK_HITS.value - h0
            res["retries_avoided"] = FEEDBACK_RETRIES_AVOIDED.value - a0
            joins = FEEDBACK_EST_JOINS.value - j0
            if joins:
                res["est_rel_err"] = round(
                    (FEEDBACK_EST_ERRSUM.value - e0) / joins, 3)
            out[mode] = res
    finally:
        config.set("plan_feedback", True)
        cat.drop("fb_fact", if_exists=True)
        cat.drop("fb_dim", if_exists=True)
    out["repeat_retries_saved_vs_off"] = (
        out["off"]["repeat"]["recompiles"]
        - out["on"]["repeat"]["recompiles"])
    return out


def run_obs_phase(iters: int = 240, nrows: int = 8000) -> dict:
    """Observability-plane overhead A/B: the WHOLE derived plane ON (the
    shipped defaults — audit log, metrics-history sampler + alert rules,
    workload aggregator, plan sentinel, stuck-query watchdog) vs OFF,
    over the two latencies the plane must NOT tax — the warm in-proc
    fast path (result-cache inline answer) and the point lane
    (planner-free PK lookup). The event journal has no off switch, but
    none of its sites fire on either lane, so the toggled set IS the
    per-statement delta. Arms alternate in interleaved rounds so host
    drift cancels out of the comparison; acceptance is <5% p50
    regression on both lanes (obs work rides the unwind hook and
    background threads, never the answer path)."""
    import shutil
    import tempfile

    from starrocks_tpu.runtime import audit  # noqa: F401 — knob define
    from starrocks_tpu.runtime.alerts import ALERTS
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.metrics import HISTORY
    from starrocks_tpu.runtime.sentinel import SENTINEL
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.runtime.watchdog import WATCHDOG
    from starrocks_tpu.runtime.workload import WORKLOAD

    d = tempfile.mkdtemp(prefix="sr_obsbench_")
    # every knob the A/B toggles (the round-19 derived plane included)
    _ARM_KNOBS = ("enable_audit_log", "enable_metrics_history",
                  "enable_alerts", "enable_workload_stats",
                  "enable_plan_sentinel", "enable_watchdog")
    prev = {k: config.get(k) for k in _ARM_KNOBS}
    prev_qc = config.get("enable_query_cache")
    out: dict = {}
    try:
        s = Session(data_dir=os.path.join(d, "db"))
        s.sql("create table obs_kv (k bigint, v varchar, n bigint, "
              "primary key(k))")
        for base in range(0, nrows, 2000):
            rows = ",".join(f"({i}, 'v{i}', {i * 3})"
                            for i in range(base, min(base + 2000, nrows)))
            s.sql(f"insert into obs_kv values {rows}")
        config.set("enable_query_cache", True)
        warm_sql = "select count(*) c, sum(n) sn from obs_kv"
        rng = random.Random(7)

        def one_warm():
            s.sql(warm_sql)

        def one_point():
            s.sql(f"select v, n from obs_kv where k = {rng.randrange(nrows)}")

        def set_arm(on: bool):
            for k in _ARM_KNOBS:
                config.set(k, on)
            if on:
                HISTORY.ensure_started()
                WATCHDOG.ensure_started()
            else:
                HISTORY.stop()
                WATCHDOG.stop()

        for _ in range(20):  # shared warmup: pay compiles, prime caches
            one_warm()
            one_point()
        lats: dict = {(lane, on): []
                      for lane in ("warm", "point") for on in (True, False)}
        rounds = 8
        per = max(iters // rounds, 10)
        for r in range(rounds):
            for on in ((True, False) if r % 2 == 0 else (False, True)):
                set_arm(on)
                for _ in range(3):  # settle the arm switch
                    one_warm()
                    one_point()
                for lane, fn in (("warm", one_warm), ("point", one_point)):
                    for _ in range(per):
                        t0 = time.perf_counter()
                        fn()
                        lats[(lane, on)].append(
                            (time.perf_counter() - t0) * 1000)

        def p50(lane, on):
            v = sorted(lats[(lane, on)])
            return v[len(v) // 2]

        out["obs_on_warm_p50_ms"] = round(p50("warm", True), 3)
        out["obs_off_warm_p50_ms"] = round(p50("warm", False), 3)
        out["obs_on_point_p50_ms"] = round(p50("point", True), 3)
        out["obs_off_point_p50_ms"] = round(p50("point", False), 3)
        warm_reg = p50("warm", True) / max(p50("warm", False), 1e-9) - 1
        point_reg = p50("point", True) / max(p50("point", False), 1e-9) - 1
        out["obs_warm_regress_pct"] = round(warm_reg * 100, 1)
        out["obs_point_regress_pct"] = round(point_reg * 100, 1)
        out["obs_pass"] = bool(warm_reg < 0.05 and point_reg < 0.05)
        # derived-plane bookkeeping after the sustained run: the summary
        # JSON records that the new state stayed hard-bounded while every
        # statement of the bench flowed through it
        wst = WORKLOAD.stats()
        ast_ = ALERTS.stats()
        out["workload_entries"] = wst["entries"]
        out["workload_registered"] = wst["registered"]
        out["workload_evicted"] = wst["evicted"]
        out["alert_rules"] = ast_["rules"]
        out["alert_firing"] = ast_["firing"]
        out["alert_fires"] = ast_["fires"]
        out["sentinel_entries"] = SENTINEL.stats()["entries"]
    finally:
        for k, v in prev.items():
            config.set(k, v)
        config.set("enable_query_cache", prev_qc)
        shutil.rmtree(d, ignore_errors=True)
    return out


def run_ingest_phase(seconds: float = 6.0, nrows: int = 12000,
                     loaders: int = 1, put_rows: int = 1000) -> dict:
    """Continuous-ingest phase: sustained HTTP stream-load lanes into one
    PK table while a Zipfian analytic lane and the point lane keep
    serving a DIFFERENT table through the same tier — the plan-footprint
    gate claims are what keep the serving lanes out of the ingest
    commits' way. Reports sustained ingest rows/s, staged->visible
    freshness p50 (the sr_tpu_ingest_freshness_ms histogram), serving
    latency under ingest vs a no-ingest baseline on the SAME process,
    and the idle cost of merely having the plane enabled (A/B toggling
    `enable_ingest_plane` with zero load traffic)."""
    import shutil
    import tempfile

    from starrocks_tpu.ingest.plane import INGEST_FRESHNESS_MS
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.http_service import SqlHttpServer
    from starrocks_tpu.runtime.serving import ServingTier
    from starrocks_tpu.runtime.session import Session

    d = tempfile.mkdtemp(prefix="sr_ingestbench_")
    prev_qc = config.get("enable_query_cache")
    out: dict = {"loaders": loaders, "put_rows": put_rows}
    half = max(seconds / 2.0, 2.0)
    try:
        s = Session(data_dir=os.path.join(d, "db"))
        s.sql("create table serve_kv (k bigint, v varchar, n bigint, "
              "primary key(k))")
        for base in range(0, nrows, 2000):
            rows = ",".join(f"({i}, 'v{i}', {i * 3})"
                            for i in range(base, min(base + 2000, nrows)))
            s.sql(f"insert into serve_kv values {rows}")
        s.sql("create table ingest_sink (k bigint, v bigint, "
              "primary key(k))")
        tier = ServingTier(s, pool_size=2)
        plane = s.ingest_plane()  # wires the tier's gate into commits
        ht = SqlHttpServer(s, port=0, tier=tier).start()
        config.set("enable_query_cache", False)
        # freshness-oriented commit policy for the sustained window: a
        # stream-load fleet tunes the age bound down exactly like this
        config.set("ingest_batch_age_ms", 50)
        analytic = [
            "select count(*) c, sum(n) sn from serve_kv where n >= 0",
            "select count(*) c, max(n) mn from serve_kv where k < "
            f"{nrows // 2}",
            "select min(k) a, max(k) b from serve_kv where n % 2 = 0",
        ]
        aw = zipf_weights(len(analytic))
        sess = tier.new_session()
        for sql in analytic:  # pay compiles before any timed window
            tier.execute(sess, sql)

        rng_idle = random.Random(13)

        def point_once(sess_, rng):
            tier.execute(sess_, "select v, n from serve_kv where k = "
                         f"{rng.randrange(nrows)}")

        # --- idle A/B: the enabled-but-unused plane must cost ~nothing
        def idle_p50(iters=150):
            lat = []
            for _ in range(iters):
                t0 = time.perf_counter()
                point_once(sess, rng_idle)
                lat.append((time.perf_counter() - t0) * 1000)
            lat.sort()
            return lat[len(lat) // 2]

        idle_p50(30)  # warm the lane before either arm samples
        config.set("enable_ingest_plane", False)
        p_off = idle_p50()
        config.set("enable_ingest_plane", True)
        p_on = idle_p50()
        out["idle_point_p50_plane_off_ms"] = round(p_off, 3)
        out["idle_point_p50_plane_on_ms"] = round(p_on, 3)
        out["idle_regress_pct"] = round((p_on / max(p_off, 1e-9) - 1)
                                        * 100, 1)

        # --- serving lanes (shared by baseline and under-ingest windows)
        def lanes(duration: float) -> dict:
            buckets = {"point": [], "analytic": []}
            lock = threading.Lock()
            stop_at = time.monotonic() + duration

            def loop(lane, fn):
                sess_ = tier.new_session()
                rng = random.Random(hash(lane) & 0xFFFF)
                my = []
                while time.monotonic() < stop_at:
                    t0 = time.perf_counter()
                    try:
                        fn(sess_, rng)
                    except Exception:  # noqa: BLE001
                        continue
                    my.append((time.perf_counter() - t0) * 1000)
                with lock:
                    buckets[lane].extend(my)

            def analytic_once(sess_, rng):
                tier.execute(
                    sess_, rng.choices(analytic, weights=aw, k=1)[0])

            ts = [threading.Thread(target=loop, args=("point", point_once),
                                   daemon=True),
                  threading.Thread(target=loop,
                                   args=("analytic", analytic_once),
                                   daemon=True)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=duration + 120)
            res = {}
            for lane, lat in buckets.items():
                lat.sort()
                res[f"{lane}_requests"] = len(lat)
                res[f"{lane}_p50_ms"] = round(_pct(lat, 0.50), 3)
                res[f"{lane}_p99_ms"] = round(_pct(lat, 0.99), 3)
            return res

        base = lanes(half)
        out["baseline"] = base

        # --- sustained stream load over HTTP + the same serving lanes
        rows_acked = [0] * loaders
        errors: list = []
        stop_at = [time.monotonic() + half]

        def loader(i: int):
            conn = http.client.HTTPConnection("127.0.0.1", ht.port,
                                              timeout=120)
            seq = 0
            while time.monotonic() < stop_at[0]:
                base_k = (i << 40) + seq * put_rows
                body = "\n".join(f"{base_k + j},{j}"
                                 for j in range(put_rows))
                try:
                    conn.request("PUT", "/api/load/ingest_sink", body)
                    resp = conn.getresponse()
                    data = resp.read()
                    if resp.status == 429:
                        time.sleep(0.05)  # backpressure: retry later
                        continue
                    if resp.status != 200:
                        errors.append(f"{resp.status}: {data[:120]!r}")
                        continue
                    rows_acked[i] += json.loads(data)["rows"]
                    seq += 1
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e)[:120])
            conn.close()

        f0_counts, _f0_sum, f0_n = INGEST_FRESHNESS_MS.snapshot()
        ts = [threading.Thread(target=loader, args=(i,), daemon=True)
              for i in range(loaders)]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        under = lanes(half)
        for t in ts:
            t.join(timeout=half + 120)
        wall = time.monotonic() - t0
        out["under_ingest"] = under
        out["ingest_rows"] = sum(rows_acked)
        out["ingest_rows_s"] = round(sum(rows_acked) / wall, 1)
        out["ingest_errors"] = len(errors)
        out["ingest_error_sample"] = errors[:3]
        # freshness over THIS window: subtract the pre-window histogram
        f1_counts, _f1_sum, f1_n = INGEST_FRESHNESS_MS.snapshot()
        out["ingest_freshness_p50_ms"] = round(
            _hist_delta_percentile(INGEST_FRESHNESS_MS, f0_counts, f0_n,
                                   f1_counts, f1_n, 0.5), 1)
        out["point_p99_under_ingest_ms"] = under["point_p99_ms"]
        sink = s.sql("select count(*) from ingest_sink").rows()[0][0]
        out["ingest_rows_visible"] = int(sink)
        out["ingest_pass"] = bool(
            out["ingest_rows_s"] >= 5000
            and out["ingest_freshness_p50_ms"] < 1000
            and under["point_p99_ms"] < 2 * max(base["point_p99_ms"], 0.5)
            and sink == sum(rows_acked))
        ht.stop()
    finally:
        config.set("enable_query_cache", prev_qc)
        config.set("enable_ingest_plane", True)
        config.set("ingest_batch_age_ms", 200)
        shutil.rmtree(d, ignore_errors=True)
    return out


def _hist_delta_percentile(hist, c0, n0, c1, n1, q: float) -> float:
    """q-quantile of the observations a histogram gained between two
    snapshots (c0/n0 -> c1/n1), by the same interpolation its own
    percentile() uses — serve_bench windows need per-phase freshness,
    not process-lifetime freshness."""
    n = n1 - n0
    if n <= 0:
        return 0.0
    deltas = [a - b for a, b in zip(c1, c0)]
    rank = q * n
    seen = 0.0
    for i, cnt in enumerate(deltas):
        if cnt <= 0:
            continue
        if seen + cnt >= rank:
            lo = hist.buckets[i - 1] if i > 0 else 0.0
            hi = (hist.buckets[i] if i < len(hist.buckets)
                  else hist.buckets[-1])
            frac = (rank - seen) / cnt
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += cnt
    return hist.buckets[-1]


def run_serve_bench(threads: int = 32, seconds: float = 8.0,
                    sf: float = 0.01, pool: int = 4,
                    include_ssb: bool = False, http_frac: float = 0.25,
                    chaos: bool = False, single_thread_ab: bool = True,
                    warm: bool = True, feedback: bool = True,
                    points: bool = True, obs: bool = True) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from starrocks_tpu import lockdep
    from starrocks_tpu.runtime import failpoint
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.http_service import SqlHttpServer
    from starrocks_tpu.runtime.lifecycle import ACCOUNTANT, REGISTRY
    from starrocks_tpu.runtime.mysql_service import MySQLServer
    from starrocks_tpu.runtime.serving import ServingTier
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.storage.catalog import tpch_catalog

    out_points = None
    if points:
        # runs FIRST so its store-backed table allocates before the leak
        # audit's baseline snapshot
        out_points = run_point_phase(seconds=min(seconds, 4.0))

    out_obs = None
    if obs:
        # also before the leak baseline: the A/B builds (and drops) its
        # own store-backed PK table
        out_obs = run_obs_phase()

    t_setup = time.monotonic()
    cat = tpch_catalog(sf=sf)
    if include_ssb:
        from starrocks_tpu.storage.datagen.ssb import ssb_catalog

        scat = ssb_catalog(sf=sf)
        # only the flat table: SSB's dimension tables share names with
        # TPC-H (customer/supplier/part) but carry different schemas
        cat.tables["lineorder_flat"] = scat.tables["lineorder_flat"]
    template = Session(cat)
    statements = build_statements(include_ssb)
    weights = zipf_weights(len(statements))

    out: dict = {
        "threads": threads, "seconds": seconds, "sf": sf, "pool": pool,
        "statements": len(statements), "mix": "zipf-1.1",
        "backend": jax.devices()[0].platform,
        # pool speedup is bounded by host cores: on a 1-core box the A/B
        # signal is queue-wait collapse, not QPS (see BENCH_DETAIL notes)
        "host_cpus": os.cpu_count(),
    }
    config.set("enable_plan_cache", True)
    config.set("enable_query_cache", False)

    def fresh_tier(size: int):
        tier = ServingTier(template, pool_size=size)
        my = MySQLServer(template, port=0, tier=tier).start()
        ht = SqlHttpServer(template, port=0, tier=tier).start()
        return tier, my, ht

    tier, my, ht = fresh_tier(pool)
    try:
        # warmup: pay every trace+compile once (single client, in order)
        warm_sess = tier.new_session()
        for _, sql in statements:
            tier.execute(warm_sess, sql)
        out["setup_s"] = round(time.monotonic() - t_setup, 1)

        mem0 = ACCOUNTANT.snapshot()["process_bytes"]
        if chaos:
            # times-bounded faults land mid-run; the tier must shed them
            # cleanly (errors count, nothing leaks)
            for name in ("executor::fetch_results", "qcache::lookup",
                         "workgroup::admit"):
                failpoint.arm(name, times=3)
            out["chaos"] = True

        # cold phase (pool = N): real execution, concurrent
        out["cold"] = run_phase(my.port, ht.port, statements, weights,
                                threads, seconds, http_frac)
        if chaos:
            for name in ("executor::fetch_results", "qcache::lookup",
                         "workgroup::admit"):
                failpoint.disarm(name)
    finally:
        my.shutdown()
        ht.stop()

    if single_thread_ab:
        # forced single-thread run: pool=1 serializes every statement —
        # the pre-serving-tier behavior, same box, same warmed programs
        tier1, my1, ht1 = fresh_tier(1)
        try:
            out["cold_single"] = run_phase(
                my1.port, ht1.port, statements, weights, threads, seconds,
                http_frac)
        finally:
            my1.shutdown()
            ht1.stop()
        if out["cold_single"]["qps"]:
            out["speedup_vs_single"] = round(
                out["cold"]["qps"] / out["cold_single"]["qps"], 2)

    if warm:
        config.set("enable_query_cache", True)
        tier2, my2, ht2 = fresh_tier(pool)
        try:
            sess = tier2.new_session()
            for _, sql in statements:  # prime the result tier
                tier2.execute(sess, sql)
            out["warm"] = run_phase(my2.port, ht2.port, statements,
                                    weights, threads, seconds, http_frac)
            # in-process fast-path latency (no wire): the <1ms claim is
            # about the ENGINE answer path; sockets add their own cost
            hot_sql = statements[0][1]
            lat = []
            for _ in range(50):
                t0 = time.perf_counter()
                tier2.execute(sess, hot_sql)
                lat.append((time.perf_counter() - t0) * 1000)
            lat.sort()
            out["warm_inproc_p50_ms"] = round(lat[len(lat) // 2], 3)
        finally:
            my2.shutdown()
            ht2.stop()
            config.set("enable_query_cache", False)

    if feedback:
        out["feedback"] = run_feedback_phase(cat, statements)

    if out_points is not None:
        out["points"] = out_points
    if out_obs is not None:
        out["obs"] = out_obs

    # leak + witness audit (the chaos-suite contract, applied to serving)
    wm = getattr(cat, "workgroups", None)
    out["leaks"] = {
        "process_bytes": ACCOUNTANT.snapshot()["process_bytes"] - mem0,
        "registry": len(REGISTRY.snapshot()),
        "slots_running": (sum(wm.running.values()) if wm else 0),
    }
    out["witness_cycles"] = len(lockdep.WITNESS.order_cycles())
    return out


def run_cluster_phase(workers: int = 2, clients: int = 4,
                      seconds: float = 8.0) -> dict:
    """--cluster: N client threads against a coordinator + M worker
    PROCESSES (runtime/cluster_exec.py), two timed windows:

      steady — every client fires fragment queries against the healthy
        fleet (each answer checked against a pre-cluster local oracle).
      kill   — same load; 25% into the window one worker is SIGKILL'd.
        Queries in flight across the kill re-place their fragments onto
        the survivors; the phase reports the worst straddling-query
        latency (retry latency) and the post-kill p99 — the acceptance
        gate is that the post-kill p99 is FINITE (no wedged query).

    Afterwards the dead worker is respawned and the fleet must report
    zero dead workers again (gauge recovery), with zero leaked slots/
    bytes/registry entries."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # the coordinator session is distributed (dist_shards=2): widen
        # this process's host platform BEFORE any jax backend initializes
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import starrocks_tpu.sql.distributed as D
    from starrocks_tpu import lockdep
    from starrocks_tpu.runtime.cluster import WORKERS_DEAD
    from starrocks_tpu.runtime.cluster_exec import ClusterRuntime
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.events import EVENTS
    from starrocks_tpu.runtime.lifecycle import ACCOUNTANT, REGISTRY

    sh0, gr0 = D.SHARD_THRESHOLD_ROWS, D.SHUFFLE_AGG_MIN_GROUPS
    frag0 = config.get("dist_fragments")
    qc0 = config.get("enable_query_cache")
    D.SHARD_THRESHOLD_ROWS = 100
    D.SHUFFLE_AGG_MIN_GROUPS = 10
    config.set("dist_fragments", True)
    config.set("enable_query_cache", False)

    from starrocks_tpu.runtime.session import Session

    s = Session(dist_shards=2)
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values "
          + ", ".join(f"({i % 97}, {i % 7})" for i in range(400)))
    s.sql("create table d (k int, v int)")
    s.sql("insert into d values "
          + ", ".join(f"({i}, {i * 10})" for i in range(97)))
    variants = [
        "select d.v, sum(t.b) s from t join d on t.a = d.k "
        f"group by d.v order by s desc, d.v limit {n}" for n in (5, 7, 9)
    ]
    oracles = {sql: s.sql(sql).rows() for sql in variants}

    t_setup = time.monotonic()
    cr = ClusterRuntime(n_workers=workers, shards=2, hb_interval_s=0.1,
                        hb_miss_limit=3).start(s)
    cr.attach(s)
    mem0 = ACCOUNTANT.snapshot()["process_bytes"]
    errors: list = []
    lat_lock = threading.Lock()

    def timed_window(window_s: float, kill_at_frac: float | None):
        """Run `clients` sessions over the shared catalog for window_s;
        optionally SIGKILL w0 at kill_at_frac of the window. Returns
        (samples, kill_ts) where samples are (t0, t1, ms) monotonic."""
        samples: list = []
        stop_at = time.monotonic() + window_s
        kill_ts = [None]

        def client_loop(i: int):
            rng = random.Random(4200 + i)
            cs = Session(catalog=s.catalog, cache=s.cache, dist_shards=2)
            my: list = []
            while time.monotonic() < stop_at:
                sql = rng.choice(variants)
                t0 = time.monotonic()
                try:
                    rows = cs.sql(sql).rows()
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{type(e).__name__}: {e}"[:200])
                    continue
                t1 = time.monotonic()
                if rows != oracles[sql]:
                    errors.append(f"oracle mismatch on: {sql[-20:]}")
                my.append((t0, t1, (t1 - t0) * 1000.0))
            with lat_lock:
                samples.extend(my)

        threads_ = [threading.Thread(target=client_loop, args=(i,),
                                     daemon=True) for i in range(clients)]
        for th in threads_:
            th.start()
        if kill_at_frac is not None:
            time.sleep(window_s * kill_at_frac)
            # hold w0's next fragment in a delay so the SIGKILL lands
            # mid-fragment — the retry path, not just a re-placement of
            # future fragments onto the survivors
            cr.inject_fault("w0", "delay", seconds=2.0, times=1)
            time.sleep(0.6)  # let a fragment land in w0's delay window
            kill_ts[0] = time.monotonic()
            cr.kill_worker("w0")
        for th in threads_:
            th.join(timeout=window_s + 120.0)
        if any(th.is_alive() for th in threads_):
            errors.append("wedged client: a query never returned")
        return samples, kill_ts[0]

    out: dict = {"cluster_workers": workers, "cluster_clients": clients}
    try:
        for sql in variants:  # warm: fragment programs cached fleet-wide
            if s.sql(sql).rows() != oracles[sql]:
                errors.append("warm-up cluster answer diverged")
        out["setup_s"] = round(time.monotonic() - t_setup, 1)
        r0 = cr.stats()["retries_total"]
        loss0 = EVENTS.stats().get("heartbeat_loss", 0)

        steady, _ = timed_window(seconds / 2, None)
        sl = sorted(ms for _, _, ms in steady)
        out["steady"] = {
            "queries": len(sl), "qps": round(len(sl) / (seconds / 2), 1),
            "p50_ms": round(_pct(sl, 0.50), 2),
            "p99_ms": round(_pct(sl, 0.99), 2),
        }

        killed, kill_ts = timed_window(seconds / 2, 0.25)
        post = sorted(ms for _, t1, ms in killed if t1 >= kill_ts)
        straddle = [ms for t0, t1, ms in killed if t0 < kill_ts <= t1]
        out["kill"] = {
            "queries": len(killed), "post_kill": len(post),
            "straddling": len(straddle),
            "retry_latency_ms": round(max(straddle), 2) if straddle
            else None,
            "p99_ms": round(_pct(post, 0.99), 2),
        }
        out["cluster_retries"] = cr.stats()["retries_total"] - r0
        out["cluster_kill_p99_ms"] = out["kill"]["p99_ms"]
        if not post:
            errors.append("kill phase produced no post-kill samples")

        # recovery: the fleet heals and the observability plane saw it
        if EVENTS.stats().get("heartbeat_loss", 0) <= loss0:
            errors.append("kill was not observed (no heartbeat_loss)")
        cr.respawn_worker("w0")
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and WORKERS_DEAD.value > 0:
            time.sleep(0.1)
        out["recovered"] = WORKERS_DEAD.value == 0
        if not out["recovered"]:
            errors.append("dead-worker gauge did not recover after "
                          "respawn")
    finally:
        s.catalog.cluster_runtime = None
        cr.stop()
        D.SHARD_THRESHOLD_ROWS, D.SHUFFLE_AGG_MIN_GROUPS = sh0, gr0
        config.set("dist_fragments", frag0)
        config.set("enable_query_cache", qc0)

    out["leaks"] = {
        "process_bytes": ACCOUNTANT.snapshot()["process_bytes"] - mem0,
        "registry": len(REGISTRY.snapshot()),
    }
    out["witness_cycles"] = len(lockdep.WITNESS.order_cycles())
    out["errors"] = errors[:5]
    out["cluster_pass"] = (
        not errors and out["cluster_kill_p99_ms"] > 0.0
        and not out["leaks"]["process_bytes"] and not out["leaks"]["registry"]
        and not out["witness_cycles"])
    return out


def main():
    ap = argparse.ArgumentParser(
        description="sustained mixed-workload serving benchmark")
    ap.add_argument("--threads", type=int, default=32)
    ap.add_argument("--seconds", type=float, default=8.0)
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--pool", type=int, default=4)
    ap.add_argument("--ssb", action="store_true",
                    help="add SSB lineorder_flat templates to the mix")
    ap.add_argument("--http-frac", type=float, default=0.25,
                    help="fraction of clients on the HTTP front door")
    ap.add_argument("--chaos", action="store_true",
                    help="arm times-bounded failpoints mid-run")
    ap.add_argument("--no-ab", action="store_true",
                    help="skip the forced single-thread A/B run")
    ap.add_argument("--no-warm", action="store_true",
                    help="skip the warm (query-cache on) phase")
    ap.add_argument("--no-feedback", action="store_true",
                    help="skip the plan-feedback effectiveness A/B phase")
    ap.add_argument("--points", action="store_true",
                    help="run ONLY the short-circuit point-query phase")
    ap.add_argument("--no-points", action="store_true",
                    help="skip the point-query phase in the full run")
    ap.add_argument("--ingest", action="store_true",
                    help="run ONLY the continuous-ingest phase (stream "
                         "load + serving lanes; rows/s, freshness, "
                         "p99-under-ingest, idle-cost gates)")
    ap.add_argument("--obs", action="store_true",
                    help="run ONLY the observability-overhead A/B phase "
                         "(audit+events+sampler on vs off; <5%% gate)")
    ap.add_argument("--no-obs", action="store_true",
                    help="skip the observability A/B phase in the full run")
    ap.add_argument("--cluster", action="store_true",
                    help="run ONLY the cluster phase: clients against a "
                         "coordinator + worker PROCESSES with a "
                         "kill-one-worker window (retry latency + "
                         "post-kill p99)")
    ap.add_argument("--cluster-workers", type=int, default=2,
                    help="worker processes for --cluster")
    ap.add_argument("--cluster-clients", type=int, default=4,
                    help="client threads for --cluster")
    ap.add_argument("--detail", action="store_true",
                    help="merge a 'serve' section into BENCH_DETAIL.json")
    args = ap.parse_args()

    if args.cluster:
        res = run_cluster_phase(workers=args.cluster_workers,
                                clients=args.cluster_clients,
                                seconds=args.seconds)
        if args.detail:
            path = os.path.join(REPO, "BENCH_DETAIL.json")
            detail = {}
            if os.path.exists(path):
                with open(path) as f:
                    detail = json.load(f)
            detail["cluster"] = res
            with open(path, "w") as f:
                json.dump(detail, f, indent=1)
        print(json.dumps(res))
        return 0 if res["cluster_pass"] else 1

    if args.points:
        import jax

        jax.config.update("jax_platforms", "cpu")
        res = {"points": run_point_phase(seconds=args.seconds)}
        print(json.dumps(res))
        return 0

    if args.obs:
        import jax

        jax.config.update("jax_platforms", "cpu")
        res = {"obs": run_obs_phase()}
        print(json.dumps(res))
        return 0 if res["obs"]["obs_pass"] else 1

    if args.ingest:
        import jax

        jax.config.update("jax_platforms", "cpu")
        res = {"ingest": run_ingest_phase(seconds=args.seconds)}
        if args.detail:
            path = os.path.join(REPO, "BENCH_DETAIL.json")
            detail = {}
            if os.path.exists(path):
                with open(path) as f:
                    detail = json.load(f)
            detail["ingest"] = res["ingest"]
            with open(path, "w") as f:
                json.dump(detail, f, indent=1)
        print(json.dumps(res))
        return 0 if res["ingest"]["ingest_pass"] else 1

    res = run_serve_bench(
        threads=args.threads, seconds=args.seconds, sf=args.sf,
        pool=args.pool, include_ssb=args.ssb, http_frac=args.http_frac,
        chaos=args.chaos, single_thread_ab=not args.no_ab,
        warm=not args.no_warm, feedback=not args.no_feedback,
        points=not args.no_points, obs=not args.no_obs)
    if args.detail:
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        detail = {}
        if os.path.exists(path):
            with open(path) as f:
                detail = json.load(f)
        detail["serve"] = res
        if "feedback" in res:
            detail["feedback"] = res["feedback"]
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    print(json.dumps(res))
    leaks = res.get("leaks", {})
    obs_fail = "obs" in res and not res["obs"].get("obs_pass")
    bad = (res.get("witness_cycles", 0)
           or leaks.get("process_bytes") or leaks.get("slots_running")
           or obs_fail)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
