#!/usr/bin/env python
"""Corpus A/B: learned plan feedback must not flip any corpus plan.

Executes every corpus query once with plan_feedback on (populating the
FeedbackStore with real observations), then re-optimizes each query twice
— once with the recorded entry, once with feedback=None — and compares
the optimized-plan reprs.  Plan identity + deterministic execution implies
row byte-identity, so this is the cheap form of the "all corpus queries
byte-identical to the feedback-off path" acceptance gate: one execution
pass instead of three.

Exit 0 iff no query's plan diverges under its learned entry.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    from plan_lint import _suites

    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.feedback import plan_fingerprint
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.sql.analyzer import Analyzer
    from starrocks_tpu.sql.optimizer import optimize
    from starrocks_tpu.sql.parser import parse

    if not config.get("compilation_cache_dir"):
        config.set("compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".xla_cache"), force=True)
    config.set("plan_feedback", True)

    t0 = time.time()
    n = with_entry = diverged = errors = 0
    for suite, catalog, queries in _suites("all"):
        sess = Session(catalog)
        for name, text in queries.items():
            n += 1
            try:
                sess.sql(text)  # records observations into the store
            except Exception as e:  # noqa: BLE001 — keep sweeping
                errors += 1
                print(f"{suite}/{name}: EXEC-ERROR {type(e).__name__}: "
                      f"{str(e)[:160]}", file=sys.stderr)
                continue
            try:
                plan = Analyzer(sess.catalog).analyze(parse(text))
                fb = sess.cache.feedback.consult(
                    plan_fingerprint(plan), sess.catalog)
                if fb is None:
                    continue
                with_entry += 1
                on = repr(optimize(plan, sess.catalog, fb))
                off = repr(optimize(plan, sess.catalog, None))
                if on != off:
                    diverged += 1
                    print(f"{suite}/{name}: PLAN DIVERGED under feedback",
                          file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — keep sweeping
                errors += 1
                print(f"{suite}/{name}: CHECK-ERROR {type(e).__name__}: "
                      f"{str(e)[:160]}", file=sys.stderr)
    print(json.dumps({
        "metric": "feedback_plan_identity",
        "queries": n,
        "with_feedback_entry": with_entry,
        "plans_diverged": diverged,
        "errors": errors,
        "seconds": round(time.time() - t0, 1),
    }))
    return 1 if (diverged or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
