"""Staged TPU-tunnel forensics (VERDICT r3 item 2).

The axon PJRT plugin proxies device ops to a remote TPU terminal through a
loopback relay (see /root/.axon_site/sitecustomize.py: JAX_PLATFORMS=axon,
PALLAS_AXON_POOL_IPS=127.0.0.1, remote_compile=1). In rounds 1-3 the first
device op hung indefinitely, so every benchmark fell back to CPU. This tool
isolates WHICH layer wedges, each stage in its own subprocess with its own
timeout + faulthandler stack dump:

  relay-tcp      raw TCP connect to the relay port (no jax)
  relay-http     HTTP GET / to the relay (is it an HTTP service at all?)
  backend-init   import jax; jax.devices() — PJRT client init + enumeration
  transfer       jax.device_put(np.arange(4)) + fetch — data plane
  compile        jit(x+1)(x) — compile plane (remote_compile=1 → relay POST)
  compile-local  same with PALLAS_AXON_REMOTE_COMPILE stripped — local compile

Results land in TPU_PROBE.json (merged into BENCH_DETAIL.json by bench.py)
so the round's failure signature is reproducible and diagnosable by the
infra owner: run `python tools/tpu_forensics.py`.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

RELAY_PORTS = (2024,)  # observed listening in the image (ss -tlnp)


def _stage_subprocess(name, code, timeout_s, env_extra=None, results=None):
    env = dict(os.environ)
    if env_extra:
        for k, v in env_extra.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
    wrapped = (
        "import sys, faulthandler; faulthandler.dump_traceback_later("
        f"{max(timeout_s - 5, 2)}, file=sys.stderr);\n" + code
    )
    t0 = time.time()
    out = {"timeout_s": timeout_s}
    try:
        r = subprocess.run(
            [sys.executable, "-c", wrapped], capture_output=True,
            timeout=timeout_s, text=True, env=env,
        )
        out.update(rc=r.returncode, stdout=r.stdout[-1500:],
                   stderr=r.stderr[-2500:])
        out["status"] = "ok" if r.returncode == 0 else "error"
    except subprocess.TimeoutExpired as e:
        se = e.stderr
        if isinstance(se, bytes):
            se = se.decode("utf-8", "replace")
        so = e.stdout
        if isinstance(so, bytes):
            so = so.decode("utf-8", "replace")
        out.update(status="timeout", stdout=(so or "")[-1500:],
                   stderr=(se or "")[-2500:])
    out["wall_s"] = round(time.time() - t0, 2)
    if results is not None:
        results[name] = out
    return out


def probe_relay(results):
    for port in RELAY_PORTS:
        key = f"relay-tcp:{port}"
        t0 = time.time()
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            s.close()
            results[key] = {"status": "ok", "wall_s": round(time.time() - t0, 3)}
        except OSError as e:
            results[key] = {"status": "error", "error": str(e)}
        # speak minimal HTTP at it — remote_compile implies an HTTP surface
        key = f"relay-http:{port}"
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            s.settimeout(5)
            s.sendall(b"GET / HTTP/1.1\r\nHost: 127.0.0.1\r\n\r\n")
            data = s.recv(512)
            s.close()
            results[key] = {
                "status": "ok",
                "first_bytes": data[:200].decode("utf-8", "replace"),
            }
        except OSError as e:
            results[key] = {"status": "error", "error": str(e)}


def deep_probe(results, hang_s=110, total_s=130):
    """Run jax.devices() and, while it hangs, sample the child's thread
    states (/proc wchan) — distinguishes a network wait from a retry loop.

    Round-4 captured signature: hang is inside PJRT ``make_c_api_client``
    (client INIT, before any device op); threads = main python in
    hrtimer_nanosleep (a sleep-retry loop), tokio-rt-worker in ep_poll
    (relay idle), axon-remote-loop in futex wait. I.e. the claim/bind
    handshake with the pool never completes and the plugin retries
    forever — matching the sitecustomize note about the bind loop
    ("grant unclaimed past timeout — client lost"). Infra-side: the relay
    accepts TCP but no grant ever arrives."""
    import collections
    import signal

    code = ("import sys, faulthandler; faulthandler.dump_traceback_later("
            f"{hang_s}, file=sys.stderr)\n"
            "import jax; print([str(d) for d in jax.devices()], flush=True)")
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True)
    samples = []
    t0 = time.time()
    while time.time() - t0 < total_s:
        time.sleep(5)
        if p.poll() is not None:
            break
        try:
            snap = []
            for t in os.listdir(f"/proc/{p.pid}/task"):
                try:
                    wchan = open(f"/proc/{p.pid}/task/{t}/wchan").read().strip()
                    name = open(f"/proc/{p.pid}/task/{t}/comm").read().strip()
                    snap.append(f"{name}:{wchan}")
                except OSError:
                    pass
            samples.append(snap)
        except OSError:
            break
    hung = p.poll() is None
    if hung:
        p.send_signal(signal.SIGABRT)
        time.sleep(2)
        p.kill()
    try:
        out, err = p.communicate(timeout=10)
    except subprocess.TimeoutExpired:
        out, err = "", ""
    hist = collections.Counter(x for s in samples for x in s)
    results["deep-init"] = {
        "status": "timeout" if hung else ("ok" if p.returncode == 0 else "error"),
        "stdout": (out or "")[-500:],
        "python_stack_at_timeout": (err or "")[-2000:],
        "thread_wchan_histogram": dict(hist.most_common(10)),
    }


def run_probe() -> dict:
    results: dict = {"env": {
        k: v for k, v in os.environ.items()
        if any(t in k for t in ("AXON", "TPU", "JAX", "PALLAS"))
    }}
    probe_relay(results)
    _stage_subprocess(
        "backend-init",
        "import jax; ds = jax.devices(); print([str(d) for d in ds])",
        60, results=results)
    if results["backend-init"]["status"] == "timeout":
        deep_probe(results)
    if results["backend-init"]["status"] == "ok":
        _stage_subprocess(
            "transfer",
            "import jax, numpy as np;"
            "x = jax.device_put(np.arange(4));"
            "print(np.asarray(x).tolist())",
            90, results=results)
        _stage_subprocess(
            "compile",
            "import jax, numpy as np;"
            "f = jax.jit(lambda x: x + 1);"
            "print(np.asarray(f(jax.device_put(np.arange(4)))).tolist())",
            120, results=results)
        if results.get("compile", {}).get("status") != "ok":
            _stage_subprocess(
                "compile-local",
                "import jax, numpy as np;"
                "f = jax.jit(lambda x: x + 1);"
                "print(np.asarray(f(jax.device_put(np.arange(4)))).tolist())",
                120, env_extra={"PALLAS_AXON_REMOTE_COMPILE": None},
                results=results)
    verdict = "wedged"
    if results.get("compile", {}).get("status") == "ok":
        verdict = "live"
        # "live" must mean the axon backend answered — a compile that ran on
        # the plain CPU PJRT client (env never routed to axon, or the plugin
        # isn't installed) is a healthy interpreter, not a healthy tunnel
        devs = results.get("backend-init", {}).get("stdout", "")
        axon_env = any("axon" in v.lower()
                       for v in results["env"].values())
        if not axon_env and "Tpu" not in devs and "Axon" not in devs:
            verdict = "cpu-only"
    elif results.get("backend-init", {}).get("status") != "ok":
        verdict = "init-failure"
    results["verdict"] = verdict
    return results


def _stamp(results: dict) -> None:
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "TPU_PROBE.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=1)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--retries", type=int, default=1,
                    help="probe attempts before giving up (standing retry: "
                         "the tunnel may come up mid-round)")
    ap.add_argument("--sleep", type=float, default=30.0,
                    help="seconds between attempts")
    args = ap.parse_args(argv)
    results: dict = {}
    for attempt in range(1, max(args.retries, 1) + 1):
        results = run_probe()
        results["attempt"] = attempt
        results["stamped_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())
        _stamp(results)
        if results["verdict"] == "live":
            break
        if attempt <= args.retries - 1:
            time.sleep(args.sleep)
    print(json.dumps({k: v.get("status", "n/a") if isinstance(v, dict) else v
                      for k, v in results.items() if k != "env"}))
    return 0 if results["verdict"] == "live" else 1


if __name__ == "__main__":
    sys.exit(main())
