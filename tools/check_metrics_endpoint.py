#!/usr/bin/env python
"""Tier-1 live-scrape gate for the /metrics endpoint.

Boots the real HTTP service (ephemeral port), drives a handful of
statements through POST /query so the latency/compile/queue histograms
actually observe samples, then scrapes /metrics and validates the
Prometheus exposition the way a collector would:

  * every `# TYPE <name> histogram` family exposes `<name>_bucket{le=...}`
    series ending in le="+Inf", plus `<name>_sum` and `<name>_count`;
  * bucket counts are cumulative (non-decreasing as le grows) and the
    +Inf bucket equals `_count`;
  * every exported family name carries the `sr_tpu_` prefix — the wire
    half of src_lint's R7 metric-name-prefix rule (declaration half).

Also scrapes the observability-plane JSON endpoints against their
schemas on the same live server: /api/audit (one record per driven
statement, terminal fields present), /api/events (list + per-type
counts over the closed taxonomy), /api/metrics/history (sampler ring
populated, samples carry counters/gauges/histograms), /api/workload
(per-fingerprint rolling stats aggregated the warm repeat), /api/alerts
(default rule set installed, states on the ok/firing enum),
/api/debug/bundle (the ADMIN DIAGNOSE document, all sections present —
including the round-19 workload/alerts sections), and /api/ingest
(plane stats + job rows after a driven PUT stream load, with the
sr_tpu_ingest_* families observed on the same scrape).

Exit 1 with a finding list on any violation, 0 otherwise.
"""

from __future__ import annotations

import json
import os
import re
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PREFIX = "sr_tpu_"

STATEMENTS = [
    "create table m_probe (a int, b int)",
    "insert into m_probe values (1, 2), (1, 3), (2, 4), (3, 5)",
    "select a, sum(b) sb from m_probe group by a",
    "select a, sum(b) sb from m_probe group by a",  # warm repeat
    "select count(*) from m_probe",
]


def scrape(port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        return r.read().decode()


def scrape_json(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return json.loads(r.read().decode())


AUDIT_FIELDS = ("query_id", "user", "stmt", "stmt_class", "tables",
                "state", "stage", "ms", "rows", "mem_peak_bytes")
BUNDLE_SECTIONS = ("running", "memory", "profiles", "audit_tail",
                   "events_tail", "event_counts", "metrics_history",
                   "lock_witness", "failpoints", "config_non_default",
                   "workload", "alerts")
WORKLOAD_FIELDS = ("fingerprint", "stmt_class", "count", "p50_ms",
                   "p95_ms", "p99_ms", "avg_ms", "errors", "sample_sql")
ALERT_FIELDS = ("name", "state", "metric", "condition", "for_s", "fires")


def validate_observability(port: int, n_statements: int) -> list[str]:
    """Schema-check the JSON observability endpoints on the live server
    (called while the statements just driven are still in the rings)."""
    findings: list[str] = []

    audit = scrape_json(port, "/api/audit")
    recs = audit.get("audit", [])
    if len(recs) < n_statements:
        findings.append(f"/api/audit retains {len(recs)} records after "
                        f"{n_statements} statements")
    for rec in recs[-n_statements:]:
        missing = [f for f in AUDIT_FIELDS if f not in rec]
        if missing:
            findings.append(f"/api/audit record {rec.get('seq')} missing "
                            f"fields {missing}")
            break
    if not isinstance(audit.get("stats", {}).get("registered"), int):
        findings.append("/api/audit stats.registered missing")

    from starrocks_tpu.runtime.events import TAXONOMY

    ev = scrape_json(port, "/api/events")
    if not isinstance(ev.get("events"), list):
        findings.append("/api/events payload missing 'events' list")
    for e in ev.get("events", []):
        if e.get("name") not in TAXONOMY:
            findings.append(f"/api/events entry {e.get('seq')} has "
                            f"off-taxonomy name {e.get('name')!r}")
            break
    for name in ev.get("counts", {}):
        if name not in TAXONOMY:
            findings.append(f"/api/events counts has off-taxonomy key "
                            f"{name!r}")
            break

    hist = scrape_json(port, "/api/metrics/history")
    samples = hist.get("samples")
    if not isinstance(samples, list) or not samples:
        findings.append("/api/metrics/history has no samples (sampler "
                        "not running on a live server?)")
    else:
        s = samples[-1]
        for key in ("ts", "counters", "gauges", "histograms"):
            if key not in s:
                findings.append(f"/api/metrics/history sample missing "
                                f"{key!r}")

    wl = scrape_json(port, "/api/workload")
    entries = wl.get("workload")
    if not isinstance(entries, list) or not entries:
        findings.append("/api/workload has no entries after live queries")
    else:
        missing = [f for f in WORKLOAD_FIELDS if f not in entries[0]]
        if missing:
            findings.append(f"/api/workload entry missing fields {missing}")
        # the warm repeat in STATEMENTS lands twice on one fingerprint
        if not any(e.get("count", 0) >= 2 for e in entries):
            findings.append("/api/workload never aggregated a repeated "
                            "statement shape (fingerprinting dead?)")

    al = scrape_json(port, "/api/alerts")
    rules = al.get("alerts")
    if not isinstance(rules, list) or not rules:
        findings.append("/api/alerts exposes no rules (default rule set "
                        "not installed?)")
    else:
        missing = [f for f in ALERT_FIELDS if f not in rules[0]]
        if missing:
            findings.append(f"/api/alerts rule missing fields {missing}")
        bad = [r.get("name") for r in rules
               if r.get("state") not in ("ok", "firing")]
        if bad:
            findings.append(f"/api/alerts rules with off-enum state: {bad}")

    bundle = scrape_json(port, "/api/debug/bundle")
    missing = [s for s in BUNDLE_SECTIONS if s not in bundle]
    if missing:
        findings.append(f"/api/debug/bundle missing sections {missing}")

    ing = scrape_json(port, "/api/ingest")
    plane = ing.get("ingest")
    if not isinstance(plane, dict):
        findings.append("/api/ingest payload missing 'ingest' stats dict")
    else:
        for key in ("staged_bytes", "staged_tables", "commits", "labels",
                    "jobs"):
            if key not in plane:
                findings.append(f"/api/ingest stats missing {key!r}")
        if plane.get("commits", 0) < 1:
            findings.append("/api/ingest shows no commit after the "
                            "driven stream load")
        if plane.get("staged_bytes", -1) != 0:
            findings.append("/api/ingest staged_bytes nonzero after the "
                            "load committed")
    if not isinstance(ing.get("jobs"), list):
        findings.append("/api/ingest payload missing 'jobs' list")
    return findings


def validate(text: str) -> list[str]:
    findings: list[str] = []
    types: dict[str, str] = {}
    for line in text.splitlines():
        m = re.match(r"# TYPE (\S+) (\S+)", line)
        if m:
            types[m.group(1)] = m.group(2)

    series = re.findall(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ",
                        text, re.M)
    for name, _labels in series:
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        if not (name.startswith(PREFIX) or base.startswith(PREFIX)):
            findings.append(f"series {name!r} lacks the {PREFIX!r} prefix")

    for name, typ in types.items():
        if not name.startswith(PREFIX):
            findings.append(f"family {name!r} lacks the {PREFIX!r} prefix")
        if typ != "histogram":
            continue
        buckets = re.findall(
            rf'^{re.escape(name)}_bucket{{le="([^"]+)"}} (\d+)$', text, re.M)
        if not buckets:
            findings.append(f"histogram {name} exposes no _bucket series")
            continue
        if buckets[-1][0] != "+Inf":
            findings.append(f"histogram {name} missing le=\"+Inf\" bucket")
        counts = [int(c) for _le, c in buckets]
        if counts != sorted(counts):
            findings.append(f"histogram {name} buckets not cumulative: "
                            f"{counts}")
        m_sum = re.search(rf"^{re.escape(name)}_sum ([-0-9.e+]+)$",
                          text, re.M)
        m_cnt = re.search(rf"^{re.escape(name)}_count (\d+)$", text, re.M)
        if m_sum is None:
            findings.append(f"histogram {name} missing _sum")
        if m_cnt is None:
            findings.append(f"histogram {name} missing _count")
        elif counts and counts[-1] != int(m_cnt.group(1)):
            findings.append(
                f"histogram {name}: +Inf bucket {counts[-1]} != _count "
                f"{m_cnt.group(1)}")
    return findings


def main() -> int:
    from starrocks_tpu.runtime.http_service import SqlHttpServer
    from starrocks_tpu.runtime.session import Session

    srv = SqlHttpServer(Session()).start()
    try:
        for sql in STATEMENTS:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/query",
                data=json.dumps({"sql": sql}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                json.loads(r.read())
        # one stream load over PUT so the ingest counters/histograms
        # observe, then /api/ingest and the sr_tpu_ingest_* families get
        # validated on the same live scrape
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/api/load/m_load?label=probe1",
            data=b"1,10\n2,20\n", method="PUT")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/query", timeout=120,
                data=json.dumps({"sql": "create table m_load (k int, "
                                 "v int, primary key (k))"}).encode()):
            pass
        with urllib.request.urlopen(req, timeout=120) as r:
            if json.loads(r.read()).get("status") != "ok":
                print("check_metrics_endpoint: PUT stream load failed")
                return 1
        text = scrape(srv.port)
        obs_findings = validate_observability(srv.port, len(STATEMENTS))
    finally:
        srv.stop()

    findings = validate(text) + obs_findings
    # the queries above must have landed samples in the read-latency and
    # compile histograms — an exposition that validates but never observes
    # would pass the shape checks while the instrumentation is dead
    for required in ("sr_tpu_query_latency_ms_read", "sr_tpu_compile_ms",
                     "sr_tpu_ingest_freshness_ms",
                     "sr_tpu_ingest_commit_ms"):
        m = re.search(rf"^{required}_count (\d+)$", text, re.M)
        if m is None or int(m.group(1)) == 0:
            findings.append(f"histogram {required} observed no samples "
                            f"after live queries")
    # and the ingest counters: the PUT above staged, committed, and rode
    # the load latency class
    for required in ("sr_tpu_ingest_loads_total", "sr_tpu_ingest_rows_total",
                     "sr_tpu_ingest_commits_total"):
        m = re.search(rf"^{required} (\d+)$", text, re.M)
        if m is None or int(m.group(1)) == 0:
            findings.append(f"counter {required} never incremented after "
                            f"the driven stream load")
    n_hist = sum(1 for t in types_of(text).values() if t == "histogram")
    for f in findings:
        print(f"check_metrics_endpoint: {f}")
    print(f"check_metrics_endpoint: {len(findings)} finding(s); "
          f"histograms={n_hist}")
    return 1 if findings else 0


def types_of(text: str) -> dict:
    return dict(re.findall(r"# TYPE (\S+) (\S+)", text))


if __name__ == "__main__":
    sys.exit(main())
