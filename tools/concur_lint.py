#!/usr/bin/env python
"""Concurrency-contract gate: static lock/guard + effect analysis +
module-boundary manifest enforcement over starrocks_tpu/.

Runs ahead of pytest in tools/run_tier1.sh (next to src_lint/plan_lint):

- analysis/concur_check.py — lock inventory, the cross-object
  lock-acquisition graph (lock-order cycles = potential deadlocks,
  lexical self-nesting of non-reentrant locks = certain deadlocks), and
  the `# guarded_by:` field discipline, strict: any error finding fails
  the gate. Warn findings (the unannotated-mutable-attr coverage ratchet)
  print and count but do not fail — bench.py tracks the count across
  rounds as `concur_findings`; use --strict-warn to ratchet hard.

- analysis/effects_check.py — interprocedural effect summaries over the
  same parse + name index: exception-safe acquire, checkpoint density of
  blocking loops, no blocking under lock, daemon-thread lifecycle. Warn
  findings are suppression annotations missing a reason (the
  `--strict-warn` ratchet keeps unexplained exceptions at zero);
  bench.py tracks the warn count as `effects_findings`.

- analysis/boundary_check.py — the repo-root module_boundary_manifest.json
  (the reference's be/module_boundary_manifest.json analog): every
  package-internal import must match its unit's declared allow/forbid
  prefixes; undeclared coupling fails.

The checkers are loaded by FILE PATH (not package import): the gate must
run on a box with no jax install, and starrocks_tpu/__init__.py pulls
jax. They share one parsed AST per module (analysis/astwalk.py) — the
same trees src_lint walks.

Exit 1 on any error finding; prints `concur_lint: ...` summary with the
counts the driver and bench read. `--json` emits the findings as one
machine-readable object instead (pass name, severity, contract rule,
file:line, message, per-pass stats) for dashboards and the driver.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name: str, rel: str):
    existing = sys.modules.get(name)
    if existing is not None:
        return existing
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def collect():
    """Run all three passes over ONE shared parse; returns
    (findings_by_pass, stats_by_pass, module_count)."""
    astwalk = _load("sr_astwalk", "starrocks_tpu/analysis/astwalk.py")
    concur_check = _load("sr_concur_check",
                         "starrocks_tpu/analysis/concur_check.py")
    effects_check = _load("sr_effects_check",
                          "starrocks_tpu/analysis/effects_check.py")
    boundary_check = _load("sr_boundary_check",
                           "starrocks_tpu/analysis/boundary_check.py")

    sources = astwalk.package_sources(REPO)
    crep = concur_check.check_sources(sources)
    erep = effects_check.check_sources(sources)
    bfindings = boundary_check.check_imports(
        boundary_check.load_manifest(REPO), sources)
    findings = {"concur": crep.findings, "effects": erep.findings,
                "boundary": bfindings}
    stats = {"concur": crep.stats, "effects": erep.stats}
    return findings, stats, len(sources)


def run(strict_warn: bool = False, as_json: bool = False) -> int:
    by_pass, stats, n_modules = collect()
    flat = [(p, f) for p in ("concur", "effects", "boundary")
            for f in by_pass[p]]
    errors = [f for _, f in flat if f.severity == "error"]
    warns = [f for _, f in flat if f.severity == "warn"]
    failed = bool(errors or (strict_warn and warns))

    if as_json:
        out = {
            "ok": not failed,
            "errors": len(errors),
            "warns": len(warns),
            "modules": n_modules,
            "suppressions": stats["effects"]["suppressions"],
            "suppressions_unexplained":
                stats["effects"]["suppressions_unexplained"],
            "findings": [
                {"pass": p, "severity": f.severity, "rule": f.rule,
                 "where": f.where, "message": f.message}
                for p, f in flat
            ],
            "stats": stats,
        }
        print(json.dumps(out, indent=1, sort_keys=True))
        return 1 if failed else 0

    for _, f in flat:
        print(f)
    cst, est = stats["concur"], stats["effects"]
    print(f"concur_lint: {len(errors)} error(s), {len(warns)} warn(s); "
          f"locks={cst['locks']} guarded_attrs={cst['guarded_attrs']} "
          f"order_edges={cst['edges']} "
          f"effect_fns={est['functions']} acquires={est['acquire_sites']} "
          f"suppressions={est['suppressions']} modules={n_modules}")
    return 1 if failed else 0


def main():
    ap = argparse.ArgumentParser(
        description="static lock-order + guarded-by + effect-contract + "
                    "module-boundary gate")
    ap.add_argument("--strict-warn", action="store_true",
                    help="fail on warn-level findings too (the coverage "
                         "ratchet, once annotations reach 100%%)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings object on stdout")
    args = ap.parse_args()
    return run(strict_warn=args.strict_warn, as_json=args.as_json)


if __name__ == "__main__":
    sys.exit(main())
