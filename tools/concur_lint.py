#!/usr/bin/env python
"""Concurrency-contract gate: static lock/guard analysis + module-boundary
manifest enforcement over starrocks_tpu/.

Runs ahead of pytest in tools/run_tier1.sh (next to src_lint/plan_lint):

- analysis/concur_check.py — lock inventory, the cross-object
  lock-acquisition graph (lock-order cycles = potential deadlocks,
  lexical self-nesting of non-reentrant locks = certain deadlocks), and
  the `# guarded_by:` field discipline, strict: any error finding fails
  the gate. Warn findings (the unannotated-mutable-attr coverage ratchet)
  print and count but do not fail — bench.py tracks the count across
  rounds as `concur_findings`; use --strict-warn to ratchet hard.

- analysis/boundary_check.py — the repo-root module_boundary_manifest.json
  (the reference's be/module_boundary_manifest.json analog): every
  package-internal import must match its unit's declared allow/forbid
  prefixes; undeclared coupling fails.

The checkers are loaded by FILE PATH (not package import): the gate must
run on a box with no jax install, and starrocks_tpu/__init__.py pulls
jax. They share one parsed AST per module (analysis/astwalk.py) — the
same trees src_lint walks.

Exit 1 on any error finding; prints `concur_lint: ...` summary with the
counts the driver and bench read.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name: str, rel: str):
    existing = sys.modules.get(name)
    if existing is not None:
        return existing
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def run(strict_warn: bool = False) -> int:
    astwalk = _load("sr_astwalk", "starrocks_tpu/analysis/astwalk.py")
    concur_check = _load("sr_concur_check",
                         "starrocks_tpu/analysis/concur_check.py")
    boundary_check = _load("sr_boundary_check",
                           "starrocks_tpu/analysis/boundary_check.py")

    sources = astwalk.package_sources(REPO)
    rep = concur_check.check_sources(sources)
    bfindings = boundary_check.check_imports(
        boundary_check.load_manifest(REPO), sources)

    findings = rep.findings + bfindings
    errors = [f for f in findings if f.severity == "error"]
    warns = [f for f in findings if f.severity == "warn"]
    for f in findings:
        print(f)
    st = rep.stats
    print(f"concur_lint: {len(errors)} error(s), {len(warns)} warn(s); "
          f"locks={st['locks']} guarded_attrs={st['guarded_attrs']} "
          f"order_edges={st['edges']} modules={len(sources)}")
    if errors or (strict_warn and warns):
        return 1
    return 0


def main():
    ap = argparse.ArgumentParser(
        description="static lock-order + guarded-by + module-boundary gate")
    ap.add_argument("--strict-warn", action="store_true",
                    help="fail on warn-level findings too (the coverage "
                         "ratchet, once annotations reach 100%%)")
    args = ap.parse_args()
    return run(strict_warn=args.strict_warn)


if __name__ == "__main__":
    sys.exit(main())
