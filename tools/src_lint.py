#!/usr/bin/env python
"""AST source lint for JAX pitfalls in starrocks_tpu/.

Four rules, all for bug classes that pass every unit test and then burn
on real hardware (or real traffic):

R1 shard-map-shim: `shard_map` must be imported from parallel/mesh.py (the
   version shim that handles the jax>=0.6 move and the check_vma/check_rep
   rename), never from jax directly. A bare import works on exactly one jax
   version.

R2 traced-host-op: inside TRACED scopes — functions handed to jax.jit /
   shard_map, and the program closures built by compile_plan /
   compile_distributed (`run` / `step`) — calling `.item()` or
   `np.asarray`/`np.array` on a traced value either crashes at trace time
   (ConcretizationTypeError) or silently freezes a trace-time constant into
   the program. Host callbacks registered via pure_callback/io_callback/
   debug_callback are exempt (numpy there is the point), as is any line
   tagged `# lint: host-ok`.

R3 cache-key-knob: inside the query cache's key builders
   (starrocks_tpu/cache/keys.py), every LITERAL `config.get("name")` must
   name a knob declared `trace=True` or `cache_key=True` at its
   `config.define` site (statically parsed from runtime/config.py — no
   import needed). Undeclared reads punch a hole in the result-key
   completeness proof: analysis/key_check.py audits the DYNAMIC read-set,
   this rule pins the STATIC one, and the two meet at the declaration.
   Non-literal reads (`config.get(k) for k in OPT_KEY_KNOBS`) are the
   shared opt-key channel and stay legal.

R4 swallowed-exception: in starrocks_tpu/runtime/, an `except Exception`
   (or bare `except`) handler must re-raise, convert to a typed query
   error (any `raise` in the handler body), or carry `# lint: swallow-ok`
   on its `except` line. A silently swallowed exception in the runtime is
   how admission slots leak, journals wedge half-written, and killed
   queries report success — the failure classes tests/test_chaos.py
   injects. Deliberate swallows (liveness loops, best-effort listeners)
   stay legal via the tag, which doubles as documentation.

R5 serve-query-scope: the serving tier's executor-pool worker body
   (runtime/serving.py `_run_statement`) must execute its statement via
   `session.sql(...)` INSIDE a `with ... query_scope(...)` block, and
   nothing in serving.py may call the session's internal execution
   surfaces (`_sql_inner` / `_query_planned` / `_query_admitted` /
   `execute_logical`) directly. A statement that runs outside a
   query_scope is invisible to SHOW PROCESSLIST, unkillable, deadline-
   free, and unaccounted — the exact bug class thread fan-out invites.

R6 feedback-key-knob: in the plan-feedback consult path
   (starrocks_tpu/runtime/feedback.py), every LITERAL `config.get("name")`
   must name a knob on SOME cache-key channel: declared trace=True or
   cache_key=True at its config.define site, or listed in OPT_KEY_KNOBS /
   HOST_LOOP_KNOBS (analysis/key_check.py). Feedback entries are keyed by
   a fingerprint over exactly those channels — a consult that also reads
   an un-channeled knob could hand two different observation sets to two
   executions with identical fingerprints, silently splitting the learned
   state (analysis/key_check.check_feedback_reads audits the DYNAMIC
   read-set; this rule pins the STATIC one).

R7 metric-name-prefix: every LITERAL metric name handed to
   `metrics.counter/gauge/histogram(...)` must start with `sr_tpu_`. The
   /metrics scrape is consumed by Prometheus relabel rules and dashboards
   keyed on that prefix; one unprefixed series silently drops out of every
   alert. Enforced at the declaration site so the tier-1 live-scrape check
   (tools/check_metrics_endpoint.py) can assert the same invariant on the
   wire and the two meet at the registry.

R8 point-query-scope: the short-circuit point lane's execution entry
   (runtime/point.py `try_execute`) may be called from exactly ONE place —
   `Session._sql_inner` (runtime/session.py), which always runs inside
   `lifecycle.query_scope` (the R5 contract applied to the lane). Serving
   code may consult the PURE text probe `point.peek_select` for its gate
   claim but must never call the lane's execution internals; a second
   entry point would execute PK lookups outside the registered/killable/
   accounted plane. `try_execute` itself must hit a `lifecycle.checkpoint`
   before the index probe so an in-flight KILL lands.

R9 event-taxonomy: system events are journaled ONLY through the
   sanctioned API `events.emit("<name>", ...)` with a LITERAL name in
   the closed TAXONOMY statically parsed from runtime/events.py (no
   import — same discipline as R3/R6). Computed names, off-taxonomy
   literals, and direct `EVENTS.emit(...)` calls outside events.py all
   fail: the taxonomy is the contract dashboards and the /api/events
   schema check key on, and an ad-hoc event string silently drops out
   of every per-type counter.

The lint also counts `fail_point()` call sites across the package and
fails below the chaos-suite floor (MIN_FAILPOINT_SITES): fault-injection
coverage is an invariant here, not a nice-to-have.

Exit 1 on any finding; each names file:line, the rule, and the offending op.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "starrocks_tpu")
SHIM = os.path.join("starrocks_tpu", "parallel", "mesh.py")


def _astwalk():
    """The shared AST walk (analysis/astwalk.py): every static gate —
    src_lint, concur_lint — reads the SAME parsed tree per module instead
    of re-parsing the package per checker. Loaded by file path: importing
    the starrocks_tpu package would pull jax, and this lint must run on a
    bare checkout."""
    mod = sys.modules.get("sr_astwalk")
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location(
        "sr_astwalk", os.path.join(PKG, "analysis", "astwalk.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["sr_astwalk"] = mod
    spec.loader.exec_module(mod)
    return mod

CALLBACK_FNS = {"pure_callback", "io_callback", "debug_callback"}
TRACE_BUILDERS = {"compile_plan": {"run"}, "compile_distributed": {"step"}}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _is_np(node) -> bool:
    return isinstance(node, ast.Name) and node.id in ("np", "numpy")


class Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.findings: list = []
        self._traced_depth = 0
        self._func_stack: list = []
        # names of local functions passed to jit/shard_map somewhere in
        # this module: defs with those names are traced roots
        self.traced_names: set = set()
        # (lineno of defs that are callback host-fns) — exempt subtrees
        self.callback_args: set = set()

    def add(self, node, rule, msg):
        line = self.lines[node.lineno - 1] if node.lineno <= len(
            self.lines) else ""
        if "lint: host-ok" in line:
            return
        self.findings.append(f"{self.rel}:{node.lineno}: [{rule}] {msg}")

    # --- pass 1: collect traced / callback names -----------------------------
    def collect(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name in ("jit", "shard_map"):
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            self.traced_names.add(a.id)
                if name in CALLBACK_FNS and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Name):
                        self.callback_args.add(a.id)

    # --- pass 2: walk with traced-scope tracking -----------------------------
    def visit_Import(self, node):
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        names = {a.name for a in node.names}
        if self.rel != SHIM and (
                ("shard_map" in names and mod.startswith("jax"))
                or mod == "jax.experimental.shard_map"):
            self.add(node, "shard-map-shim",
                     f"import shard_map from parallel/mesh.py, not "
                     f"{mod!r} (version shim bypassed)")
        self.generic_visit(node)

    def visit_Attribute(self, node):
        # jax.experimental.shard_map.* attribute access
        if (node.attr == "shard_map" and isinstance(node.value, ast.Attribute)
                and node.value.attr == "experimental"
                and self.rel != SHIM):
            self.add(node, "shard-map-shim",
                     "use parallel/mesh.py's shard_map shim")
        self.generic_visit(node)

    def _enter_func(self, node):
        traced = False
        name = getattr(node, "name", "<lambda>")
        if name in self.traced_names:
            traced = True
        parent = self._func_stack[-1] if self._func_stack else None
        if parent is not None and name in TRACE_BUILDERS.get(parent, ()):
            traced = True
        if self._traced_depth and name in self.callback_args:
            traced = False  # host callback body nested in a traced scope
            self._func_stack.append(name)
            self._visit_body(node, bump=0, host_exempt=True)
            self._func_stack.pop()
            return
        self._func_stack.append(name)
        self._visit_body(node, bump=1 if (traced or self._traced_depth) else 0)
        self._func_stack.pop()

    def _visit_body(self, node, bump: int, host_exempt: bool = False):
        if host_exempt:
            # walk without traced context (nested defs restart clean)
            saved = self._traced_depth
            self._traced_depth = 0
            for child in ast.iter_child_nodes(node):
                self.visit(child)
            self._traced_depth = saved
            return
        self._traced_depth += bump
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._traced_depth -= bump

    def visit_FunctionDef(self, node):
        self._enter_func(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._visit_body(node, bump=0)

    def visit_Call(self, node):
        if self._traced_depth:
            name = _call_name(node)
            if name == "item" and isinstance(node.func, ast.Attribute):
                self.add(node, "traced-host-op",
                         ".item() inside a traced function pulls the value "
                         "to host (trace-time concretization)")
            if name in ("asarray", "array") and isinstance(
                    node.func, ast.Attribute) and _is_np(node.func.value):
                self.add(node, "traced-host-op",
                         f"np.{name}() inside a traced function freezes a "
                         f"trace-time constant (use jnp, or tag the line "
                         f"`# lint: host-ok` if the operand is static)")
        self.generic_visit(node)


RUNTIME_PREFIX = os.path.join("starrocks_tpu", "runtime") + os.sep
MIN_FAILPOINT_SITES = 51  # ratchet: includes the ingest plane's 4 sites
#                           (ingest::stage/commit/label_journal/poll)


def _is_exception_catch(handler: ast.ExceptHandler) -> bool:
    """True for `except Exception` / bare `except` (incl. tuples holding
    Exception). Narrow typed catches are R4-exempt: they name what they
    swallow."""
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return "Exception" in names or "BaseException" in names


def lint_runtime_swallow(path: str, rel: str, src: str, tree) -> list:
    """R4: see module docstring."""
    if not rel.startswith(RUNTIME_PREFIX):
        return []
    lines = src.splitlines()
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_exception_catch(node):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "lint: swallow-ok" in line:
            continue
        if any(isinstance(n, ast.Raise) for b in node.body
               for n in ast.walk(b)):
            continue  # re-raises or converts to a typed error
        findings.append(
            f"{rel}:{node.lineno}: [runtime-swallow] `except Exception` in "
            f"runtime/ must re-raise, convert to a typed query error, or "
            f"carry `# lint: swallow-ok` on the except line")
    return findings


def count_failpoints(sources) -> int:
    """Static count of fail_point(...) call sites across the package (the
    chaos-coverage floor reported next to the findings)."""
    n = 0
    for ms in sources:
        for node in ast.walk(ms.tree):
            if isinstance(node, ast.Call) \
                    and _call_name(node) == "fail_point":
                n += 1
    return n


CACHE_KEY_MODULE = os.path.join("starrocks_tpu", "cache", "keys.py")
CONFIG_MODULE = os.path.join(PKG, "runtime", "config.py")


def _declared_key_knobs() -> dict:
    """{knob name: (trace, cache_key)} parsed from the config.define calls
    in runtime/config.py — purely static, so the lint needs no package
    import (and can't be fooled by runtime monkey-patching)."""
    with open(CONFIG_MODULE) as f:
        tree = ast.parse(f.read())
    out = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and _call_name(node) == "define"
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            flags = {
                kw.arg: bool(kw.value.value)
                for kw in node.keywords
                if kw.arg in ("trace", "cache_key")
                and isinstance(kw.value, ast.Constant)
            }
            out[node.args[0].value] = (
                flags.get("trace", False), flags.get("cache_key", False))
    return out


def lint_cache_keys() -> list:
    """R3: literal config.get reads inside cache-key construction must be
    declared trace=True or cache_key=True (see module docstring)."""
    path = os.path.join(REPO, CACHE_KEY_MODULE)
    if not os.path.exists(path):
        return []
    declared = _declared_key_knobs()
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{CACHE_KEY_MODULE}:{e.lineno}: [parse] {e.msg}"]
    lines = src.splitlines()
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "config"
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "lint: host-ok" in line:
            continue
        name = node.args[0].value
        trace, cache_key = declared.get(name, (False, False))
        if not (trace or cache_key):
            findings.append(
                f"{CACHE_KEY_MODULE}:{node.lineno}: [cache-key-knob] "
                f"config.get({name!r}) inside cache-key construction: "
                f"declare the knob trace=True or cache_key=True at its "
                f"config.define site, or the result key cannot be proven "
                f"complete")
    return findings


FEEDBACK_MODULE = os.path.join("starrocks_tpu", "runtime", "feedback.py")
KEY_CHECK_MODULE = os.path.join(PKG, "analysis", "key_check.py")


def _keyed_knob_channels() -> set:
    """Every knob name on SOME cache-key channel: declared trace=True or
    cache_key=True in runtime/config.py, plus the members of OPT_KEY_KNOBS
    and HOST_LOOP_KNOBS in analysis/key_check.py — all statically parsed,
    same no-import discipline as R3."""
    names = {k for k, (t, c) in _declared_key_knobs().items() if t or c}
    with open(KEY_CHECK_MODULE) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not (isinstance(tgt, ast.Name)
                    and tgt.id in ("OPT_KEY_KNOBS", "HOST_LOOP_KNOBS")):
                continue
            v = node.value
            if isinstance(v, (ast.Tuple, ast.List)):
                names |= {e.value for e in v.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)}
            elif isinstance(v, ast.Dict):
                names |= {k.value for k in v.keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str)}
    return names


def lint_feedback_keys(src: str | None = None,
                       rel: str = FEEDBACK_MODULE) -> list:
    """R6: see module docstring. `src` is injectable so the golden
    bad-fixture test (tests/test_plan_feedback.py) can prove the rule
    rejects what it exists to reject."""
    if src is None:
        path = os.path.join(REPO, rel)
        if not os.path.exists(path):
            return [f"{rel}:1: [feedback-key-knob] plan-feedback module "
                    f"missing (the consult path is a keyed surface)"]
        with open(path) as f:
            src = f.read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: [parse] {e.msg}"]
    channels = _keyed_knob_channels()
    lines = src.splitlines()
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "config"
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if "lint: host-ok" in line:
            continue
        name = node.args[0].value
        if name not in channels:
            findings.append(
                f"{rel}:{node.lineno}: [feedback-key-knob] "
                f"config.get({name!r}) in the feedback consult path is on "
                f"no cache-key channel (trace/cache_key declaration, "
                f"OPT_KEY_KNOBS, or HOST_LOOP_KNOBS): identical plan "
                f"fingerprints could consult different observations")
    return findings


SERVING_MODULE = os.path.join("starrocks_tpu", "runtime", "serving.py")
_SESSION_INTERNALS = {"_sql_inner", "_query_planned", "_query_admitted",
                      "execute_logical"}


METRIC_PREFIX = "sr_tpu_"
_METRIC_FACTORIES = ("counter", "gauge", "histogram")


def lint_metric_names(sources) -> list:
    """R7: literal metric names at `metrics.counter/gauge/histogram(...)`
    declaration sites must carry the sr_tpu_ exporter prefix."""
    findings = []
    for ms in sources:
        for node in ast.walk(ms.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_FACTORIES
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "metrics"):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # computed names are registry-internal helpers
            name = node.args[0].value
            if not name.startswith(METRIC_PREFIX):
                findings.append(
                    f"{ms.rel}:{node.lineno}: [metric-name-prefix] "
                    f"metrics.{node.func.attr}({name!r}) — exported series "
                    f"must start with {METRIC_PREFIX!r}")
    return findings


def _declared_event_taxonomy() -> frozenset:
    """Statically parse the closed event taxonomy from the
    `TAXONOMY = frozenset((...))` literal in runtime/events.py — no
    import, same discipline as _declared_key_knobs."""
    path = os.path.join(REPO, "starrocks_tpu", "runtime", "events.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return frozenset()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and node.targets
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "TAXONOMY"):
            continue
        names = set()
        for c in ast.walk(node.value):
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                names.add(c.value)
        return frozenset(names)
    return frozenset()


def lint_event_names(sources) -> list:
    """R9: see module docstring."""
    taxonomy = _declared_event_taxonomy()
    findings = []
    for ms in sources:
        in_events_module = ms.rel.endswith("runtime/events.py")
        for node in ast.walk(ms.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and isinstance(node.func.value, ast.Name)):
                continue
            owner = node.func.value.id
            if owner == "EVENTS" and not in_events_module:
                findings.append(
                    f"{ms.rel}:{node.lineno}: [event-taxonomy] direct "
                    f"EVENTS.emit(...) — journal through the sanctioned "
                    f"events.emit(...) API")
                continue
            if owner != "events":
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                findings.append(
                    f"{ms.rel}:{node.lineno}: [event-taxonomy] "
                    f"events.emit(...) with a computed name — event types "
                    f"are a closed taxonomy (runtime/events.py)")
                continue
            name = node.args[0].value
            if name not in taxonomy:
                findings.append(
                    f"{ms.rel}:{node.lineno}: [event-taxonomy] "
                    f"events.emit({name!r}) — not in the declared "
                    f"taxonomy (runtime/events.py TAXONOMY)")
    return findings


def lint_serving_scope(sources) -> list:
    """R5: see module docstring."""
    ms = next((m for m in sources if m.rel == SERVING_MODULE), None)
    if ms is None:
        return [f"{SERVING_MODULE}:1: [serve-query-scope] serving tier "
                f"module missing (the executor pool is a tier-1 surface)"]
    findings = []
    run_fn = None
    for node in ast.walk(ms.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "_run_statement":
            run_fn = node
        if isinstance(node, ast.Call) \
                and _call_name(node) in _SESSION_INTERNALS:
            findings.append(
                f"{ms.rel}:{node.lineno}: [serve-query-scope] serving "
                f"code must execute statements via session.sql inside a "
                f"query_scope, never {_call_name(node)}() directly")
    if run_fn is None:
        findings.append(
            f"{ms.rel}:1: [serve-query-scope] missing `_run_statement` "
            f"worker body (the pool's single statement entry point)")
        return findings
    scoped_ok = False
    for node in ast.walk(run_fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(isinstance(i.context_expr, ast.Call)
                   and _call_name(i.context_expr) == "query_scope"
                   for i in node.items):
            continue
        inner = {_call_name(c) for b in node.body for c in ast.walk(b)
                 if isinstance(c, ast.Call)}
        if "sql" in inner:
            scoped_ok = True
    if not scoped_ok:
        findings.append(
            f"{ms.rel}:{run_fn.lineno}: [serve-query-scope] "
            f"_run_statement must call session.sql(...) INSIDE `with "
            f"query_scope(...)` — unregistered statement execution is "
            f"unkillable, deadline-free, and unaccounted")
    return findings


POINT_MODULE = os.path.join("starrocks_tpu", "runtime", "point.py")
SESSION_MODULE = os.path.join("starrocks_tpu", "runtime", "session.py")
_POINT_INTERNALS = {"try_execute", "_run_select", "_run_update",
                    "_run_delete", "_resolve"}


def lint_point_scope(sources) -> list:
    """R8: see module docstring."""
    pm = next((m for m in sources if m.rel == POINT_MODULE), None)
    if pm is None:
        return [f"{POINT_MODULE}:1: [point-query-scope] point-lane module "
                f"missing (the short-circuit read path is a tier-1 "
                f"surface)"]
    findings = []
    # the lane's entry must checkpoint before the probe: a KILL delivered
    # mid-lookup needs a stage boundary to land on
    entry = next((n for n in ast.walk(pm.tree)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n.name == "try_execute"), None)
    if entry is None:
        findings.append(
            f"{pm.rel}:1: [point-query-scope] missing `try_execute` (the "
            f"lane's single execution entry point)")
    elif not any(isinstance(c, ast.Call) and _call_name(c) == "checkpoint"
                 for c in ast.walk(entry)):
        findings.append(
            f"{pm.rel}:{entry.lineno}: [point-query-scope] try_execute "
            f"must call lifecycle.checkpoint(...) before the index probe "
            f"— an unkillable point lane breaks the KILL contract")
    # callers: point-lane execution internals are reachable from exactly
    # one site, Session._sql_inner (itself pinned inside query_scope)
    for ms in sources:
        if ms.rel == POINT_MODULE:
            continue
        sql_inner = next(
            (n for n in ast.walk(ms.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and n.name == "_sql_inner"), None) \
            if ms.rel == SESSION_MODULE else None
        allowed = set()
        if sql_inner is not None:
            allowed = {id(c) for c in ast.walk(sql_inner)
                       if isinstance(c, ast.Call)}
        for node in ast.walk(ms.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _POINT_INTERNALS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "point"):
                continue
            if id(node) in allowed:
                continue
            findings.append(
                f"{ms.rel}:{node.lineno}: [point-query-scope] "
                f"point.{node.func.attr}() outside Session._sql_inner — "
                f"the short-circuit lane must enter through the "
                f"query_scope'd session path (peek_select is the only "
                f"serving-side probe)")
    return findings


def lint_module(ms) -> list:
    linter = Linter(ms.path, ms.rel, ms.src)
    linter.collect(ms.tree)
    for node in ms.tree.body:
        linter.visit(node)
    return linter.findings + lint_runtime_swallow(
        ms.path, ms.rel, ms.src, ms.tree)


def main():
    try:
        sources = _astwalk().package_sources(REPO)
    except SyntaxError as e:
        print(f"{e.filename}:{e.lineno}: [parse] {e.msg}")
        print("src_lint: 1 finding(s); failpoint_sites=?")
        return 1
    findings = []
    for ms in sources:
        findings += lint_module(ms)
    findings += lint_cache_keys()
    findings += lint_feedback_keys()
    findings += lint_serving_scope(sources)
    findings += lint_metric_names(sources)
    findings += lint_point_scope(sources)
    findings += lint_event_names(sources)
    n_fp = count_failpoints(sources)
    if n_fp < MIN_FAILPOINT_SITES:
        findings.append(
            f"starrocks_tpu/: [failpoint-floor] only {n_fp} fail_point() "
            f"call sites; the chaos-suite floor is {MIN_FAILPOINT_SITES}")
    for f in findings:
        print(f)
    print(f"src_lint: {len(findings)} finding(s); failpoint_sites={n_fp}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
