#!/usr/bin/env bash
# Tier-1 verify gate — the EXACT pytest command from ROADMAP.md ("Tier-1
# verify"), so builders and reviewers run the same thing the driver
# enforces, preceded by the static-analysis gates (tools/src_lint.py +
# tools/plan_lint.py --corpus): new invariant violations fail the gate
# before the test suite even starts.
# Prints DOTS_PASSED=<n> and exits non-zero on any failing stage.
cd "$(dirname "$0")/.." || exit 1

echo "== src_lint =="
python tools/src_lint.py || exit 1

echo "== concur_lint (lock order + guarded-by + effects + module boundaries) =="
# --strict-warn: the round-11 coverage ratchet is LOCKED (round 12 burned
# the last TabletStore warnings down to zero) — any new unannotated
# mutable attr on a lock-owning class fails the gate. The effects pass
# (acquire safety / checkpoint density / no-blocking-under-lock / thread
# lifecycle) rides the same flag: a suppression without a reason fails.
python tools/concur_lint.py --strict-warn || exit 1

echo "== plan_lint --corpus =="
timeout -k 10 900 env JAX_PLATFORMS=cpu python tools/plan_lint.py --corpus || exit 1

echo "== plan_lint --fragments (fragment IR vs monolithic byte identity) =="
timeout -k 10 1200 env JAX_PLATFORMS=cpu python tools/plan_lint.py --fragments || exit 1

echo "== /metrics live scrape (Prometheus exposition + sr_tpu_ prefix) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/check_metrics_endpoint.py || exit 1

echo "== chaos_fuzz --coverage-check (failpoint coverage of acquire sites) =="
# round-20 ratchet: every static acquire site must have a failpoint-
# reachable unwind path in its module, or a written exemption in
# chaos_fuzz.COVERAGE_EXEMPT — an uncovered module fails the gate.
timeout -k 10 120 env JAX_PLATFORMS=cpu python tools/chaos_fuzz.py \
  --coverage-check || exit 1

echo "== chaos suite (failpoint/KILL/timeout/mem-limit scenarios) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py \
  -q -m chaos -p no:cacheprovider || exit 1

# Opt-in randomized fault-schedule fuzz (NEXT 7d first cut): set
# SR_TPU_CHAOS_FUZZ=1 to run with the pinned seed below; set it to any
# other integer to fuzz that seed instead. Failures print the seed, so
# a red run replays bit-identically via tools/chaos_fuzz.py --seed N.
if [ -n "${SR_TPU_CHAOS_FUZZ:-}" ]; then
  seed=20260805
  [ "$SR_TPU_CHAOS_FUZZ" != "1" ] && seed="$SR_TPU_CHAOS_FUZZ"
  echo "== chaos_fuzz (randomized fault schedules, seed=$seed) =="
  timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/chaos_fuzz.py \
    --seed "$seed" --rounds 8 || exit 1
fi

# Opt-in cluster chaos (ISSUE 20): set SR_TPU_CLUSTER_CHAOS=1 to drive a
# REAL coordinator + 2 worker processes through seeded process-kill /
# blackhole / delay fault families at the pinned seed (any other integer
# fuzzes that seed). A red run replays bit-identically via
# tools/chaos_fuzz.py --cluster --seed N.
if [ -n "${SR_TPU_CLUSTER_CHAOS:-}" ]; then
  seed=20260805
  [ "$SR_TPU_CLUSTER_CHAOS" != "1" ] && seed="$SR_TPU_CLUSTER_CHAOS"
  echo "== chaos_fuzz --cluster (worker-kill/partition fault families, seed=$seed) =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/chaos_fuzz.py \
    --cluster --seed "$seed" || exit 1
fi

echo "== tier-1 pytest =="
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
