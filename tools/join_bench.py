#!/usr/bin/env python
"""Join-engine microbench (round 13): same-box A/B of the three join
upgrades, merged into BENCH_DETAIL.json under "join_bench".

1. unique-join probe strategy: sorted (jnp argsort + searchsorted) vs
   pallas_sorted (explicit binary-search ladder kernel) vs pallas
   (open-addressing hash-table build+probe kernels). Off-TPU the Pallas
   kernels run in INTERPRET mode — correctness-comparable, not
   perf-comparable; the numbers become meaningful on silicon
   (`interpret` is recorded so readers can't misread CPU rows).
2. skewed partitioned join: hybrid (skew-aware dynamic build
   partitioning) vs the legacy grace path on a build whose single hot
   key previously forced the ENTIRE build through the partition loop —
   the acceptance scenario: hybrid spills zero partitions and must not
   lose to grace.
3. oversized cold partition: many sub-threshold keys crafted to hash
   into one partition whose build is 4x the batch budget. Legacy runs
   it as one oversized pass; recursive salted repartitioning
   (join_recursive_repartition, ISSUE 11) must bound every pass's build
   by the budget while returning identical rows.

Usage: python tools/join_bench.py [--rows N] [--build N] [--repeats N]
       [--no-detail]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def _best(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_probe_strategies(n_probe: int, n_build: int, repeats: int) -> dict:
    """Time the unique-join build+probe under each strategy through the
    REAL kernel entry points (ops/join.py), matches verified equal."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from starrocks_tpu.ops.join import hash_probe_rows
    from starrocks_tpu.ops.pallas_kernels import probe_searchsorted_pallas

    rng = np.random.default_rng(7)
    bk = jnp.asarray(rng.permutation(n_build * 4)[:n_build].astype(np.int64))
    pk = jnp.asarray(rng.integers(0, n_build * 4, n_probe).astype(np.int64))
    interpret = jax.default_backend() != "tpu"

    @jax.jit
    def sorted_path(bk, pk):
        order = jnp.argsort(bk, stable=True)
        bks = bk[order]
        pos = jnp.clip(jnp.searchsorted(bks, pk), 0, n_build - 1)
        match = bks[pos] == pk
        return match.sum(), order[pos]

    @jax.jit
    def ladder_path(bk, pk):
        order = jnp.argsort(bk, stable=True)
        bks = bk[order]
        pos = jnp.clip(probe_searchsorted_pallas(
            bks, pk, block=2048, interpret=interpret), 0, n_build - 1)
        match = bks[pos] == pk
        return match.sum(), order[pos]

    @jax.jit
    def hash_path(bk, pk):
        match, row = hash_probe_rows(
            bk, pk, n_build, jnp.ones(pk.shape, jnp.bool_))
        return match.sum(), row

    out = {"rows_probe": n_probe, "rows_build": n_build,
           "backend": jax.default_backend(), "interpret": interpret}
    counts = {}
    for name, fn in (("sorted", sorted_path), ("pallas_sorted", ladder_path),
                     ("pallas_hash", hash_path)):
        m, _ = fn(bk, pk)  # compile + correctness anchor
        counts[name] = int(m)
        best = _best(lambda: jax.block_until_ready(fn(bk, pk)), repeats)
        out[f"{name}_ms"] = round(best * 1000, 2)
        out[f"{name}_rows_per_sec"] = round(n_probe / best)
    assert len(set(counts.values())) == 1, f"strategy mismatch: {counts}"
    out["matches"] = counts["sorted"]
    return out


def bench_skewed_hybrid_vs_grace(n_probe: int, n_build: int, repeats: int,
                                 batch_rows: int) -> dict:
    """The acceptance A/B: one hot key owns half the build. Grace
    partitions + streams EVERYTHING; hybrid routes the hot key to the
    broadcast lane, keeps in-budget partitions resident, and spills only
    the overflow."""
    import numpy as np

    from starrocks_tpu.column import HostTable
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.storage.catalog import Catalog

    rng = np.random.default_rng(17)
    bk = rng.integers(0, n_build, n_build)
    bk[: n_build // 2] = 42  # the hot key owns half the build: under
    # grace ONE partition carries it, so every partition pass compiles
    # at (and argsorts) that inflated build capacity; the hybrid routes
    # it to the broadcast lane and sizes cold passes at the batch budget
    rng.shuffle(bk)
    cat = Catalog()
    cat.register("fact", HostTable.from_pydict({
        "k": list(rng.integers(0, int(n_build * 1.2), n_probe).astype(int)),
        "v": list(rng.integers(0, 100, n_probe).astype(int))}))
    cat.register("dim", HostTable.from_pydict({
        "k": list(bk.astype(int)),
        "w": list(rng.integers(0, 100, n_build).astype(int))}))
    s = Session(cat)
    q = "SELECT count(*) c, sum(v + w) sv FROM fact, dim WHERE fact.k = dim.k"
    old_t = config.get("batch_rows_threshold")
    old_b = config.get("spill_batch_rows")
    config.set("batch_rows_threshold", batch_rows)
    config.set("spill_batch_rows", batch_rows)
    out = {"rows_probe": n_probe, "rows_build": n_build,
           "batch_rows": batch_rows}
    try:
        results = {}
        for strat in ("auto", "grace"):
            config.set("join_hybrid_strategy", strat)
            results[strat] = s.sql(q).rows()  # compile + partition warmup
            best = _best(lambda: s.sql(q), repeats)
            key = "hybrid" if strat == "auto" else "grace"
            out[f"{key}_ms"] = round(best * 1000, 2)
            if strat == "auto":
                prof = s.last_profile
                ctr = {}

                def walk(p):
                    ctr.update(
                        {k: v for k, (v, _) in p.counters.items()})
                    for c in p.children:
                        walk(c)

                walk(prof)
                for k in ("join_skew_keys", "join_spilled_partitions",
                          "join_resident_partitions",
                          "join_skew_probe_rows"):
                    if k in ctr:
                        out[k] = int(ctr[k])
        assert results["auto"] == results["grace"], "hybrid != grace"
        out["hybrid_speedup"] = round(out["grace_ms"] / out["hybrid_ms"], 3)
    finally:
        config.set("batch_rows_threshold", old_t)
        config.set("spill_batch_rows", old_b)
        config.set("join_hybrid_strategy", "auto")
    return out


def bench_oversized_cold_recursion(repeats: int,
                                   batch_rows: int = 8192) -> dict:
    """A/B for recursive salted repartitioning (NEXT 11a): MANY distinct
    keys — every per-key count under the skew threshold, so nothing
    qualifies for the broadcast lane — crafted to hash into ONE cold
    partition. Legacy (`join_recursive_repartition=off`) must run that
    partition as a single pass whose build is 4x the batch budget; the
    recursion re-salts it into sub-passes, each within budget."""
    import numpy as np

    from starrocks_tpu.column import HostTable
    from starrocks_tpu.native import hash_partition_i64
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.storage.catalog import Catalog

    n_build = batch_rows * 4
    n_parts = 4  # == ceil(n_build / batch_rows) once every key is cold
    thresh = max(batch_rows // max(config.get("join_skew_factor"), 1), 1)
    per_key = max(thresh // 2, 1)
    need = -(-n_build // per_key)
    keys: list = []
    k = 0
    while len(keys) < need:  # mine keys that land in partition 0
        cand = np.arange(k, k + 100_000, dtype=np.int64)
        keys.extend(int(x) for x in cand[
            hash_partition_i64(cand, n_parts) == 0])
        k += 100_000
    keys = np.asarray(keys[:need], dtype=np.int64)
    rng = np.random.default_rng(23)
    bk = np.repeat(keys, per_key)[:n_build].copy()
    rng.shuffle(bk)
    pk = rng.choice(keys, n_build * 2)  # probe 2x build so dim stays the
    # build side under the DP join order

    cat = Catalog()
    cat.register("fact", HostTable.from_pydict({
        "k": list(int(x) for x in pk),
        "v": list(int(x) for x in rng.integers(0, 100, pk.size))}))
    cat.register("dim", HostTable.from_pydict({
        "k": list(int(x) for x in bk),
        "w": list(int(x) for x in rng.integers(0, 100, n_build))}))
    s = Session(cat)
    q = "SELECT count(*) c, sum(v + w) sv FROM fact, dim WHERE fact.k = dim.k"
    old_t = config.get("batch_rows_threshold")
    old_b = config.get("spill_batch_rows")
    config.set("batch_rows_threshold", batch_rows)
    config.set("spill_batch_rows", batch_rows)
    out = {"rows_probe": int(pk.size), "rows_build": n_build,
           "batch_rows": batch_rows, "distinct_keys": int(keys.size)}
    try:
        results = {}
        for mode in (True, False):
            config.set("join_recursive_repartition", mode)
            results[mode] = s.sql(q).rows()
            ctr = {}

            def walk(p):
                ctr.update({k: v for k, (v, _) in p.counters.items()})
                for c in p.children:
                    walk(c)

            walk(s.last_profile)
            key = "recursive" if mode else "legacy"
            for c in ("join_max_pass_build", "join_subpartitions",
                      "join_oversized_passes", "join_spilled_partitions"):
                if c in ctr:
                    out[f"{key}_{c[5:]}"] = int(ctr[c])
            best = _best(lambda: s.sql(q), repeats)
            out[f"{key}_ms"] = round(best * 1000, 2)
        assert results[True] == results[False], "recursive != legacy rows"
        assert out["legacy_max_pass_build"] > batch_rows, (
            "scenario failed to build an oversized cold partition")
        assert out["recursive_max_pass_build"] <= batch_rows, (
            "recursion left a pass above the batch budget")
        out["recursion_speedup"] = round(
            out["legacy_ms"] / out["recursive_ms"], 3)
    finally:
        config.set("batch_rows_threshold", old_t)
        config.set("spill_batch_rows", old_b)
        config.set("join_recursive_repartition", True)
    return out


def run_join_bench(rows: int = 1 << 20, build: int = 1 << 16,
                   repeats: int = 3, skew_batch: int = 65_536) -> dict:
    return {
        "probe_strategies": bench_probe_strategies(rows, build, repeats),
        "skewed_hybrid_vs_grace": bench_skewed_hybrid_vs_grace(
            rows, max(build * 2, 1 << 17), repeats, skew_batch),
        "oversized_cold_recursion": bench_oversized_cold_recursion(repeats),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--rows", type=int, default=1 << 20,
                    help="probe rows (default 1M)")
    ap.add_argument("--build", type=int, default=1 << 16,
                    help="build rows for the kernel A/B (default 64k)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--skew-batch", type=int, default=65_536,
                    help="spill batch rows for the hybrid/grace A/B")
    ap.add_argument("--no-detail", action="store_true",
                    help="do not merge into BENCH_DETAIL.json")
    args = ap.parse_args()

    res = run_join_bench(args.rows, args.build, args.repeats,
                         args.skew_batch)
    print(json.dumps(res, indent=1))
    if not args.no_detail:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "BENCH_DETAIL.json")
        detail = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    detail = json.load(f)
            except Exception as e:  # noqa: BLE001 — a corrupt detail file must not kill the bench
                print(f"# BENCH_DETAIL.json unreadable ({e}); rewriting",
                      file=sys.stderr)
        detail["join_bench"] = res
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
        print(f"# merged into {os.path.normpath(path)}", file=sys.stderr)


if __name__ == "__main__":
    main()
