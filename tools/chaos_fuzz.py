#!/usr/bin/env python3
"""Randomized fault-schedule fuzzer over the failpoint registry (the
NEXT 7d "randomized schedules" first cut).

The curated chaos suite (tests/test_chaos.py) injects ONE fault per
scenario at hand-picked sites; this tool fuzzes the schedule instead:
every round arms a seeded-random subset of the statically-enumerated
`fail_point(...)` sites (random `times` budgets, so faults land mid-
workload, not just on the first hit) and drives a short mixed workload
— DDL, DML, analytic reads, point lookups, KILL-adjacent shapes —
accepting that statements may fail, while asserting the lifecycle
contract that NOTHING may leak:

  1. memory accountant process_bytes back to the baseline;
  2. zero admission slots held, empty running-query registry;
  3. the lock witness still acyclic (no ordering cycle latched);
  4. exactly ONE audit record per driven statement (every exit path
     unwinds through lifecycle._finalize_observability);
  5. a clean probe query returns oracle-correct rows after each round.

Determinism: `--seed` fixes the whole schedule (run_tier1.sh pins one);
every failure prints the seed so any red run replays bit-identically.

Usage: chaos_fuzz.py [--seed N] [--rounds N] [--sites-per-round N]
"""

from __future__ import annotations

import argparse
import ast as pyast
import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "starrocks_tpu")
sys.path.insert(0, REPO)

# sites whose faults are out-of-band for a single-process fuzz loop:
# cluster heartbeats need a monitor/worker pair, and the serving-pool
# sites need the ExecutorPool front door (this tool drives Session.sql)
_SKIP_PREFIXES = ("heartbeat::", "serve::")


def _scan_failpoints():
    """(site names, rel paths of modules containing at least one site):
    every literal fail_point("<name>") call site in the package,
    statically (same AST approach as src_lint.count_failpoints — the
    registry keeps no site list by design)."""
    names, mods = set(), set()
    for dirpath, _dirs, files in os.walk(PKG):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                try:
                    tree = pyast.parse(f.read())
                except SyntaxError:
                    continue
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            for node in pyast.walk(tree):
                if (isinstance(node, pyast.Call)
                        and isinstance(node.func, pyast.Name)
                        and node.func.id == "fail_point"
                        and node.args
                        and isinstance(node.args[0], pyast.Constant)
                        and isinstance(node.args[0].value, str)):
                    names.add(node.args[0].value)
                    mods.add(rel)
    return names, mods


def enumerate_sites() -> list:
    names, _mods = _scan_failpoints()
    return sorted(s for s in names
                  if not s.startswith(_SKIP_PREFIXES))


def coverage_cross_check() -> int:
    """Warn-only ratchet against analysis/effects_check.py: every acquire
    site the effect analyzer discovers statically should sit in a module
    with at least one failpoint — an acquire in a failpoint-free module
    has NO fuzz-injectable unwind path, so this tool can never probe
    whether a fault there leaks it (only the static contract covers it).
    Prints each uncovered (acquire site, kind) pair; returns the count.
    The pinned-seed run stays green regardless."""
    import importlib.util

    def load(name, rel):
        mod = sys.modules.get(name)
        if mod is None:
            spec = importlib.util.spec_from_file_location(
                name, os.path.join(REPO, rel))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
        return mod

    astwalk = load("sr_astwalk", "starrocks_tpu/analysis/astwalk.py")
    effects_check = load("sr_effects_check",
                         "starrocks_tpu/analysis/effects_check.py")
    acquires = effects_check.acquire_sites(astwalk.package_sources(REPO))
    _names, fp_mods = _scan_failpoints()
    uncovered = [s for s in acquires if s.rel not in fp_mods]
    for s in uncovered:
        print(f"chaos_fuzz: uncovered acquire {s.rel}:{s.line} "
              f"({s.kind} in {s.func}) — module has no failpoint, so no "
              f"fuzzable unwind path reaches this acquire")
    print(f"chaos_fuzz: acquire coverage {len(acquires) - len(uncovered)}"
          f"/{len(acquires)} sites in failpoint-covered modules")
    return len(uncovered)


def _mixed_workload(rng: random.Random, round_no: int) -> list:
    """A short statement mix over the fixture tables; literals vary by
    round so plan/result caches see both hits and misses."""
    k = rng.randint(1, 3)
    stmts = [
        f"insert into fz values ({round_no * 100 + 1}, {k}),"
        f" ({round_no * 100 + 2}, {k + 1})",
        "select b, sum(a) from fz group by b order by b",
        f"select a, b from fz where a > {rng.randint(0, 50)} order by a",
        "select f.b, count(*) from fz f join fzd d on f.b = d.k "
        "group by f.b order by f.b",
        f"select v from fzd where k = {rng.randint(0, 4)}",  # point lane
        f"update fz set b = b + 1 where a = {round_no * 100 + 1}",
        f"delete from fz where a = {round_no * 100 + 2}",
        "show processlist",
    ]
    rng.shuffle(stmts)
    return stmts[: rng.randint(4, len(stmts))]


def run(seed: int, rounds: int, sites_per_round: int) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("SR_TPU_LOCK_WITNESS", "1")
    from starrocks_tpu import lockdep
    from starrocks_tpu.runtime import failpoint
    from starrocks_tpu.runtime.audit import AUDIT
    from starrocks_tpu.runtime.failpoint import FailPointError
    from starrocks_tpu.runtime.lifecycle import (
        ACCOUNTANT, REGISTRY, QueryAbortError,
    )
    from starrocks_tpu.runtime.session import Session

    sites = enumerate_sites()
    if not sites:
        print("chaos_fuzz: no failpoint sites found", file=sys.stderr)
        return 2
    coverage_cross_check()  # warn-only: uncovered acquires print above
    rng = random.Random(seed)
    print(f"chaos_fuzz: seed={seed} rounds={rounds} "
          f"sites={len(sites)} (<= {sites_per_round}/round)")

    s = Session()
    s.sql("create table fz (a int, b int)")
    s.sql("create table fzd (k int, v int, primary key (k))")
    s.sql("insert into fzd values (0, 10), (1, 11), (2, 12), "
          "(3, 13), (4, 14)")
    s.sql("create table fzi (k int, v int, primary key (k))")
    # the ingest lane: fuzzed stream loads land here, so faults at the
    # ingest:: sites (stage/commit/label_journal) get a real unwind path
    plane = s.ingest_plane()
    from starrocks_tpu.runtime.config import config as _cfg

    _cfg.set("ingest_batch_age_ms", 5)  # commit promptly per round

    def leak_snapshot():
        wm = getattr(s.catalog, "workgroups", None)
        return {
            "process_bytes": ACCOUNTANT.snapshot()["process_bytes"],
            "slots": sum(wm.running.values()) if wm is not None else 0,
            "registry": len(REGISTRY.snapshot()),
            "ingest_staged": plane.stats()["staged_bytes"],
        }

    def fail(msg: str):
        print(f"chaos_fuzz: FAIL (replay with --seed {seed}): {msg}",
              file=sys.stderr)
        return 1

    baseline = leak_snapshot()
    driven = faults = 0
    for r in range(rounds):
        armed = rng.sample(sites, k=min(sites_per_round, len(sites)))
        schedule = [(site, rng.randint(1, 2)) for site in armed]
        for site, times in schedule:
            failpoint.arm(site, times=times)
        stmts = _mixed_workload(rng, r)
        try:
            for stmt in stmts:
                driven += 1
                try:
                    s.sql(stmt)
                except (FailPointError, QueryAbortError):
                    faults += 1
                except Exception as e:  # noqa: BLE001 — a fault mid-DDL
                    # may surface as a wrapped engine error; what matters
                    # is the leak/witness/audit contract below
                    faults += 1
                    del e
            # stream-load lane under the SAME armed schedule: each load
            # audits exactly once (its own query_scope) whether it
            # commits, replays, or faults at an ingest:: site
            driven += 1
            try:
                plane.load(
                    s, "fzi",
                    [{"k": r * 10 + i, "v": rng.randint(0, 99)}
                     for i in range(rng.randint(1, 3))],
                    label=f"fuzz:{r}")
            except Exception as e:  # noqa: BLE001 — same contract as SQL
                faults += 1
                del e
        finally:
            for site, _times in schedule:
                failpoint.disarm(site)
        leaks = leak_snapshot()
        if leaks != baseline:
            return fail(f"round {r} schedule={schedule}: leaked state "
                        f"{leaks} != baseline {baseline}")
        cycles = lockdep.WITNESS.order_cycles()
        if cycles:
            return fail(f"round {r} schedule={schedule}: lock witness "
                        f"cycle {lockdep.WITNESS.render(cycles)}")
        try:
            got = s.sql("select count(*) from fzd").rows()
        except Exception as e:  # noqa: BLE001
            return fail(f"round {r}: clean probe failed after disarm: "
                        f"{type(e).__name__}: {e}")
        if got != [(5,)]:
            return fail(f"round {r}: probe returned {got}, expected "
                        "[(5,)] — fault corrupted committed data")
        driven += 1  # the probe statement audits too
        # clean ingest probe: with faults disarmed a fresh-label load
        # must commit and be immediately visible (freshness contract)
        driven += 1
        try:
            plane.load(s, "fzi", [{"k": 100000 + r, "v": r}],
                       label=f"probe:{r}")
        except Exception as e:  # noqa: BLE001
            return fail(f"round {r}: clean ingest probe failed after "
                        f"disarm: {type(e).__name__}: {e}")
        got = s.sql(
            f"select count(*) from fzi where k = {100000 + r}").rows()
        driven += 1
        if got != [(1,)]:
            return fail(f"round {r}: ingest probe row missing ({got}) — "
                        "committed load not visible")
    AUDIT.flush()
    registered = AUDIT.stats()["registered"]
    expected = driven + 4  # + the four fixture statements
    if registered != expected:
        return fail(f"audit records {registered} != statements driven "
                    f"{expected} (every exit path must audit once)")
    print(f"chaos_fuzz: OK — {rounds} rounds, {driven} statements, "
          f"{faults} injected faults, audit={registered}, zero leaks, "
          "witness acyclic")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int,
                    default=int.from_bytes(os.urandom(4), "big"))
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--sites-per-round", type=int, default=3)
    a = ap.parse_args()
    return run(a.seed, a.rounds, a.sites_per_round)


if __name__ == "__main__":
    sys.exit(main())
