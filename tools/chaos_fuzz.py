#!/usr/bin/env python3
"""Randomized fault-schedule fuzzer over the failpoint registry (the
NEXT 7d "randomized schedules" first cut).

The curated chaos suite (tests/test_chaos.py) injects ONE fault per
scenario at hand-picked sites; this tool fuzzes the schedule instead:
every round arms a seeded-random subset of the statically-enumerated
`fail_point(...)` sites (random `times` budgets, so faults land mid-
workload, not just on the first hit) and drives a short mixed workload
— DDL, DML, analytic reads, point lookups, KILL-adjacent shapes —
accepting that statements may fail, while asserting the lifecycle
contract that NOTHING may leak:

  1. memory accountant process_bytes back to the baseline;
  2. zero admission slots held, empty running-query registry;
  3. the lock witness still acyclic (no ordering cycle latched);
  4. exactly ONE audit record per driven statement (every exit path
     unwinds through lifecycle._finalize_observability);
  5. a clean probe query returns oracle-correct rows after each round.

Determinism: `--seed` fixes the whole schedule (run_tier1.sh pins one);
every failure prints the seed so any red run replays bit-identically.

Usage: chaos_fuzz.py [--seed N] [--rounds N] [--sites-per-round N]
"""

from __future__ import annotations

import argparse
import ast as pyast
import os
import random
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "starrocks_tpu")
sys.path.insert(0, REPO)

# sites whose faults are out-of-band for a single-process fuzz loop:
# cluster heartbeats need a monitor/worker pair, the serving-pool sites
# need the ExecutorPool front door (this tool drives Session.sql), and
# the cluster:: exchange-plane sites need coordinator+worker processes
# (the --cluster mode drives those with real kills/partitions instead)
_SKIP_PREFIXES = ("heartbeat::", "serve::", "cluster::")

# Modules whose acquire sites CANNOT get a fuzz-injectable failpoint, with
# the reason the static contract alone must carry them. Every other module
# that acquires (per analysis/effects_check.acquire_sites) MUST contain at
# least one fail_point(...) — enforced as a hard gate by
# coverage_cross_check (run_tier1.sh runs `--coverage-check`).
COVERAGE_EXEMPT = {
    "starrocks_tpu/analysis/astwalk.py":
        "static-analysis loader: runs inside the lint CLIs at import "
        "time, never on a workload path the fuzzer can drive",
    "starrocks_tpu/analysis/boundary_check.py":
        "manifest loader for the boundary linter: same import-time "
        "tooling surface as astwalk",
    "starrocks_tpu/runtime/config.py":
        "knob bootstrap: load_file runs before any fuzz schedule can "
        "arm, and a fault there breaks the harness, not the engine",
    "starrocks_tpu/runtime/failpoint.py":
        "the injection plane itself: arm/scoped ARE the flagged "
        "acquires; the registry cannot inject faults into its own "
        "bookkeeping without deadlocking the schedule",
}


def _scan_failpoints():
    """(site names, rel paths of modules containing at least one site):
    every literal fail_point("<name>") call site in the package,
    statically (same AST approach as src_lint.count_failpoints — the
    registry keeps no site list by design)."""
    names, mods = set(), set()
    for dirpath, _dirs, files in os.walk(PKG):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                try:
                    tree = pyast.parse(f.read())
                except SyntaxError:
                    continue
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            for node in pyast.walk(tree):
                if (isinstance(node, pyast.Call)
                        and isinstance(node.func, pyast.Name)
                        and node.func.id == "fail_point"
                        and node.args
                        and isinstance(node.args[0], pyast.Constant)
                        and isinstance(node.args[0].value, str)):
                    names.add(node.args[0].value)
                    mods.add(rel)
    return names, mods


def enumerate_sites() -> list:
    names, _mods = _scan_failpoints()
    return sorted(s for s in names
                  if not s.startswith(_SKIP_PREFIXES))


def coverage_cross_check() -> int:
    """HARD gate against analysis/effects_check.py: every acquire site
    the effect analyzer discovers statically must sit in a module with at
    least one failpoint — an acquire in a failpoint-free module has NO
    fuzz-injectable unwind path, so this tool can never probe whether a
    fault there leaks it. Modules in COVERAGE_EXEMPT carry a written
    reason instead. Prints each NON-EXEMPT uncovered (acquire site, kind)
    pair; returns their count (0 = gate green). Both the pinned-seed run
    and run_tier1.sh's `--coverage-check` stage fail on a non-zero
    return — growing a new acquiring module ratchets the gate."""
    import importlib.util

    def load(name, rel):
        mod = sys.modules.get(name)
        if mod is None:
            spec = importlib.util.spec_from_file_location(
                name, os.path.join(REPO, rel))
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
        return mod

    astwalk = load("sr_astwalk", "starrocks_tpu/analysis/astwalk.py")
    effects_check = load("sr_effects_check",
                         "starrocks_tpu/analysis/effects_check.py")
    acquires = effects_check.acquire_sites(astwalk.package_sources(REPO))
    _names, fp_mods = _scan_failpoints()
    uncovered = [s for s in acquires
                 if s.rel not in fp_mods and s.rel not in COVERAGE_EXEMPT]
    exempt = [s for s in acquires
              if s.rel not in fp_mods and s.rel in COVERAGE_EXEMPT]
    for s in uncovered:
        print(f"chaos_fuzz: UNCOVERED acquire {s.rel}:{s.line} "
              f"({s.kind} in {s.func}) — module has no failpoint, so no "
              f"fuzzable unwind path reaches this acquire: add a "
              f"fail_point(...) or a COVERAGE_EXEMPT entry with a reason")
    print(f"chaos_fuzz: acquire coverage "
          f"{len(acquires) - len(uncovered) - len(exempt)}/{len(acquires)}"
          f" sites in failpoint-covered modules "
          f"({len(exempt)} exempt with reasons, {len(uncovered)} uncovered)")
    return len(uncovered)


def _mixed_workload(rng: random.Random, round_no: int) -> list:
    """A short statement mix over the fixture tables; literals vary by
    round so plan/result caches see both hits and misses."""
    k = rng.randint(1, 3)
    stmts = [
        f"insert into fz values ({round_no * 100 + 1}, {k}),"
        f" ({round_no * 100 + 2}, {k + 1})",
        "select b, sum(a) from fz group by b order by b",
        f"select a, b from fz where a > {rng.randint(0, 50)} order by a",
        "select f.b, count(*) from fz f join fzd d on f.b = d.k "
        "group by f.b order by f.b",
        f"select v from fzd where k = {rng.randint(0, 4)}",  # point lane
        f"update fz set b = b + 1 where a = {round_no * 100 + 1}",
        f"delete from fz where a = {round_no * 100 + 2}",
        "show processlist",
    ]
    rng.shuffle(stmts)
    return stmts[: rng.randint(4, len(stmts))]


def run(seed: int, rounds: int, sites_per_round: int) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("SR_TPU_LOCK_WITNESS", "1")
    from starrocks_tpu import lockdep
    from starrocks_tpu.runtime import failpoint
    from starrocks_tpu.runtime.audit import AUDIT
    from starrocks_tpu.runtime.failpoint import FailPointError
    from starrocks_tpu.runtime.lifecycle import (
        ACCOUNTANT, REGISTRY, QueryAbortError,
    )
    from starrocks_tpu.runtime.session import Session

    sites = enumerate_sites()
    if not sites:
        print("chaos_fuzz: no failpoint sites found", file=sys.stderr)
        return 2
    if coverage_cross_check():  # hard gate: see COVERAGE_EXEMPT
        print("chaos_fuzz: FAIL — acquire sites without a fuzzable "
              "failpoint (see above)", file=sys.stderr)
        return 1
    rng = random.Random(seed)
    print(f"chaos_fuzz: seed={seed} rounds={rounds} "
          f"sites={len(sites)} (<= {sites_per_round}/round)")

    s = Session()
    s.sql("create table fz (a int, b int)")
    s.sql("create table fzd (k int, v int, primary key (k))")
    s.sql("insert into fzd values (0, 10), (1, 11), (2, 12), "
          "(3, 13), (4, 14)")
    s.sql("create table fzi (k int, v int, primary key (k))")
    # the ingest lane: fuzzed stream loads land here, so faults at the
    # ingest:: sites (stage/commit/label_journal) get a real unwind path
    plane = s.ingest_plane()
    from starrocks_tpu.runtime.config import config as _cfg

    _cfg.set("ingest_batch_age_ms", 5)  # commit promptly per round

    def leak_snapshot():
        wm = getattr(s.catalog, "workgroups", None)
        return {
            "process_bytes": ACCOUNTANT.snapshot()["process_bytes"],
            "slots": sum(wm.running.values()) if wm is not None else 0,
            "registry": len(REGISTRY.snapshot()),
            "ingest_staged": plane.stats()["staged_bytes"],
        }

    def fail(msg: str):
        print(f"chaos_fuzz: FAIL (replay with --seed {seed}): {msg}",
              file=sys.stderr)
        return 1

    baseline = leak_snapshot()
    driven = faults = 0
    for r in range(rounds):
        armed = rng.sample(sites, k=min(sites_per_round, len(sites)))
        schedule = [(site, rng.randint(1, 2)) for site in armed]
        for site, times in schedule:
            failpoint.arm(site, times=times)
        stmts = _mixed_workload(rng, r)
        try:
            for stmt in stmts:
                driven += 1
                try:
                    s.sql(stmt)
                except (FailPointError, QueryAbortError):
                    faults += 1
                except Exception as e:  # noqa: BLE001 — a fault mid-DDL
                    # may surface as a wrapped engine error; what matters
                    # is the leak/witness/audit contract below
                    faults += 1
                    del e
            # stream-load lane under the SAME armed schedule: each load
            # audits exactly once (its own query_scope) whether it
            # commits, replays, or faults at an ingest:: site
            driven += 1
            try:
                plane.load(
                    s, "fzi",
                    [{"k": r * 10 + i, "v": rng.randint(0, 99)}
                     for i in range(rng.randint(1, 3))],
                    label=f"fuzz:{r}")
            except Exception as e:  # noqa: BLE001 — same contract as SQL
                faults += 1
                del e
        finally:
            for site, _times in schedule:
                failpoint.disarm(site)
        leaks = leak_snapshot()
        if leaks != baseline:
            return fail(f"round {r} schedule={schedule}: leaked state "
                        f"{leaks} != baseline {baseline}")
        cycles = lockdep.WITNESS.order_cycles()
        if cycles:
            return fail(f"round {r} schedule={schedule}: lock witness "
                        f"cycle {lockdep.WITNESS.render(cycles)}")
        try:
            got = s.sql("select count(*) from fzd").rows()
        except Exception as e:  # noqa: BLE001
            return fail(f"round {r}: clean probe failed after disarm: "
                        f"{type(e).__name__}: {e}")
        if got != [(5,)]:
            return fail(f"round {r}: probe returned {got}, expected "
                        "[(5,)] — fault corrupted committed data")
        driven += 1  # the probe statement audits too
        # clean ingest probe: with faults disarmed a fresh-label load
        # must commit and be immediately visible (freshness contract)
        driven += 1
        try:
            plane.load(s, "fzi", [{"k": 100000 + r, "v": r}],
                       label=f"probe:{r}")
        except Exception as e:  # noqa: BLE001
            return fail(f"round {r}: clean ingest probe failed after "
                        f"disarm: {type(e).__name__}: {e}")
        got = s.sql(
            f"select count(*) from fzi where k = {100000 + r}").rows()
        driven += 1
        if got != [(1,)]:
            return fail(f"round {r}: ingest probe row missing ({got}) — "
                        "committed load not visible")
    AUDIT.flush()
    registered = AUDIT.stats()["registered"]
    expected = driven + 4  # + the four fixture statements
    if registered != expected:
        return fail(f"audit records {registered} != statements driven "
                    f"{expected} (every exit path must audit once)")
    print(f"chaos_fuzz: OK — {rounds} rounds, {driven} statements, "
          f"{faults} injected faults, audit={registered}, zero leaks, "
          "witness acyclic")
    return 0


def run_cluster(seed: int, rounds: int) -> int:
    """Cluster fault families: a REAL coordinator + 2 worker processes
    (runtime/cluster_exec.py) driven through SQL while a seeded schedule
    injects process kills (SIGKILL mid-fragment), network partitions
    (blackholed worker) and slow-worker delays. Per round the contract is
    the tentpole's: the query never wedges, answers oracle-correct within
    `cluster_fragment_retries`, and the observability plane OBSERVES every
    injected failure — `heartbeat_loss` lands and its alert fires+resolves
    for kills, `query_stuck` lands for partitions (stage-wedge watchdog),
    exactly one audit record per driven statement, zero leaked slots/
    bytes/registry entries, lock witness acyclic."""
    import threading
    import time

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("SR_TPU_LOCK_WITNESS", "1")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # the coordinator session is distributed (dist_shards=2): widen
        # this process's host platform BEFORE any jax backend initializes
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import starrocks_tpu.sql.distributed as D

    D.SHARD_THRESHOLD_ROWS = 100
    D.SHUFFLE_AGG_MIN_GROUPS = 10
    from starrocks_tpu import lockdep
    from starrocks_tpu.runtime.alerts import ALERTS
    from starrocks_tpu.runtime.audit import AUDIT
    from starrocks_tpu.runtime.cluster import WORKERS_DEAD
    from starrocks_tpu.runtime.cluster_exec import ClusterRuntime
    from starrocks_tpu.runtime.config import config
    from starrocks_tpu.runtime.events import EVENTS
    from starrocks_tpu.runtime.lifecycle import ACCOUNTANT, REGISTRY
    from starrocks_tpu.runtime.session import Session
    from starrocks_tpu.runtime.watchdog import WATCHDOG

    def fail(msg: str):
        print(f"chaos_fuzz: CLUSTER FAIL (replay with --cluster "
              f"--seed {seed}): {msg}", file=sys.stderr)
        return 1

    def ev(name: str) -> int:
        return EVENTS.stats().get(name, 0)

    rng = random.Random(seed)
    s = Session(dist_shards=2)
    s.sql("create table t (a int, b int)")
    s.sql("insert into t values "
          + ", ".join(f"({i % 97}, {i % 7})" for i in range(400)))
    s.sql("create table d (k int, v int)")
    s.sql("insert into d values "
          + ", ".join(f"({i}, {i * 10})" for i in range(97)))
    config.set("dist_fragments", True)
    base_sql = ("select d.v, sum(t.b) s from t join d on t.a = d.k "
                "group by d.v order by s desc, d.v limit 5")
    oracle = s.sql(base_sql).rows()
    cr = ClusterRuntime(n_workers=2, shards=2, hb_interval_s=0.1,
                        hb_miss_limit=3).start(s)
    cr.attach(s)
    print(f"chaos_fuzz: cluster seed={seed} rounds={rounds} workers=2")
    try:
        # warm both workers so chaos lands on cached fragment programs
        if s.sql(base_sql + " ").rows() != oracle:
            return fail("warm-up cluster query diverged from oracle")
        baseline = {
            "process_bytes": ACCOUNTANT.snapshot()["process_bytes"],
            "registry": len(REGISTRY.snapshot()),
        }
        AUDIT.flush()
        audit0 = AUDIT.stats()["registered"]
        driven = 0
        injected = 0
        # every family lands at least once per run (a seed that never
        # draws "kill" would skip the headline contract); order and any
        # extra rounds stay seed-random
        families = ["kill", "blackhole", "delay"][:rounds]
        families += [rng.choice(("kill", "blackhole", "delay"))
                     for _ in range(rounds - len(families))]
        rng.shuffle(families)
        for r in range(rounds):
            family = families[r]
            victim = rng.choice(("w0", "w1"))
            pad = " " * (r + 2)  # fresh query text: dodge the result cache
            if family == "kill":
                injected += 1
                loss0, rec0 = ev("heartbeat_loss"), ev("heartbeat_reconnect")
                cr.inject_fault(victim, "delay",
                                seconds=1.0 + rng.random(), times=1)
                res: dict = {}

                def _q(res=res, pad=pad):
                    try:
                        res["rows"] = s.sql(base_sql + pad).rows()
                    except Exception as e:  # noqa: BLE001 — asserted below
                        res["err"] = e

                th = threading.Thread(target=_q)
                th.start()
                time.sleep(0.4)  # let the query reach the slowed fragment
                cr.kill_worker(victim)
                th.join(timeout=90)
                driven += 1
                if th.is_alive():
                    return fail(f"round {r}: query WEDGED after SIGKILL "
                                f"of {victim}")
                if res.get("rows") != oracle:
                    return fail(f"round {r}: post-kill answer {res} != "
                                f"oracle")
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline \
                        and ev("heartbeat_loss") <= loss0:
                    time.sleep(0.05)
                if ev("heartbeat_loss") <= loss0:
                    return fail(f"round {r}: kill of {victim} never "
                                "journaled heartbeat_loss")
                af0 = ev("alert_fire")
                ALERTS.evaluate(
                    {"gauges": {"sr_tpu_cluster_workers_dead":
                                float(WORKERS_DEAD.value)}})
                if ev("alert_fire") != af0 + 1:
                    return fail(f"round {r}: heartbeat_loss alert did "
                                "not fire on a dead worker")
                cr.respawn_worker(victim)
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline \
                        and (WORKERS_DEAD.value > 0
                             or ev("heartbeat_reconnect") <= rec0):
                    time.sleep(0.05)
                if WORKERS_DEAD.value != 0:
                    return fail(f"round {r}: respawned {victim} never "
                                "cleared the dead-workers gauge")
                if ev("heartbeat_reconnect") != rec0 + 1:
                    return fail(f"round {r}: reconnect journaled "
                                f"{ev('heartbeat_reconnect') - rec0} "
                                "times, want exactly 1")
                ar0 = ev("alert_resolve")
                ALERTS.evaluate(
                    {"gauges": {"sr_tpu_cluster_workers_dead": 0.0}})
                if ev("alert_resolve") != ar0 + 1:
                    return fail(f"round {r}: heartbeat_loss alert did "
                                "not resolve after respawn")
            elif family == "blackhole":
                injected += 1
                qs0 = ev("query_stuck")
                retries0 = cr.stats()["retries_total"]
                config.set("cluster_exec_timeout_s", 2.0)
                hole_s = 5.0
                cr.inject_fault(victim, "blackhole", seconds=hole_s,
                                times=1)
                t_hole = time.monotonic()
                res = {}

                def _q(res=res, pad=pad):
                    try:
                        res["rows"] = s.sql(base_sql + pad).rows()
                    except Exception as e:  # noqa: BLE001 — asserted below
                        res["err"] = e

                th = threading.Thread(target=_q)
                th.start()
                time.sleep(0.8)  # the partitioned fragment is wedged now
                # fake-clock watchdog pass: seed the stage, then jump past
                # watchdog_stage_budget_s — the wedged cluster wait must
                # surface as query_stuck
                WATCHDOG.clear()
                t0 = time.monotonic()
                WATCHDOG.scan(t0)
                budget = float(config.get("watchdog_stage_budget_s"))
                WATCHDOG.scan(t0 + budget + 1.0)
                th.join(timeout=90)
                driven += 1
                config.set("cluster_exec_timeout_s", 30.0)
                if th.is_alive():
                    return fail(f"round {r}: query WEDGED across a "
                                f"partition of {victim}")
                if res.get("rows") != oracle:
                    return fail(f"round {r}: post-partition answer "
                                f"{res} != oracle")
                if cr.stats()["retries_total"] <= retries0:
                    return fail(f"round {r}: partition of {victim} "
                                "produced no fragment re-placement")
                if ev("query_stuck") <= qs0:
                    return fail(f"round {r}: watchdog never flagged the "
                                "partitioned query as query_stuck")
                # drain the victim's blackhole window before the next round
                time.sleep(max(0.0, hole_s - (time.monotonic() - t_hole)))
            else:  # delay: latency-only fault, no retry expected
                cr.inject_fault(victim, "delay",
                                seconds=0.3 + rng.random() * 0.4, times=1)
                driven += 1
                if s.sql(base_sql + pad).rows() != oracle:
                    return fail(f"round {r}: slow-worker round diverged "
                                "from oracle")
            # invariants after EVERY round
            driven += 1
            if s.sql(base_sql + pad + " ").rows() != oracle:
                return fail(f"round {r} ({family} on {victim}): clean "
                            "probe diverged — fault corrupted state")
            leaks = {
                "process_bytes": ACCOUNTANT.snapshot()["process_bytes"],
                "registry": len(REGISTRY.snapshot()),
            }
            if leaks != baseline:
                return fail(f"round {r} ({family} on {victim}): leaked "
                            f"state {leaks} != baseline {baseline}")
            cycles = lockdep.WITNESS.order_cycles()
            if cycles:
                return fail(f"round {r}: lock witness cycle "
                            f"{lockdep.WITNESS.render(cycles)}")
            print(f"chaos_fuzz: cluster round {r} ({family} on {victim}) "
                  f"OK — retries_total={cr.stats()['retries_total']}")
        AUDIT.flush()
        registered = AUDIT.stats()["registered"] - audit0
        if registered != driven:
            return fail(f"audit records {registered} != statements "
                        f"driven {driven} (every exit path must audit "
                        "exactly once)")
        print(f"chaos_fuzz: cluster OK — {rounds} rounds, {injected} "
              f"injected process/partition faults, {driven} statements, "
              f"{cr.stats()['retries_total']} fragment re-placements, "
              "audit balanced, zero leaks, witness acyclic")
        return 0
    finally:
        s.catalog.cluster_runtime = None
        cr.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int,
                    default=int.from_bytes(os.urandom(4), "big"))
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--sites-per-round", type=int, default=3)
    ap.add_argument("--coverage-check", action="store_true",
                    help="run only the acquire-coverage gate (non-zero "
                         "exit when a non-exempt module lacks failpoints)")
    ap.add_argument("--cluster", action="store_true",
                    help="drive the multi-process cluster runtime with "
                         "process-kill / partition / delay fault families "
                         "(default 4 rounds unless --rounds is given)")
    a = ap.parse_args()
    if a.coverage_check:
        return 1 if coverage_cross_check() else 0
    if a.cluster:
        rounds = a.rounds if "--rounds" in sys.argv else 4
        return run_cluster(a.seed, rounds)
    return run(a.seed, a.rounds, a.sites_per_round)


if __name__ == "__main__":
    sys.exit(main())
