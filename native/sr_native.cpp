// Native host-runtime kernels for starrocks_tpu.
//
// Reference behavior re-implemented natively (the BE's host-side hot paths):
// - hash partitioning for tablet bucketing (reference: OlapTableSink
//   partition/bucket routing, be/src/data_sink/tablet/olap_table_sink.h:52)
// - CSV -> columnar parsing for the load path (reference: formats/csv/)
// - zonemap min/max computation (reference: storage/rowset/zone_map_index)
//
// Exposed as a C ABI for ctypes; the Python side falls back to numpy when
// the shared library is unavailable.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cmath>
#include <thread>
#include <vector>
#include <algorithm>

extern "C" {

// --- splitmix64 bucketing ----------------------------------------------------

static inline uint64_t mix64(uint64_t z) {
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// out[i] ^= mix64(keys[i] * GOLDEN); callers chain per key column then mod.
void sr_hash_mix_i64(const int64_t* keys, int64_t n, uint64_t* inout) {
  for (int64_t i = 0; i < n; i++) {
    inout[i] ^= mix64((uint64_t)keys[i] * 0x9E3779B97F4A7C15ULL);
  }
}

void sr_hash_bucket(const uint64_t* h, int64_t n, int32_t nbuckets,
                    int32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = (int32_t)(h[i] % (uint64_t)nbuckets);
  }
}

// parallel variant over std::thread
void sr_hash_partition_i64_mt(const int64_t* keys, int64_t n, int32_t nbuckets,
                              int32_t* out, int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      out[i] =
          (int32_t)(mix64((uint64_t)keys[i] * 0x9E3779B97F4A7C15ULL) %
                    (uint64_t)nbuckets);
    }
  };
  if (nthreads == 1 || n < 1 << 16) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t step = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    int64_t lo = t * step, hi = std::min(n, lo + step);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// --- zonemaps ----------------------------------------------------------------

void sr_minmax_i64(const int64_t* a, const uint8_t* valid, int64_t n,
                   int64_t* out_min, int64_t* out_max, int64_t* out_count) {
  int64_t mn = INT64_MAX, mx = INT64_MIN, cnt = 0;
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) continue;
    int64_t v = a[i];
    if (v < mn) mn = v;
    if (v > mx) mx = v;
    cnt++;
  }
  *out_min = mn;
  *out_max = mx;
  *out_count = cnt;
}

void sr_minmax_f64(const double* a, const uint8_t* valid, int64_t n,
                   double* out_min, double* out_max, int64_t* out_count) {
  double mn = INFINITY, mx = -INFINITY;
  int64_t cnt = 0;
  for (int64_t i = 0; i < n; i++) {
    if (valid && !valid[i]) continue;
    double v = a[i];
    if (v < mn) mn = v;
    if (v > mx) mx = v;
    cnt++;
  }
  *out_min = mn;
  *out_max = mx;
  *out_count = cnt;
}

// --- CSV parsing -------------------------------------------------------------
// Single-pass splitter: counts rows, then parses columns into preallocated
// typed buffers. Types: 0 = int64, 1 = float64, 2 = date (YYYY-MM-DD ->
// days since epoch), 3 = string (byte offsets recorded for python-side dict
// encoding). Delimiter configurable; no quoted-field support (the python
// pyarrow path handles quoted CSVs).

int64_t sr_csv_count_rows(const char* buf, int64_t len) {
  int64_t rows = 0;
  for (int64_t i = 0; i < len; i++)
    if (buf[i] == '\n') rows++;
  if (len > 0 && buf[len - 1] != '\n') rows++;
  return rows;
}

static inline int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
  y -= m <= 2;
  int64_t era = (y >= 0 ? y : y - 399) / 400;
  int64_t yoe = y - era * 400;
  int64_t doy = (153 * (m > 2 ? m - 3 : m + 9) + 2) / 5 + d - 1;
  int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + doe - 719468;
}

// returns number of parsed rows; -1 on structural error (bad digit, short
// date, too many fields in a line, or more rows than max_rows buffers hold).
// out_cols: array of ncols pointers (int64_t* / double* per type)
// str_offsets: for string cols, 2 entries per row (start, end) into buf,
//   stored in the column's int64 buffer as interleaved pairs.
// null_mask: ncols pointers (uint8_t*) or null; empty field -> NULL.
int64_t sr_csv_parse(const char* buf, int64_t len, char delim, int32_t ncols,
                     const int32_t* types, void** out_cols,
                     uint8_t** null_masks, int64_t max_rows) {
  int64_t row = 0;
  int64_t i = 0;
  while (i < len) {
    if (row >= max_rows) return -1;
    for (int32_t c = 0; c < ncols; c++) {
      int64_t start = i;
      while (i < len && buf[i] != delim && buf[i] != '\n') i++;
      int64_t end = i;
      bool is_null = (end == start);
      if (null_masks && null_masks[c]) null_masks[c][row] = is_null ? 0 : 1;
      switch (types[c]) {
        case 0: {  // int64
          int64_t v = 0;
          bool neg = false;
          int64_t p = start;
          if (p < end && (buf[p] == '-' || buf[p] == '+')) {
            neg = buf[p] == '-';
            p++;
          }
          for (; p < end; p++) {
            char ch = buf[p];
            if (ch < '0' || ch > '9') return -1;
            v = v * 10 + (ch - '0');
          }
          ((int64_t*)out_cols[c])[row] = neg ? -v : v;
          break;
        }
        case 1: {  // float64
          if (is_null) {
            ((double*)out_cols[c])[row] = 0.0;
          } else {
            char tmp[64];
            int64_t m = end - start;
            if (m > 63) m = 63;
            memcpy(tmp, buf + start, m);
            tmp[m] = 0;
            ((double*)out_cols[c])[row] = strtod(tmp, nullptr);
          }
          break;
        }
        case 2: {  // date YYYY-MM-DD
          if (is_null || end - start < 10) {
            ((int64_t*)out_cols[c])[row] = 0;
            if (!is_null && end - start < 10) return -1;
          } else {
            const char* s = buf + start;
            int64_t y = (s[0] - '0') * 1000 + (s[1] - '0') * 100 +
                        (s[2] - '0') * 10 + (s[3] - '0');
            int64_t mo = (s[5] - '0') * 10 + (s[6] - '0');
            int64_t d = (s[8] - '0') * 10 + (s[9] - '0');
            ((int64_t*)out_cols[c])[row] = days_from_civil(y, mo, d);
          }
          break;
        }
        case 3: {  // string: record (start, end) offsets
          ((int64_t*)out_cols[c])[row * 2] = start;
          ((int64_t*)out_cols[c])[row * 2 + 1] = end;
          break;
        }
        default:
          return -1;
      }
      if (c + 1 < ncols) {
        if (i >= len || buf[i] != delim) return -1;  // too few fields
        i++;
      }
    }
    if (i < len && buf[i] != '\n') return -1;  // too many fields in this line
    if (i < len) i++;
    row++;
  }
  return row;
}

// --- fused filter + sum scan-agg ---------------------------------------------
// One pass over int64 columns: a conjunctive compare predicate (each term is
// column <op> literal) gates rows whose a[i]*b[i] (or a[i] when b is null)
// accumulates into the sum. Reference behavior: the segment iterator's late
// materialization (be/src/storage/rowset/segment_iterator) — predicate
// columns are read once and non-matching rows never touch the value columns.
// Closes the python fallback's per-operator materialization overhead for the
// SSB q1.x scan-agg family. ops: 0 eq, 1 ne, 2 lt, 3 le, 4 gt, 5 ge.

static inline bool fs_pass(int64_t v, int32_t op, int64_t w) {
  switch (op) {
    case 0: return v == w;
    case 1: return v != w;
    case 2: return v < w;
    case 3: return v <= w;
    case 4: return v > w;
    default: return v >= w;
  }
}

void sr_fused_filter_sum_i64_mt(const int64_t** pred_cols,
                                const int32_t* ops, const int64_t* vals,
                                int32_t npreds, const int64_t* a,
                                const int64_t* b, int64_t n,
                                int64_t* out_sum, int64_t* out_count,
                                int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  auto work = [&](int64_t lo, int64_t hi, int64_t* psum, int64_t* pcnt) {
    int64_t s = 0, c = 0;
    for (int64_t i = lo; i < hi; i++) {
      bool pass = true;
      for (int32_t p = 0; p < npreds; p++) {
        if (!fs_pass(pred_cols[p][i], ops[p], vals[p])) {
          pass = false;
          break;
        }
      }
      if (pass) {
        s += b ? a[i] * b[i] : a[i];
        c++;
      }
    }
    *psum = s;
    *pcnt = c;
  };
  if (nthreads == 1 || n < 1 << 16) {
    work(0, n, out_sum, out_count);
    return;
  }
  std::vector<int64_t> sums(nthreads, 0), cnts(nthreads, 0);
  std::vector<std::thread> ts;
  int64_t step = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    int64_t lo = t * step, hi = std::min(n, lo + step);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi, &sums[t], &cnts[t]);
  }
  for (auto& t : ts) t.join();
  int64_t s = 0, c = 0;
  for (int t = 0; t < nthreads; t++) {
    s += sums[t];
    c += cnts[t];
  }
  *out_sum = s;
  *out_count = c;
}

}  // extern "C"
